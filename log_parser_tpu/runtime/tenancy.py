"""Multi-tenant serving: per-tenant engines, state namespaces, quotas.

ROADMAP item 3. Every stateful subsystem — pattern bank, frequency
window, WAL, line cache, quarantine fingerprints, breaker boards,
micro-batcher, shadow verifier, streaming sessions, the reload quiesce
gate — already lives on :class:`~log_parser_tpu.runtime.engine.AnalysisEngine`.
Tenancy therefore does NOT thread a tenant id through every call site;
it resolves the id ONCE at the transport edge to a :class:`TenantContext`
wrapping a dedicated engine, and everything downstream runs exactly the
single-tenant code path. That is the isolation contract: a tenant's
output is bit-identical to a dedicated single-tenant engine run of its
traffic alone, by construction (pinned by tests/test_tenancy.py).

What IS shared across tenants, deliberately:

- the **admission gate** — one process-wide bounded semaphore
  (serve/admission.py). Each tenant engine is pre-attached to the
  default engine's gate, so every transport × every tenant admits
  through the same in-flight/queue bounds; :class:`TenantQuota` refines
  that gate per tenant (in-flight cap, queue share, lines/s bucket).
- the **process** — one XLA runtime, one compile cache, one faults
  registry. Per-tenant banks rebuild warm through patterns/libcache.py.

Resolution: HTTP ``X-Tenant`` header; framed shim ``method@tenant``
envelope suffix; gRPC ``x-tenant`` invocation metadata. A missing id
maps to the default tenant (the engine the server booted with), so
single-tenant deployments behave exactly as before this module existed.

Residency: non-default tenants build lazily from ``root/<id>/`` and are
LRU-resident under ``--tenant-budget-mb``; eviction only takes idle
tenants (no live request lease, no in-flight work, no open stream
sessions — resolve() pins the context until the transport's release
``finally``, so a request between resolution and admission still
counts as busy), snapshots their
journal, and the next resolve rebuilds from the libcache snapshot.

Fault sites (tools/chaos_sweep.py --group tenant): ``tenant_resolve``
(resolution path), ``tenant_evict`` (residency eviction),
``tenant_quota`` (quota enforcement, fired in serve/admission.py).
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time

import numpy as np

from log_parser_tpu import _clock as pclock
from log_parser_tpu.runtime import faults

log = logging.getLogger(__name__)

DEFAULT_TENANT = "default"

# the tenancy chaos vocabulary (tools/chaos_sweep.py --group tenant);
# tools/hygiene.py check 13 pins every key to a docs/OPS.md row AND to a
# live faults.fire site, so the table can neither rot nor go undocumented
FAULT_SITES = {
    "tenant_resolve": "tenant id resolution (TenantRegistry.resolve)",
    "tenant_evict": "LRU residency eviction (TenantRegistry)",
    "tenant_quota": "per-tenant quota enforcement (serve/admission.py)",
}

# path-component safety: tenant ids name WAL directories and library
# sub-directories, so they must never traverse ("..", "/", empty)
_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class TenantError(Exception):
    """Tenant resolution refused: unknown tenant (404) or malformed id
    (400). Transports map status onto their wire the same way they map
    AdmissionRejected."""

    def __init__(self, reason: str, status: int = 404):
        super().__init__(reason)
        self.reason = reason
        self.status = status


def edge_tenant_id(tenant_id: str | None) -> str | None:
    """The wire-id normalization + validation :meth:`TenantRegistry
    .resolve` applies, as a standalone function so the fleet router can
    refuse a malformed id AT THE EDGE (fleet/router.py) with the exact
    semantics the backend would: None/empty/``default`` → None (the
    default tenant), a well-formed id is returned unchanged, anything
    else raises the same 400 :class:`TenantError`."""
    if not tenant_id or tenant_id == DEFAULT_TENANT:
        return None
    if not _ID_RE.match(tenant_id):
        raise TenantError(f"invalid tenant id {tenant_id!r}", status=400)
    return tenant_id


class TenantForwarded(TenantError):
    """The tenant has been migrated away (runtime/migrate.py): a durable
    CUTOVER record made another process the owner. Transports render
    this as a 307 with ``Location`` + ``Retry-After`` (gRPC maps it to
    UNAVAILABLE with the target in the message) until the caller
    re-resolves the tenant's placement."""

    def __init__(self, tenant_id: str, location: str, retry_after_s: int = 5):
        super().__init__(
            f"tenant {tenant_id!r} migrated to {location}", status=307
        )
        self.tenant_id = tenant_id
        self.location = location
        self.retry_after_s = int(retry_after_s)


class TenantQuota:
    """Per-tenant refinement of the shared admission gate: an in-flight
    cap, a queue share, and a lines/s token bucket. Passive arithmetic
    only — every mutation happens under the gate's condition variable
    (serve/admission.py), so quota state needs no lock of its own and
    never introduces a second lock order.

    ``0`` disables a bound. The bucket debits at admission using the
    request's declared line count; tokens are not refunded on failure
    (a shed request still cost its arrival). Streaming sessions bypass
    the bucket (their line count is unknown at open) but hold an
    in-flight slot like any request.
    """

    def __init__(
        self,
        max_inflight: int = 0,
        max_queued: int = 0,
        lines_per_s: float = 0.0,
        burst_s: float = 2.0,
        clock=pclock.mono,
    ):
        self.max_inflight = int(max_inflight)
        self.max_queued = int(max_queued)
        self.lines_per_s = float(lines_per_s)
        self.clock = clock
        self._capacity = max(self.lines_per_s * float(burst_s), self.lines_per_s)
        self._tokens = self._capacity
        self._stamp = clock()
        # mutated under the gate's _cv, read unlocked for stats
        self.inflight = 0
        self.queued = 0
        self.admitted = 0
        self.lines_admitted = 0
        self.shed_rate = 0
        self.shed_oversize = 0
        self.shed_inflight = 0
        self.shed_queue = 0

    def debit_lines(self, lines: int) -> float | None:
        """Refill, then try to take ``lines`` tokens. Returns None when
        admitted, else the seconds until the bucket could cover the
        request (the Retry-After hint) — ``inf`` when the request
        declares more lines than the bucket can EVER hold, so the gate
        sheds it as futile (413) instead of sending the client into a
        permanent finite-Retry-After 429 loop. Caller holds the gate's
        _cv."""
        if self.lines_per_s <= 0 or lines <= 0:
            return None
        if lines > self._capacity:
            return float("inf")
        now = self.clock()
        self._tokens = min(
            self._capacity,
            self._tokens + (now - self._stamp) * self.lines_per_s,
        )
        self._stamp = now
        if self._tokens >= lines:
            self._tokens -= lines
            return None
        return max((lines - self._tokens) / self.lines_per_s, 0.05)

    def stats(self) -> dict:
        return {
            "maxInflight": self.max_inflight,
            "maxQueued": self.max_queued,
            "linesPerS": self.lines_per_s,
            "inflight": self.inflight,
            "queued": self.queued,
            "admitted": self.admitted,
            "linesAdmitted": self.lines_admitted,
            "shedRate": self.shed_rate,
            "shedOversize": self.shed_oversize,
            "shedInflight": self.shed_inflight,
            "shedQueue": self.shed_queue,
        }


def _bank_nbytes(bank) -> int:
    """Resident-size estimate for one compiled bank: every numpy array
    reachable one attribute level down from the bank and its columns
    (DFA transition tables dominate). An LRU budget knob, not an
    allocator — systematic under-count is fine as long as it is
    monotone in bank size."""
    total = 0
    seen: set[int] = set()

    def add(obj) -> None:
        if obj is None or id(obj) in seen:
            return
        seen.add(id(obj))
        if isinstance(obj, np.ndarray):
            nonlocal total
            total += obj.nbytes

    def scan(holder) -> None:
        d = getattr(holder, "__dict__", None)
        if not d:
            return
        for v in d.values():
            add(v)
            if isinstance(v, (list, tuple)):
                for x in v:
                    add(x)

    scan(bank)
    for col in getattr(bank, "columns", ()) or ():
        scan(col)
        dfa = getattr(col, "dfa", None)
        if dfa is not None:
            scan(dfa)
    return total + 4096 * max(1, getattr(bank, "n_columns", 1))


class TenantContext:
    """One tenant's slice of the process: a dedicated engine (bank,
    frequency, line cache, quarantine, breakers, batcher, shadow,
    stream manager, journal) plus its quota and lazily-built reloader.
    Handed out by :class:`TenantRegistry.resolve`; request paths hold
    the context, never the tenant id."""

    def __init__(self, tenant_id: str, engine, quota: TenantQuota,
                 pattern_dir: str | None = None, lint_mode: str = "warn"):
        self.tenant_id = tenant_id
        self.engine = engine
        self.quota = quota
        self.pattern_dir = pattern_dir
        self.lint_mode = lint_mode
        self._reloader = None
        self._reloader_lock = threading.Lock()
        # live request leases (resolve → transport finish). The quota's
        # inflight/queued only exist from admission.acquire on; the pin
        # covers the whole window so eviction can never close the engine
        # under a request that holds the context but has not yet (or will
        # never) pass the gate.
        self._pins = 0
        self._pins_lock = threading.Lock()
        self.bank_bytes = _bank_nbytes(engine.bank)

    def reloader(self):
        """This tenant's reload ladder — quiesces only this tenant's
        engine, so a reload here never stalls another tenant's traffic."""
        with self._reloader_lock:
            if self._reloader is None:
                from log_parser_tpu.runtime.reload import PatternReloader

                self._reloader = PatternReloader(
                    self.engine,
                    pattern_dir=self.pattern_dir,
                    lint_mode=self.lint_mode,
                )
            return self._reloader

    def note_reloaded(self) -> None:
        """Re-estimate residency after a swap changed the bank."""
        self.bank_bytes = _bank_nbytes(self.engine.bank)

    def pin(self) -> "TenantContext":
        """Lease this context for one request. :meth:`TenantRegistry.
        resolve` pins every context it hands out; the transport unpins
        when the request finishes (its release/cleanup ``finally``)."""
        with self._pins_lock:
            self._pins += 1
        return self

    def unpin(self) -> None:
        with self._pins_lock:
            self._pins -= 1

    def busy(self) -> bool:
        """True while eviction would strand live work: a resolved-but-
        unreleased request lease, in-flight or queued requests, or open
        streaming sessions pinned to this tenant's bank epoch."""
        with self._pins_lock:
            if self._pins > 0:
                return True
        if self.quota.inflight > 0 or self.quota.queued > 0:
            return True
        mgr = getattr(self.engine, "stream_manager", None)
        if mgr is not None and mgr.stats().get("openSessions", 0) > 0:
            return True
        return False

    def close(self) -> None:
        """Quiesce this tenant's moving parts for eviction/shutdown:
        flush the batcher, stop the shadow verifier, kill stream
        sessions, and fold the WAL into a final snapshot so the next
        build warm-attaches the frequency state it left behind."""
        eng = self.engine
        mgr = getattr(eng, "stream_manager", None)
        if mgr is not None:
            mgr.shutdown()
        if getattr(eng, "batcher", None) is not None:
            eng.batcher.close()
        if getattr(eng, "miner", None) is not None:
            # parked candidates stay durable under the tenant's state
            # dir; the rebuilt tenant's miner rehydrates them
            eng.miner.stop()
        if getattr(eng, "shadow", None) is not None:
            eng.shadow.close()
        journal = getattr(eng, "journal", None)
        if journal is not None:
            journal.snapshot_now()
            journal.close()
        obs = getattr(eng, "obs", None)
        if obs is not None:
            # an evicted engine must stop feeding the shared registry —
            # a stale collector would pin the engine in memory and keep
            # emitting dead samples
            obs.remove_engine_collector(eng)

    def stats(self) -> dict:
        return {
            "bankBytes": int(self.bank_bytes),
            "patterns": int(self.engine.bank.n_patterns),
            "reloadEpoch": int(getattr(self.engine, "reload_epoch", 0)),
            "quota": self.quota.stats(),
        }


# /metrics view over TenantRegistry.stats() — registered against the
# default engine's obs bundle at construction (log_parser_tpu/obs)
METRIC_SAMPLES = (
    ("residentTenants", "logparser_tenants_resident", {}),
    ("created", "logparser_tenant_builds_total", {}),
    ("evicted", "logparser_tenant_evictions_total", {}),
)


class TenantRegistry:
    """Tenant id → :class:`TenantContext`, with lazy builds and LRU
    residency. The default tenant wraps the engine the server booted
    with and is never evicted; non-default tenants build from
    ``root/<id>/`` on first resolve (warm through patterns/libcache.py)
    and compete for ``budget_mb`` of resident bank bytes.

    ``engine_setup(engine, tenant_id)`` is the serve-layer hook that
    mirrors the boot-time wiring (batching, line cache, journal at
    ``state_root/<id>``, stream manager) onto each new tenant engine —
    the registry itself stays policy-free. ``gate`` is the shared
    admission controller pre-attached to every tenant engine so all
    transports admit through one semaphore.
    """

    def __init__(
        self,
        default_engine,
        *,
        root: str | None = None,
        budget_mb: float = 0.0,
        gate=None,
        engine_setup=None,
        quota_factory=None,
        lint_mode: str = "warn",
        clock=pclock.mono,
    ):
        self.default_engine = default_engine
        self.root = root
        self.budget_bytes = int(float(budget_mb) * 1024 * 1024)
        self.gate = gate
        self.engine_setup = engine_setup
        self.quota_factory = quota_factory or (lambda tid: TenantQuota())
        self.lint_mode = lint_mode
        self.clock = clock
        self._lock = threading.RLock()
        # LRU order: oldest-resolved first; default kept out of the map
        self._contexts: dict[str, TenantContext] = {}
        self._order: list[str] = []
        self._evicted_ids: set[str] = set()
        # first-touch builds in flight: tenant id -> completion event.
        # Builds run OUTSIDE _lock (a bank compile takes seconds and must
        # never stall another tenant's resolution); concurrent first
        # touches of the same tenant coalesce on the event instead of
        # compiling the bank twice.
        self._building: dict[str, threading.Event] = {}
        # post-cutover forwards (runtime/migrate.py): tenant id ->
        # (location, retry_after_s). A forwarded tenant resolves to 307
        # until the caller re-resolves its placement; forwards are
        # re-installed on boot from the migration journals.
        self._forwards: dict[str, tuple[str, int]] = {}
        # registry-wide fence (runtime/replicate.py): a demoted/standby
        # process must refuse EVERY tenant resolution — including the
        # default tenant, which per-tenant forwards cannot cover — or
        # stale local state would fork the frequency history under a
        # split brain. (location, retry_after_s) → 307 to the owner.
        self._fence: tuple[str, int] | None = None
        self.default_context = TenantContext(
            DEFAULT_TENANT,
            default_engine,
            self.quota_factory(DEFAULT_TENANT),
            pattern_dir=None,
            lint_mode=lint_mode,
        )
        if gate is not None:
            default_engine.admission_gate = gate
        # counters (GET /trace/last `tenants` block)
        self.resolved = 0
        self.created = 0
        self.evicted = 0
        self.rebuilds = 0
        self.unknown = 0
        self.invalid = 0
        self.forwarded = 0
        self.fenced = 0
        obs = getattr(default_engine, "obs", None)
        if obs is not None:
            obs.add_stats_collector("tenants", self.stats, METRIC_SAMPLES)

    # ------------------------------------------------------------ resolve

    def resolve(
        self, tenant_id: str | None, *, ignore_forward: bool = False
    ) -> TenantContext:
        """Map a wire tenant id to its context, building on first use.
        None/empty → default tenant (single-tenant back-compat).

        The returned context is PINNED: the caller must
        :meth:`TenantContext.unpin` it when the request finishes (the
        transports do so in the same ``finally`` that releases the
        admission slot). The pin keeps eviction off the engine for the
        whole request — the quota's inflight/queued counters only cover
        the stretch after ``admission.acquire``.

        ``ignore_forward`` is for the migration protocol's own internal
        resolutions (e.g. the target's bank verification while this
        process still holds a stale outbound forward for a tenant coming
        BACK): traffic routing must keep answering 307 until ownership
        actually returns, so only ``runtime/migrate.py`` passes it."""
        faults.fire(  # conlint: contained-by-caller (transport error path)
            "tenant_resolve", key=tenant_id or DEFAULT_TENANT
        )
        if not ignore_forward:
            with self._lock:
                fence = self._fence
                if fence is not None:
                    # fenced (standby / demoted primary): every resolution
                    # — default tenant included, which the per-tenant
                    # forward check below never sees — 307s to the owner
                    self.fenced += 1
                    raise TenantForwarded(
                        tenant_id or DEFAULT_TENANT, fence[0], fence[1]
                    )
        try:
            # the shared edge validation (also run by fleet/router.py
            # before a request ever reaches this process)
            edge_id = edge_tenant_id(tenant_id)
        except TenantError:
            with self._lock:
                self.invalid += 1
            raise
        if edge_id is None:
            with self._lock:
                self.resolved += 1
            return self.default_context.pin()
        tenant_id = edge_id
        if not ignore_forward:
            with self._lock:
                fwd = self._forwards.get(tenant_id)
                if fwd is not None:
                    # post-cutover: another process owns this tenant now.
                    # Refuse to serve (stale local state would fork the
                    # frequency history) and point the caller at the owner.
                    self.forwarded += 1
                    raise TenantForwarded(tenant_id, fwd[0], fwd[1])
        while True:
            with self._lock:
                ctx = self._contexts.get(tenant_id)
                if ctx is not None:
                    self.resolved += 1
                    self._order.remove(tenant_id)
                    self._order.append(tenant_id)
                    ctx.pin()  # before the evict pass: busy() must see it
                    # an eviction deferred while every candidate was busy
                    # retries here, as traffic flows
                    self._evict_over_budget()
                    return ctx
                pending = self._building.get(tenant_id)
                if pending is None:
                    if self.root is None:
                        self.unknown += 1
                        raise TenantError(
                            f"unknown tenant {tenant_id!r} (no --tenant-root)",
                            404,
                        )
                    lib_dir = os.path.join(self.root, tenant_id)
                    if not os.path.isdir(lib_dir):
                        self.unknown += 1
                        raise TenantError(f"unknown tenant {tenant_id!r}", 404)
                    pending = threading.Event()
                    self._building[tenant_id] = pending
                    break  # this thread owns the build
            # another thread is compiling this tenant's bank: wait for it
            # and re-check the map (its failure makes us the next builder)
            pending.wait()
        try:
            ctx = self._build(tenant_id, lib_dir)
        except BaseException:
            with self._lock:
                self._building.pop(tenant_id, None)
            pending.set()
            raise
        with self._lock:
            self._contexts[tenant_id] = ctx
            self._order.append(tenant_id)
            self._building.pop(tenant_id, None)
            self.resolved += 1
            self.created += 1
            if tenant_id in self._evicted_ids:
                self.rebuilds += 1
            ctx.pin()
            self._evict_over_budget()
        pending.set()
        return ctx

    def _build(self, tenant_id: str, lib_dir: str) -> TenantContext:
        from log_parser_tpu.patterns.loader import load_pattern_directory
        from log_parser_tpu.runtime.engine import AnalysisEngine

        sets = load_pattern_directory(lib_dir)
        if not sets:
            raise TenantError(
                f"tenant {tenant_id!r} has no pattern sets in {lib_dir!r}", 404
            )
        t0 = self.clock()
        wt0 = pclock.mono()
        eng = AnalysisEngine(
            sets, self.default_engine.config, clock=self.clock
        )
        if self.gate is not None:
            # shared process-wide gate: shared_gate(tenant_engine) in any
            # transport now returns this controller, not a fresh one
            eng.admission_gate = self.gate
        # one observability plane per fleet: the tenant engine swaps its
        # private bundle for the primary's, labeled by tenant, so one
        # /metrics scrape covers every resident engine
        primary_obs = getattr(self.default_engine, "obs", None)
        if primary_obs is not None:
            eng.obs.remove_engine_collector(eng)
            eng.obs = primary_obs
            eng.obs_tenant = tenant_id
            primary_obs.add_engine_collector(eng)
        if self.engine_setup is not None:
            self.engine_setup(eng, tenant_id)
        ctx = TenantContext(
            tenant_id, eng, self.quota_factory(tenant_id),
            pattern_dir=lib_dir, lint_mode=self.lint_mode,
        )
        log.info(
            "tenant %r built: %d pattern(s), ~%.1f MB bank, %.2fs",
            tenant_id, eng.bank.n_patterns, ctx.bank_bytes / 2**20,
            self.clock() - t0,
        )
        if primary_obs is not None:
            # lifecycle spans are rare and force-committed; the trace id
            # is deterministic per tenant so rebuild-after-evict shows as
            # repeated tenant_build/tenant_evict trees for one id
            primary_obs.spans.end_trace(
                f"tenant:{tenant_id}",
                duration_s=pclock.mono() - wt0,
                tenant=tenant_id,
                name="tenant_build",
                attrs={
                    "patterns": eng.bank.n_patterns,
                    "bankBytes": ctx.bank_bytes,
                    "rebuild": tenant_id in self._evicted_ids,
                },
                force=True,
            )
        return ctx

    # ----------------------------------------------------------- residency

    def _resident_bytes(self) -> int:
        return sum(c.bank_bytes for c in self._contexts.values())

    def set_line_cache_budget(self, budget_bytes: int) -> None:
        """Push a re-arbitrated line-cache budget to every resident
        engine, default included (the fleet share covers the process,
        not one engine)."""
        with self._lock:
            engines = [self.default_engine] + [
                ctx.engine for ctx in self._contexts.values()
            ]
        for engine in engines:
            cache = getattr(engine, "line_cache", None)
            if cache is not None:
                cache.set_budget(budget_bytes)

    def set_budget_mb(self, budget_mb: float) -> None:
        """Re-arbitrate the residency budget live (fleet/budget.py
        pushes shares through ``POST /admin/budget``). Shrinking evicts
        idle tenants down to the new budget immediately; growth simply
        stops the next eviction sooner."""
        with self._lock:
            self.budget_bytes = int(float(budget_mb) * 1024 * 1024)
            self._evict_over_budget()

    def shed_idle(self, frac: float = 0.5) -> int:
        """Memory-pressure lever (runtime/pressure.py): LRU-evict idle
        non-default tenants down to ``frac`` of their *current* resident
        bank bytes, without touching the configured budget — pressure is
        transient, the operator's budget is policy. Returns how many
        tenants were evicted; busy tenants are skipped exactly as in
        budget eviction."""
        with self._lock:
            resident = self._resident_bytes()
            target = int(resident * max(0.0, min(1.0, float(frac))))
            if resident <= 0 or target <= 0:
                return 0
            before = self.evicted
            saved = self.budget_bytes
            self.budget_bytes = target
            try:
                self._evict_over_budget()
            finally:
                self.budget_bytes = saved
            return self.evicted - before

    def _evict_over_budget(self) -> None:
        """LRU-evict idle non-default tenants until resident bank bytes
        fit the budget. Busy tenants are skipped — an in-flight request
        keeps its engine reference, and evicting under it would violate
        the epoch pinning streaming relies on. Caller holds _lock."""
        if self.budget_bytes <= 0:
            return
        while self._resident_bytes() > self.budget_bytes:
            victim = None
            # the MRU entry is always protected: it is the tenant whose
            # resolve is running right now, and evicting it would close
            # the journal/batcher under the request that just built it
            for tid in self._order[:-1]:
                ctx = self._contexts[tid]
                if not ctx.busy():
                    victim = tid
                    break
            if victim is None:
                log.warning(
                    "tenant budget exceeded (%.1f/%.1f MB) but every "
                    "resident tenant is busy; deferring eviction",
                    self._resident_bytes() / 2**20,
                    self.budget_bytes / 2**20,
                )
                return
            faults.fire("tenant_evict", key=victim)  # conlint: contained-by-caller (resolve -> transport error path)
            ctx = self._contexts.pop(victim)
            self._order.remove(victim)
            self._evicted_ids.add(victim)
            self.evicted += 1
            log.info(
                "tenant %r evicted (LRU, ~%.1f MB freed); next resolve "
                "rebuilds from the library snapshot",
                victim, ctx.bank_bytes / 2**20,
            )
            t0 = pclock.mono()
            ctx.close()
            obs = getattr(self.default_engine, "obs", None)
            if obs is not None:
                obs.spans.end_trace(
                    f"tenant:{victim}",
                    duration_s=pclock.mono() - t0,
                    tenant=victim,
                    name="tenant_evict",
                    attrs={"bankBytes": ctx.bank_bytes,
                           "residentBytes": self._resident_bytes()},
                    force=True,
                )

    # -------------------------------------------------------------- admin

    def resident(self) -> list[str]:
        with self._lock:
            return [DEFAULT_TENANT] + list(self._order)

    def context_if_resident(self, tenant_id: str) -> TenantContext | None:
        with self._lock:
            if not tenant_id or tenant_id == DEFAULT_TENANT:
                return self.default_context
            return self._contexts.get(tenant_id)

    def set_forward(self, tenant_id: str, location: str,
                    retry_after_s: int = 5) -> None:
        """Install a post-cutover forward: every subsequent resolve of
        ``tenant_id`` raises :class:`TenantForwarded` (307 + Location +
        Retry-After on the wire) until :meth:`clear_forward`."""
        with self._lock:
            self._forwards[tenant_id] = (location, int(retry_after_s))

    def clear_forward(self, tenant_id: str) -> bool:
        """Drop a forward (the tenant migrated back, or ownership was
        re-assigned by the fleet router)."""
        with self._lock:
            return self._forwards.pop(tenant_id, None) is not None

    def forward_for(self, tenant_id: str) -> tuple[str, int] | None:
        with self._lock:
            return self._forwards.get(tenant_id)

    def forward_count(self) -> int:
        with self._lock:
            return len(self._forwards)

    def set_fence(self, location: str, retry_after_s: int = 5) -> None:
        """Fence the WHOLE registry: every resolve — default tenant
        included — raises :class:`TenantForwarded` (307 to ``location``)
        until :meth:`clear_fence`. Installed by runtime/replicate.py when
        this process is (or demotes to) the warm standby; internal
        ``ignore_forward`` resolutions (replication apply, migration)
        pass through so the standby can keep its bank warm."""
        with self._lock:
            self._fence = (location, int(retry_after_s))

    def clear_fence(self) -> bool:
        """Drop the registry fence (this process was promoted to owner)."""
        with self._lock:
            was = self._fence is not None
            self._fence = None
            return was

    def fence_for(self) -> tuple[str, int] | None:
        with self._lock:
            return self._fence

    def detach(self, tenant_id: str) -> TenantContext | None:
        """Remove a tenant from residency WITHOUT closing it — the
        migration engine detaches after cutover and closes the context
        itself, outside the registry lock. Returns the context, or None
        if the tenant was not resident (the default tenant is never
        detachable)."""
        with self._lock:
            if not tenant_id or tenant_id == DEFAULT_TENANT:
                return None
            ctx = self._contexts.pop(tenant_id, None)
            if ctx is not None:
                self._order.remove(tenant_id)
            return ctx

    def shutdown(self) -> None:
        """Close every non-default tenant (the default engine's parts are
        torn down by the server's own shutdown sequence)."""
        with self._lock:
            ctxs = list(self._contexts.values())
            self._contexts.clear()
            self._order.clear()
        for ctx in ctxs:
            try:
                ctx.close()
            except Exception:
                log.exception("tenant %r close failed", ctx.tenant_id)

    def stats(self) -> dict:
        with self._lock:
            per_tenant = {
                DEFAULT_TENANT: self.default_context.stats(),
                **{tid: c.stats() for tid, c in self._contexts.items()},
            }
            return {
                "residentTenants": 1 + len(self._contexts),
                "budgetMb": round(self.budget_bytes / 2**20, 3),
                "residentBankMb": round(
                    (self.default_context.bank_bytes + self._resident_bytes())
                    / 2**20, 3,
                ),
                "resolved": self.resolved,
                "created": self.created,
                "evicted": self.evicted,
                "rebuilds": self.rebuilds,
                "unknown": self.unknown,
                "invalid": self.invalid,
                "forwarded": self.forwarded,
                "forwards": len(self._forwards),
                "fenced": self.fenced,
                "fence": self._fence[0] if self._fence is not None else "",
                "perTenant": per_tenant,
            }
