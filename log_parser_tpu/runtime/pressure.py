"""Process-wide resource-pressure plane: disk watermarks, memory
watermarks, and retry budgets under one controller.

PRs 4-18 grew the parser into a durable, replicated, routed fleet, and
every one of those layers quietly assumed infinite disk and calm
clients: an ENOSPC on a WAL append, a snapshot rotation, a replica
re-journal, a migration bundle write, or the shutdown OTLP dump used to
surface as an unhandled OSError mid-request (or mid-drain), and the
router/shim retry paths had no budget, so one slow backend could
amplify into a fleet-wide retry storm. This module is the single place
that failure family is decided:

* **Disk** — a watermark monitor over ``--state-dir`` (free-bytes poll
  plus per-write ENOSPC/EIO escalation) drives a three-state ladder:

  - ``ok``   — full fsync'd durability, nothing special.
  - ``soft`` — reclaim: every registered journal snapshots + truncates
    its WAL, the migration and ``_replica/epoch.wal`` journals compact
    past their terminal records, and the miner stops parking pending
    YAML to disk (candidates stay reviewable in memory).
  - ``hard`` — degrade: journals divert appends to a bounded in-memory
    ring and stamp ``durability: degraded`` on ``/q/health``,
    ``/trace/last`` and every response envelope; replica senders pause
    (the receiver refuses feeds with a distinct 409 reason); snapshot
    and OTLP writers skip atomically instead of raising. The serving
    path keeps answering 200s throughout.

  Recovery is hysteretic (free space must clear the watermark by
  :data:`RECOVER_MARGIN`, and a tiny probe write must succeed) and
  re-arms fsync'd journaling from a clean barrier: each journal's
  :meth:`rearm` snapshots the *live* tracker — which holds everything
  the ring records echoed — so a crash after recovery replays exactly
  like one that never saw pressure.

* **Memory** — an RSS watermark (psutil-free, ``/proc/self/statm``)
  composes the levers the earlier PRs built individually — line-cache
  shrink, interner evict-half, tenant LRU eviction, span staging trim,
  miner tap close — under one controller: one lever per poll in
  severity order while over the watermark, released in reverse once RSS
  clears the watermark by the same hysteresis margin.

* **Retry budgets** — a token-bucket budget shared per destination
  (every first attempt deposits ``ratio`` tokens, default 10%; every
  retry spends one) wrapped around shim reconnects, router
  forward-follows/next-owner retries, and replica sender backoff, so
  retries shed deterministically (``retry budget exhausted``) instead
  of multiplying load into a storm.

Fault sites (LOG_PARSER_TPU_FAULTS) so drills run on any host without
filling a real disk:

- ``disk_enospc`` — fired with ``key=`` the durability site name at
  every guarded write (:data:`DISK_SITES`) and with
  ``key="watermark:hard"`` / ``key="watermark:soft"`` by the ladder
  poll. ``disk_enospc_raise@match=wal_append`` injects ENOSPC at WAL
  appends only; ``disk_enospc_raise@match=watermark:hard`` forces the
  ladder hard; an unqualified ``disk_enospc_raise`` is a full disk —
  every write fails and the ladder pins hard.
- ``mem_pressure`` — fired by the memory poll; a raise is "RSS is over
  the soft watermark" regardless of the real number.
- ``retry_storm`` — fired inside :meth:`RetryBudget.allow`; a raise is
  an exhausted bucket, so sheds happen deterministically in drills.

Transitions are journaled-then-acted where durable state changes
hands: every reclaim/degrade action rides an existing journal or
atomic-replace discipline (snapshot-before-truncate, tmp+fsync+
``os.replace``), while the ladder state itself is *derived* — a boot
re-polls the same watermarks, so there is nothing to replay.
"""

from __future__ import annotations

import errno
import logging
import os
import threading
import time
from typing import Callable

from log_parser_tpu import _clock as pclock
from log_parser_tpu.runtime import faults

log = logging.getLogger(__name__)

STATES = ("ok", "soft", "hard")
_RANK = {"ok": 0, "soft": 1, "hard": 2}

# free space must clear a watermark by this factor (and a probe write
# must succeed) before the ladder de-escalates — flapping around the
# threshold must not churn snapshot/degrade cycles
RECOVER_MARGIN = 1.25

# records each degraded journal keeps in memory while hard; the ring is
# an *echo* of state the live tracker already holds, so overflow loses
# observability of the oldest diverted records, never state
DEGRADED_RING_RECORDS = 4096

# durability sites guarded by disk_write_guard(); ``@match=<site>``
# selects one. tools/hygiene.py pins each to a docs/OPS.md row.
DISK_SITES = (
    "wal_append",
    "fsync",
    "snapshot_rotate",
    "bundle_write",
    "replica_rejournal",
    "otlp_dump",
)

# watermark-probe keys the ladder poll fires (match targets for drills)
PROBE_HARD = "watermark:hard"
PROBE_SOFT = "watermark:soft"

# chaos vocabulary — tools/hygiene.py pins every key here to a
# docs/OPS.md row AND a live faults.fire call site, exactly like the
# miner/tenancy site tables
FAULT_SITES: dict[str, str] = {
    "disk_enospc": "every guarded durability write (key= the DISK_SITES "
    "name: wal_append/fsync/snapshot_rotate/bundle_write/"
    "replica_rejournal/otlp_dump) and the ladder's watermark probes "
    "(key= watermark:hard then watermark:soft) — a raise is ENOSPC at "
    "that site; unqualified, the disk is simply full",
    "mem_pressure": "the memory-watermark poll — a raise reads as RSS "
    "over the soft watermark, driving the lever ladder without "
    "allocating anything",
    "retry_storm": "RetryBudget.allow (key= the destination) — a raise "
    "is an exhausted bucket, so retries shed deterministically in "
    "drills",
}

_ENOSPC_ERRNOS = frozenset(
    e for e in (
        errno.ENOSPC,
        errno.EIO,
        getattr(errno, "EDQUOT", None),
    ) if e is not None
)


def disk_write_guard(site: str) -> None:
    """Injection point in front of a durability write. Converts an
    injected ``disk_enospc`` raise into an organic ``OSError(ENOSPC)``
    so the *real* containment path under test is exercised — callers
    never special-case injection."""
    try:
        faults.fire("disk_enospc", key=site)
    except faults.InjectedFault as exc:
        raise OSError(errno.ENOSPC, f"injected ENOSPC ({site})") from exc


def rss_bytes() -> int:
    """Resident set size without psutil: ``/proc/self/statm`` field 1
    (pages) times the page size. Returns 0 where /proc is absent (the
    memory ladder then only moves under an injected ``mem_pressure``)."""
    try:
        with open("/proc/self/statm", "rb") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError, AttributeError):
        return 0


class RetryBudget:
    """Token-bucket retry budget shared per destination.

    Every *first* attempt toward a destination deposits ``ratio`` tokens
    (:meth:`note_request`); every retry spends one (:meth:`allow`). The
    bucket starts at ``floor`` (so cold destinations can still retry)
    and caps at ``cap`` (so a quiet hour cannot bank an unbounded
    burst). Sustained retry throughput is therefore at most ``ratio``
    times request throughput — the classic 10% budget — and when the
    bucket runs dry the caller sheds with ``retry budget exhausted``
    instead of piling on. ``ratio <= 0`` disables the budget entirely
    (every retry allowed), which is also the drill's unbounded control.
    """

    def __init__(self, ratio: float = 0.1, *, floor: float = 3.0,
                 cap: float = 50.0):
        self.ratio = float(ratio)
        self.floor = float(floor)
        self.cap = float(cap)
        self._mu = threading.Lock()
        self._tokens: dict[str, float] = {}
        self.requests = 0
        self.allowed = 0
        self.shed = 0

    @property
    def enabled(self) -> bool:
        return self.ratio > 0.0

    def note_request(self, dest: str) -> None:
        """Account one first attempt toward ``dest`` (NOT a retry)."""
        if not self.enabled:
            return
        with self._mu:
            self.requests += 1
            have = self._tokens.get(dest, self.floor)
            self._tokens[dest] = min(self.cap, have + self.ratio)

    def allow(self, dest: str) -> bool:
        """Spend one retry token toward ``dest``; False means shed."""
        if not self.enabled:
            return True
        try:
            faults.fire("retry_storm", key=dest)
        except faults.InjectedFault:
            with self._mu:
                self.shed += 1
            return False
        with self._mu:
            have = self._tokens.get(dest, self.floor)
            if have >= 1.0:
                self._tokens[dest] = have - 1.0
                self.allowed += 1
                return True
            self.shed += 1
            return False

    def stats(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "ratio": self.ratio,
                "requests": self.requests,
                "allowed": self.allowed,
                "shed": self.shed,
                "destinations": len(self._tokens),
            }


class PressureController:
    """One controller per process: the disk ladder, the memory lever
    chain, and the shared retry budget. Everything is inert until
    watermarks are configured (or a fault site forces a state), so the
    default boot is byte-identical to the pre-pressure behaviour."""

    def __init__(
        self,
        state_dir: str | None,
        *,
        disk_soft_mb: float = 0.0,
        disk_hard_mb: float = 0.0,
        mem_soft_mb: float = 0.0,
        retry_ratio: float = 0.1,
        poll_s: float = 1.0,
        clock: Callable[[], float] = pclock.mono,
    ):
        self.state_dir = str(state_dir) if state_dir else None
        self.disk_soft_bytes = max(0, int(float(disk_soft_mb) * 2**20))
        self.disk_hard_bytes = max(0, int(float(disk_hard_mb) * 2**20))
        self.mem_soft_bytes = max(0, int(float(mem_soft_mb) * 2**20))
        self.poll_s = float(poll_s)
        self.clock = clock
        self.retry = RetryBudget(retry_ratio)

        self._mu = threading.RLock()
        self.disk_state = "ok"
        self.mem_state = "ok"
        self.transitions: dict[tuple[str, str], int] = {}
        self.write_errors = 0  # ENOSPC/EIO escalations observed
        self.free_bytes_last = -1
        self.rss_last = 0

        self._journals: list = []  # degrade()/rearm()/snapshot_now()
        self._compactors: list[tuple[str, Callable[[], int]]] = []
        self._miners: list = []
        self._levers: list[tuple[str, Callable, Callable | None]] = []
        self._applied = 0  # memory levers currently applied
        self.lever_counts: dict[str, int] = {}
        self.compacted: dict[str, int] = {}

        self._obs = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------- registration

    def register_journal(self, journal) -> None:
        """A journal exposing ``snapshot_now()``, ``degrade()`` and
        ``rearm()`` (runtime/journal.py FrequencyJournal). Soft pressure
        snapshots+truncates it; hard degrades it; recovery re-arms it."""
        with self._mu:
            self._journals.append(journal)
            if self.disk_state == "hard":
                journal.degrade()

    def unregister_journal(self, journal) -> None:
        with self._mu:
            try:
                self._journals.remove(journal)
            except ValueError:
                pass

    def register_compactor(self, name: str, fn: Callable[[], int]) -> None:
        """A bounded-growth reclaimer (migration-journal / epoch-WAL
        compaction) run at bootstrap and on every entry into soft. The
        callable returns how many journal files it compacted."""
        with self._mu:
            self._compactors.append((name, fn))

    def register_miner(self, miner) -> None:
        """Miner whose pending-YAML parking pauses under soft+ (it
        consults :func:`miner_park_paused` through the switchboard)."""
        with self._mu:
            self._miners.append(miner)

    def add_lever(self, name: str, apply: Callable[[], None],
                  release: Callable[[], None] | None = None) -> None:
        """Memory lever, registered in severity order. ``apply`` fires
        once as the ladder escalates (one lever per poll); ``release``
        (optional) undoes it when RSS clears the watermark."""
        with self._mu:
            self._levers.append((name, apply, release))

    def bind_obs(self, obs) -> None:
        """Attach the primary Obs bundle: transition spans + the
        ``logparser_pressure_*`` collector."""
        self._obs = obs
        obs.registry.register_collector("pressure", self.metric_samples)

    # ----------------------------------------------------------- ladders

    def bootstrap(self) -> None:
        """Boot-time pass: run compactors once (journals must not grow
        without bound across restarts) and take an initial poll so the
        first request already sees the true state."""
        self._run_compactors()
        self.poll()

    def free_disk_bytes(self) -> int:
        if not self.state_dir:
            return -1
        try:
            st = os.statvfs(self.state_dir)
            return int(st.f_bavail) * int(st.f_frsize)
        except OSError:
            return -1

    def _probe_write(self) -> bool:
        """Can the state dir actually take bytes again? A tiny
        write+fsync+unlink — required before de-escalating out of hard
        so an ENOSPC-escalated state never clears on a statvfs that
        looks fine while writes still fail."""
        if not self.state_dir:
            return True
        path = os.path.join(self.state_dir, ".pressure.probe")
        try:
            with open(path, "wb") as f:
                f.write(b"ok")
                f.flush()
                os.fsync(f.fileno())
            os.unlink(path)
            return True
        except OSError:
            return False

    def poll(self) -> None:
        """One evaluation of both ladders; the background thread calls
        this on the interval, tests call it directly."""
        self._poll_disk()
        self._poll_mem()

    def _poll_disk(self) -> None:
        forced = None
        try:
            faults.fire("disk_enospc", key=PROBE_HARD)
        except faults.InjectedFault:
            forced = "hard"
        if forced is None:
            try:
                faults.fire("disk_enospc", key=PROBE_SOFT)
            except faults.InjectedFault:
                forced = "soft"

        free = self.free_disk_bytes()
        self.free_bytes_last = free
        target = "ok"
        if forced is not None:
            target = forced
        elif free >= 0:
            if self.disk_hard_bytes and free <= self.disk_hard_bytes:
                target = "hard"
            elif self.disk_soft_bytes and free <= self.disk_soft_bytes:
                target = "soft"

        with self._mu:
            current = self.disk_state
            if _RANK[target] > _RANK[current]:
                self._transition_disk(target)
            elif _RANK[target] < _RANK[current]:
                # hysteresis: clear the watermark we are leaving by the
                # margin, and prove the disk takes writes again
                threshold = (
                    self.disk_hard_bytes if current == "hard"
                    else self.disk_soft_bytes
                )
                cleared = (
                    free < 0
                    or threshold == 0
                    or free > threshold * RECOVER_MARGIN
                )
                if cleared and self._probe_write():
                    self._transition_disk(target)

    def _poll_mem(self) -> None:
        over = False
        try:
            faults.fire("mem_pressure")
        except faults.InjectedFault:
            over = True
        rss = rss_bytes()
        self.rss_last = rss
        if not over and self.mem_soft_bytes and rss > self.mem_soft_bytes:
            over = True

        with self._mu:
            if over:
                if self.mem_state != "soft":
                    self._note_transition("memory", "soft")
                    self.mem_state = "soft"
                self._apply_next_lever()
            elif self.mem_state == "soft":
                # hysteresis on release too: stay soft until RSS clears
                # the watermark by the margin (forced-over polls count
                # as not-cleared only while the fault keeps firing)
                if (
                    not self.mem_soft_bytes
                    or rss * RECOVER_MARGIN < self.mem_soft_bytes
                    or rss == 0
                ):
                    self._release_levers()
                    self._note_transition("memory", "ok")
                    self.mem_state = "ok"

    # ------------------------------------------------------- transitions

    def _note_transition(self, resource: str, state: str) -> None:
        key = (resource, state)
        self.transitions[key] = self.transitions.get(key, 0) + 1
        obs = self._obs
        if obs is not None:
            try:
                obs.spans.end_trace(
                    f"pressure:{resource}",
                    duration_s=0.0,
                    tenant="default",
                    name="pressure",
                    attrs={"resource": resource, "state": state},
                    force=True,
                )
            except Exception:  # noqa: BLE001 — observability must not
                # gate a pressure transition
                log.exception("pressure span emit failed")
        log.warning("pressure: %s -> %s", resource, state)

    def _transition_disk(self, target: str) -> None:
        """Caller holds ``_mu``. Escalation and recovery actions both
        ride existing journal/atomic-replace disciplines — the ladder
        state itself is derived, never persisted."""
        current = self.disk_state
        self._note_transition("disk", target)
        self.disk_state = target
        if _RANK[target] > _RANK[current]:
            if target in ("soft", "hard") and current == "ok":
                self._enter_soft()
            if target == "hard":
                self._enter_hard()
        else:
            if current == "hard":
                self._rearm_journals()

    def _live_journals(self) -> list:
        """Prune journals closed since registration (tenant evictions
        close their WAL; nothing unregisters for them) and return the
        live set."""
        with self._mu:
            self._journals = [
                j for j in self._journals
                if getattr(j, "_fp", None) is not None or j.degraded
            ]
            return list(self._journals)

    def _enter_soft(self) -> None:
        """Reclaim: snapshot+truncate every WAL, compact the protocol
        journals. Each action is atomic on its own (tmp+fsync+replace /
        truncate-under-lock), so a crash mid-reclaim is recoverable."""
        for journal in self._live_journals():
            try:
                journal.snapshot_now()
            except Exception:  # noqa: BLE001 — reclaim is best-effort;
                # a failing journal already contained the error
                log.exception("soft-pressure snapshot failed")
        self._run_compactors()

    def _enter_hard(self) -> None:
        for journal in self._live_journals():
            try:
                journal.degrade()
            except Exception:  # noqa: BLE001
                log.exception("journal degrade failed")

    def _rearm_journals(self) -> None:
        """Recovery barrier: every degraded journal snapshots the live
        tracker (which holds everything the ring echoed) and resumes
        fsync'd appends — a crash after this replays bit-identically to
        one that never saw pressure."""
        for journal in self._live_journals():
            try:
                journal.rearm()
            except Exception:  # noqa: BLE001
                log.exception("journal rearm failed")

    def _run_compactors(self) -> None:
        for name, fn in list(self._compactors):
            try:
                n = int(fn() or 0)
            except Exception:  # noqa: BLE001 — compaction must never
                # take the process down; growth resumes, nothing lost
                log.exception("compactor %s failed", name)
                continue
            if n:
                self.compacted[name] = self.compacted.get(name, 0) + n

    def _apply_next_lever(self) -> None:
        if self._applied >= len(self._levers):
            return
        name, apply, _ = self._levers[self._applied]
        self._applied += 1
        try:
            apply()
            self.lever_counts[name] = self.lever_counts.get(name, 0) + 1
            log.warning("memory pressure: lever %r applied", name)
        except Exception:  # noqa: BLE001 — a broken lever must not stop
            # the ladder from trying the next one
            log.exception("memory lever %r failed", name)

    def _release_levers(self) -> None:
        for name, _, release in reversed(self._levers[: self._applied]):
            if release is None:
                continue
            try:
                release()
                log.info("memory pressure cleared: lever %r released", name)
            except Exception:  # noqa: BLE001
                log.exception("memory lever %r release failed", name)
        self._applied = 0

    # ----------------------------------------------------- escalation API

    def note_write_error(self, exc: BaseException, site: str = "") -> None:
        """Per-write escalation: an organic (or injected-then-converted)
        ENOSPC/EIO observed by a durability writer pins the ladder hard
        immediately — watermark polls alone would race the very next
        append."""
        e = getattr(exc, "errno", None)
        if e not in _ENOSPC_ERRNOS:
            return
        with self._mu:
            self.write_errors += 1
            if self.disk_state != "hard":
                log.error(
                    "pressure: write error at %s (%s) — degrading", site, exc
                )
                self._transition_disk("hard")

    # ------------------------------------------------------------ queries

    def durability_degraded(self) -> bool:
        return self.disk_state == "hard"

    def writes_paused(self) -> bool:
        return self.disk_state == "hard"

    def miner_park_paused(self) -> bool:
        return self.disk_state != "ok"

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "PressureController":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="pressure", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not pclock.wait(self._stop, self.poll_s):
            self.poll()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -------------------------------------------------------------- stats

    def degraded_writes(self) -> int:
        return sum(
            int(getattr(j, "degraded_records", 0)) for j in list(self._journals)
        )

    def stats(self) -> dict:
        with self._mu:
            return {
                "disk": self.disk_state,
                "memory": self.mem_state,
                "freeBytes": self.free_bytes_last,
                "rssBytes": self.rss_last,
                "diskSoftBytes": self.disk_soft_bytes,
                "diskHardBytes": self.disk_hard_bytes,
                "memSoftBytes": self.mem_soft_bytes,
                "writeErrors": self.write_errors,
                "degradedWrites": self.degraded_writes(),
                "transitions": {
                    f"{r}:{s}": n for (r, s), n in sorted(self.transitions.items())
                },
                "levers": dict(self.lever_counts),
                "compacted": dict(self.compacted),
                "retry": self.retry.stats(),
            }

    def health_check(self) -> dict:
        """One /q/health check row; DEGRADED whenever either ladder has
        left ``ok`` (the server still answers 200s — that is the point)."""
        with self._mu:
            ok = self.disk_state == "ok" and self.mem_state == "ok"
            return {
                "name": "pressure",
                "status": "UP" if ok else "DEGRADED",
                "data": {
                    "disk": self.disk_state,
                    "memory": self.mem_state,
                    "degradedWrites": self.degraded_writes(),
                },
            }

    def metric_samples(self) -> list:
        with self._mu:
            out = [
                ("logparser_pressure_state",
                 {"resource": "disk"}, float(_RANK[self.disk_state])),
                ("logparser_pressure_state",
                 {"resource": "memory"}, float(_RANK[self.mem_state])),
                ("logparser_pressure_degraded_writes_total",
                 {}, float(self.degraded_writes())),
            ]
            for (resource, state), n in sorted(self.transitions.items()):
                out.append((
                    "logparser_pressure_transitions_total",
                    {"resource": resource, "state": state}, float(n),
                ))
            for lever, n in sorted(self.lever_counts.items()):
                out.append((
                    "logparser_pressure_levers_total",
                    {"lever": lever}, float(n),
                ))
            r = self.retry
            out.append(("logparser_pressure_retry_total",
                        {"outcome": "allowed"}, float(r.allowed)))
            out.append(("logparser_pressure_retry_total",
                        {"outcome": "shed"}, float(r.shed)))
            return out


# ------------------------------------------------------- module switchboard
#
# journal/migrate/replicate/miner sit below the serving layer and cannot
# be handed a controller at construction without threading it through a
# dozen signatures — the same reasoning as faults.py's switchboard. The
# default (no controller installed) is inert: every query answers "ok".

_CONTROLLER: PressureController | None = None


def install(controller: PressureController | None) -> None:
    """Install (or clear, with None) the process-wide controller —
    serve boot and tests. Clearing stops the outgoing poll thread."""
    global _CONTROLLER
    old, _CONTROLLER = _CONTROLLER, controller
    if old is not None and old is not controller:
        old.stop()


def current() -> PressureController | None:
    return _CONTROLLER


def durability_degraded() -> bool:
    c = _CONTROLLER
    return c is not None and c.durability_degraded()


def writes_paused() -> bool:
    c = _CONTROLLER
    return c is not None and c.writes_paused()


def miner_park_paused() -> bool:
    c = _CONTROLLER
    return c is not None and c.miner_park_paused()


def note_write_error(exc: BaseException, site: str = "") -> None:
    c = _CONTROLLER
    if c is not None:
        c.note_write_error(exc, site)


def retry_budget() -> RetryBudget | None:
    c = _CONTROLLER
    return None if c is None else c.retry


def stamp(payload: dict) -> dict:
    """Mark a response envelope when durability is degraded. The stamp
    is explicit and structural — clients and drills key on it, so its
    absence is a *promise* that fsync'd journaling is armed."""
    if durability_degraded():
        payload["durability"] = "degraded"
    return payload
