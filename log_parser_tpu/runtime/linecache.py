"""Routing tier in front of the match cube: the exact-match line cache.

Real pod logs are overwhelmingly repeats of a small template set
(CelerLog routes by shape so only novel lines pay full parsing; Logram's
n-gram dictionaries are an O(1) membership test — PAPERS.md). The match
cube is gather-bound at ~9 ns/element and pays per (row × automaton ×
byte) (PERF.md §1), so the cheapest row is the one that never reaches
the device. This module memoizes the per-line *device-side* result — the
post-valid match-bit row of the cube, NOT final scores — keyed by the
hash of the ingest-normalized line bytes (the same normalization the
quarantine fingerprint uses, native/ingest.py ``normalize_blob``).

What is cacheable, exactly: in ``FusedMatchScore._step`` everything
downstream of the cube is a pure function of the post-override bit
matrix plus the request's line count. The PRE-override bit row is a pure
per-line function of (line bytes, bank identity): the automata consume
exactly ``length`` bytes, zero padding is automaton-neutral, and lines
flagged ``needs_host`` — whose truncated encode IS width-dependent — are
excluded from population (their rows are fully host-overridden anyway).
So the cache stores pre-override rows and the engine re-applies the
request's override cube (host-only columns, breaker-overridden patterns,
needs_host lines) on top at assembly time. That makes breaker handling
exact *by construction*: a tripped pattern's columns are served from the
host regex for cached and fresh rows alike — the per-pattern slice of
every cached entry is invalidated the instant the breaker opens, without
dropping the other patterns' bits.

Cross-line factors (proximity distances, sequence chains, context
windows) are NOT per-line — they are recomputed per request from the
assembled bit matrix by :func:`records_from_bits`, a numpy mirror of the
device extraction (same discovery order, same integer semantics), so
cached requests produce bit-identical ``MatchRecords`` and the
frequency-coupled factors replay on the host under ``state_lock``
exactly as before.

Novel lines flow to the device as a *compacted* residual batch —
deduplicated by key within a request and within a batcher flush before
padding, one device row per unique line — then populate the cache on the
way back (``dedupFanout`` counts the rows that never had to exist).

Invalidation: wholesale on ``reload_epoch`` bump (``apply_library``
flushes under the quiesced swap, so no stale populate can race it) and
functionally per-pattern on a shadow-verifier breaker trip via the
override replay described above. Bounded: LRU by resident bytes
(``--line-cache-mb``). Quarantine-compatible: a request served entirely
from cache never reaches the device step, so it can never strike.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict, deque

import numpy as np

from log_parser_tpu.golden.engine import SEQUENCE_NEAR_WINDOW
from log_parser_tpu.ops.fused import FusedStaticTables, MatchRecords, NO_HIT
from log_parser_tpu.patterns.bank import (
    CTX_ERROR,
    CTX_EXCEPTION,
    CTX_STACK,
    CTX_WARN,
    PatternBank,
)

DEFAULT_LINE_CACHE_MB = 64.0

# per-entry bookkeeping estimate beyond key + packed row: OrderedDict
# node, bytes objects' headers. Deliberately generous — the budget is an
# operator-facing ceiling, and under-counting would let the cache outgrow
# its flag.
_ENTRY_OVERHEAD = 96


def line_key(line_bytes: bytes) -> bytes:
    """Cache key for one ingest-normalized line. blake2b-128 over the
    exact content bytes: collisions are cryptographically negligible and
    cache poisoning is impossible — there is no way to make line A serve
    line B's bits without a preimage."""
    return hashlib.blake2b(line_bytes, digest_size=16).digest()


_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)


def probe64(v64: np.ndarray, lengths: np.ndarray, width: int) -> np.ndarray:
    """Vectorized 64-bit probe over :func:`dedup_slots`' int64 key-matrix
    rows: an FNV-1a fold of each row's content-carrying words plus its
    length, splitmix64-finalized. Width-independent for lines that fit
    the device width (the padding past a line's last partial word is
    zeros at every width, and padded-only words are skipped), so the
    same line yields the same probe across requests with different
    batch widths — the property the cross-request :class:`KeyInterner`
    needs. Lines longer than ``width`` hash their truncated prefix — an
    ambiguous key, which is why :meth:`KeyInterner.digests` never interns
    them (the stored word row would be truncated too, so the memcmp
    verify could not tell two same-length lines apart)."""
    n = v64.shape[0]
    wc_total = width // 8
    u = v64[:, :wc_total].view(np.uint64)
    # words that carry content; the fold skips the all-padding tail so
    # probes do not depend on this batch's padded width
    nw = np.minimum(-(-lengths // 8), wc_total)
    h = np.full(n, _FNV_OFFSET, dtype=np.uint64)
    max_w = int(nw.max()) if n else 0
    for j in range(max_w):
        h = np.where(nw > j, (h ^ u[:, j]) * _FNV_PRIME, h)
    h = (h ^ lengths.astype(np.uint64)) * _FNV_PRIME
    h ^= h >> np.uint64(30)
    h *= np.uint64(0xBF58476D1CE4E5B9)
    h ^= h >> np.uint64(27)
    h *= np.uint64(0x94D049BB133111EB)
    h ^= h >> np.uint64(31)
    return h


DEFAULT_INTERNER_MB = 32.0

# interned-content ceiling: 64 words = 512 bytes covers essentially every
# real log line (device_width already sits at the 99.5% length quantile);
# longer lines simply keep paying blake2b — exactness never depends on
# the ceiling
_INTERN_WORDS = 64
# fixed per-entry cost: words row + probe + length + recency stamp +
# digest bytes object + ndarray slot overheads
_INTERN_ENTRY_BYTES = _INTERN_WORDS * 8 + 8 + 8 + 8 + 16 + _ENTRY_OVERHEAD


class KeyInterner:
    """Two-level cache keying (PERF.md §15): the per-unique-line
    blake2b-128 fan-in is the keying lane's floor once ingest is
    vectorized, and repeat traffic pays it again for lines whose digest
    an earlier request already computed. The interner short-circuits
    that: a vectorized :func:`probe64` per unique line, a single
    ``searchsorted`` against the flat probe table, and a numpy
    word-matrix equality check (the vectorized memcmp) — warm requests
    recover their digests with ZERO per-line Python and zero
    cryptographic hashing. Only first-touch lines (and the
    cryptographically-negligible probe collisions) pay blake2b.

    Poisoning stays impossible: a digest is only ever returned for
    content whose padded word row AND true length compared equal to the
    content blake2b was run on — the same (prefix, length) ⇒ equality
    argument :func:`dedup_slots` rests on. Digests are pure functions of
    line content, so entries survive pattern reloads and breaker trips;
    the only bound is the byte budget, enforced by evicting the
    least-recently-used half when full.
    """

    def __init__(self, budget_bytes: int = int(DEFAULT_INTERNER_MB * 2**20)):
        self.lock = threading.Lock()
        self.budget_bytes = max(0, int(budget_bytes))
        self.max_entries = max(64, self.budget_bytes // _INTERN_ENTRY_BYTES)
        self._n = 0
        self._probes = np.zeros(0, dtype=np.uint64)
        self._words = np.zeros((0, _INTERN_WORDS), dtype=np.uint64)
        self._lengths = np.zeros(0, dtype=np.int64)
        self._stamp = np.zeros(0, dtype=np.int64)  # recency, for eviction
        self._digests = np.zeros(0, dtype=object)
        self._gen = 0
        self._sorted: tuple[np.ndarray, np.ndarray] | None = None
        self.probe_hits = 0
        self.inserts = 0
        self.collisions = 0
        self.evictions = 0

    def _sorted_view(self) -> tuple[np.ndarray, np.ndarray]:
        if self._sorted is None:
            order = np.argsort(self._probes[: self._n], kind="stable")
            self._sorted = (self._probes[order], order)
        return self._sorted

    def _grow(self, need: int) -> None:
        cap = len(self._probes)
        if need <= cap:
            return
        new = max(need, 256, cap * 2)
        for name in ("_probes", "_lengths", "_stamp", "_digests"):
            old = getattr(self, name)
            buf = np.zeros(new, dtype=old.dtype)
            buf[: self._n] = old[: self._n]
            setattr(self, name, buf)
        w = np.zeros((new, _INTERN_WORDS), dtype=np.uint64)
        w[: self._n] = self._words[: self._n]
        self._words = w

    def evict_half(self) -> int:
        """Memory-pressure lever (runtime/pressure.py): drop the
        least-recently-used half of the *current* entries, regardless of
        table fullness. Returns how many entries were dropped. Safe at
        any time — a dropped digest is recomputed on next touch."""
        with self.lock:
            keep_n = self._n // 2
            if self._n <= 1 or keep_n < 1:
                return 0
            dropped = self._n - keep_n
            keep = np.argpartition(self._stamp[: self._n], dropped)[dropped:]
            self.evictions += dropped
            for name in ("_probes", "_lengths", "_stamp", "_digests"):
                arr = getattr(self, name)
                arr[:keep_n] = arr[keep]
                setattr(self, name, arr)
            self._words[:keep_n] = self._words[keep]
            self._n = keep_n
            self._sorted = None
            return dropped

    def _evict_half(self) -> None:
        """Table full: keep the most-recently-used half. Coarser than a
        per-entry LRU but keeps eviction a single vectorized compaction
        instead of a per-insert OrderedDict walk."""
        keep_n = self.max_entries // 2
        if self._n <= keep_n:
            return
        keep = np.argpartition(self._stamp[: self._n], self._n - keep_n)[
            self._n - keep_n:
        ]
        self.evictions += self._n - keep_n
        for name in ("_probes", "_lengths", "_stamp", "_digests"):
            arr = getattr(self, name)
            arr[:keep_n] = arr[keep]
            setattr(self, name, arr)
        self._words[:keep_n] = self._words[keep]
        self._n = keep_n
        self._sorted = None

    def digests(
        self,
        v64_rows: np.ndarray,
        lengths: np.ndarray,
        width: int,
        blob,
        starts,
        ends,
    ) -> list[bytes]:
        """Digest per unique line, hashing only first-touch content.
        ``v64_rows``/``lengths`` are :func:`dedup_slots`' int64 key-matrix
        rows and true byte lengths for the unique lines;
        ``starts``/``ends`` are plain lists indexing ``blob`` (the same
        slices :func:`line_key` would hash)."""
        n = v64_rows.shape[0]
        if n == 0:
            return []
        probes = probe64(v64_rows, lengths, width)
        wc = width // 8
        u = v64_rows[:, : min(wc, _INTERN_WORDS)].view(np.uint64)
        if wc >= _INTERN_WORDS:
            batch_words = np.ascontiguousarray(u)
            internable = lengths <= _INTERN_WORDS * 8
        else:
            batch_words = np.zeros((n, _INTERN_WORDS), dtype=np.uint64)
            batch_words[:, :wc] = u
            # rows longer than the device width are TRUNCATED in v64: two
            # distinct lines sharing a width prefix (and length) would
            # compare equal word-for-word and share one digest. They stay
            # on blake2b — the same guard the wide branch applies at the
            # interning ceiling.
            internable = lengths <= width
        # comparing only the words any batch line can occupy is exact: an
        # entry with content past that point has a larger length, and the
        # length check fails first
        wmax = max(1, min(_INTERN_WORDS, -(-int(lengths.max()) // 8)))
        out = np.empty(n, dtype=object)
        found = np.zeros(n, dtype=bool)
        with self.lock:
            self._gen += 1
            present = np.zeros(n, dtype=bool)
            if self._n:
                sp, sid = self._sorted_view()
                pos = np.minimum(
                    np.searchsorted(sp, probes), self._n - 1
                )
                present = sp[pos] == probes
                cand = np.flatnonzero(present & internable)
                if cand.size:
                    eid = sid[pos[cand]]
                    ok = (self._lengths[eid] == lengths[cand]) & (
                        self._words[eid, :wmax] == batch_words[cand, :wmax]
                    ).all(axis=1)
                    hit_rows = cand[ok]
                    hit_eids = eid[ok]
                    self._stamp[hit_eids] = self._gen
                    self.probe_hits += len(hit_rows)
                    out[hit_rows] = self._digests[hit_eids]
                    found[hit_rows] = True
                    # probe matched but content differs: a 64-bit
                    # collision — those lines stay on blake2b forever
                    self.collisions += int(ok.size - ok.sum())
            miss_rows = np.flatnonzero(~found).tolist()
            ins_rows: list[int] = []
            batch_probes: set[int] = set()
            for i in miss_rows:
                out[i] = line_key(blob[starts[i] : ends[i]])
                p = int(probes[i])
                if internable[i] and not present[i] and p not in batch_probes:
                    batch_probes.add(p)
                    ins_rows.append(i)
            if self._n + len(ins_rows) > self.max_entries:
                self._evict_half()
                ins_rows = ins_rows[: max(0, self.max_entries - self._n)]
            if ins_rows:
                self._grow(self._n + len(ins_rows))
                ir = np.asarray(ins_rows, dtype=np.int64)
                sl = slice(self._n, self._n + len(ins_rows))
                self._probes[sl] = probes[ir]
                self._words[sl] = batch_words[ir]
                self._lengths[sl] = lengths[ir]
                self._stamp[sl] = self._gen
                self._digests[sl] = out[ir]
                self._n += len(ins_rows)
                self.inserts += len(ins_rows)
                self._sorted = None
        return out.tolist()

    def stats(self) -> dict:
        with self.lock:
            return {
                "budgetMb": round(self.budget_bytes / 2**20, 3),
                "entries": self._n,
                "residentBytes": self._n * _INTERN_ENTRY_BYTES,
                "probeHits": self.probe_hits,
                "inserts": self.inserts,
                "collisions": self.collisions,
                "evictions": self.evictions,
            }


def dedup_slots(
    corpus, interner: "KeyInterner | None" = None
) -> tuple[np.ndarray, np.ndarray, list[bytes], np.ndarray] | None:
    """Vectorized request-level dedup: unique lines and the line→slot
    fan-in in array speed instead of a per-line dict loop.

    Returns ``(line_slot, rep_lines, keys, counts)`` where slots are
    numbered by first appearance (bit-compatible with the scalar dict
    loop it replaces), ``rep_lines[s]`` is the first line index of slot
    ``s``, ``keys[s]`` its :func:`line_key` digest and ``counts[s]`` its
    multiplicity. Returns ``None`` when the corpus has no contiguous
    byte view (the lone-surrogate scalar path) — callers keep the dict
    loop there.

    Exactness: the comparison key is the encoded ``[width]`` u8 row
    concatenated with the true byte length. For lines that fit the
    device width the row IS the content (zero-padding is disambiguated
    by the length word: equal lengths + equal prefix ⇒ equal bytes).
    Lines longer than the width are ambiguous under truncation, so they
    are re-grouped exactly on their blob slices — they can never collide
    with a short line (lengths differ) and are rare by construction
    (device_width covers the 99.5% quantile, ops/encode.py).
    """
    kv = corpus.key_view()
    if kv is None:
        return None
    blob, starts, ends = kv
    enc = corpus.encoded
    n = int(enc.n_lines)
    if n == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z, [], z
    # the offset arrays may carry dropped trailing-empty parts past n
    starts = starts[:n]
    ends = ends[:n]
    width = enc.u8.shape[1]
    lengths = (ends - starts).astype(np.int64)
    # key row = u8 content ‖ true length, padded to an int64 boundary so
    # the grouping sort runs over a handful of int64 columns (a memcmp
    # sort over void rows is ~1.5× slower at this shape)
    kw = -(-(width + 8) // 8) * 8
    km = np.zeros((n, kw), dtype=np.uint8)
    km[:, :width] = enc.u8[:n]
    km[:, width : width + 8] = lengths.astype("<i8").reshape(n, 1).view(np.uint8)
    v64 = km.view("<i8")
    order = np.lexsort(v64.T[::-1])
    srt = v64[order]
    newrun = np.empty(n, dtype=bool)
    newrun[0] = True
    np.any(srt[1:] != srt[:-1], axis=1, out=newrun[1:])
    gid_sorted = np.cumsum(newrun) - 1
    group = np.empty(n, dtype=np.int64)
    group[order] = gid_sorted
    # lexsort is stable, so the first member of each run is the group's
    # first appearance in line order
    first_idx = order[np.flatnonzero(newrun)]
    long_lines = np.flatnonzero(lengths > width)
    if long_lines.size:
        next_gid = int(first_idx.size)
        exact: dict[bytes, int] = {}
        s_l = starts.tolist()
        e_l = ends.tolist()
        for i in long_lines.tolist():
            content = blob[s_l[i] : e_l[i]]
            gid = exact.get(content)
            if gid is None:
                gid = next_gid
                next_gid += 1
                exact[content] = gid
            group[i] = gid
        # regrouping may have emptied gids and appended new ones: rebuild
        # first-occurrence indices the general way
        uniq_g, first = np.unique(group, return_index=True)
        ord2 = np.argsort(first, kind="stable")
        remap = np.empty(uniq_g.size, dtype=np.int64)
        remap[ord2] = np.arange(uniq_g.size)
        line_slot = remap[np.searchsorted(uniq_g, group)]
        rep_lines = first[ord2]
    else:
        # renumber groups by first appearance so slot order matches the
        # scalar dict loop byte-for-byte
        ord2 = np.argsort(first_idx, kind="stable")
        remap = np.empty(first_idx.size, dtype=np.int64)
        remap[ord2] = np.arange(first_idx.size)
        line_slot = remap[group]
        rep_lines = first_idx[ord2]
    s_l = starts[rep_lines].tolist()
    e_l = ends[rep_lines].tolist()
    if interner is not None and width % 8 == 0:
        # two-level keying: vectorized probes + word-matrix-verified
        # digest reuse; blake2b only for lines never seen before
        keys = interner.digests(
            v64[rep_lines], lengths[rep_lines], width, blob, s_l, e_l
        )
    else:
        keys = [line_key(blob[a:b]) for a, b in zip(s_l, e_l)]
    counts = np.bincount(line_slot, minlength=rep_lines.size)
    return line_slot, rep_lines, keys, counts


class LineCache:
    """Bounded LRU of per-line pre-override match-bit rows.

    Thread-safe: one lock acquisition per ``lookup_packed`` /
    ``populate`` call (the batcher and concurrent pipelined requests
    share one instance). Rows are stored bit-packed (``np.packbits``) —
    a 600-column bank costs 75 bytes per resident line."""

    def __init__(self, n_columns: int, budget_bytes: int):
        self.lock = threading.Lock()
        self.budget_bytes = max(0, int(budget_bytes))
        self._entries: OrderedDict[bytes, bytes] = OrderedDict()
        self._set_columns(n_columns)
        self.resident_bytes = 0
        # counters (GET /trace/last "lineCache"; guarded by lock)
        self.hits = 0
        self.misses = 0
        self.residual_rows = 0
        self.dedup_fanout = 0
        self.evictions = 0
        self.epoch_flushes = 0

    def _set_columns(self, n_columns: int) -> None:
        self.n_columns = int(n_columns)
        self._row_bytes = (self.n_columns + 7) // 8
        self._entry_cost = 16 + self._row_bytes + _ENTRY_OVERHEAD

    # ------------------------------------------------------------- data path

    def lookup_packed(
        self, keys: list[bytes], counts: list[int] | None = None
    ) -> list[bytes | None]:
        """Per-key packed bit rows (or None for misses), LRU touch +
        hit/miss accounting in one lock acquisition. ``counts`` weights
        each key by its line multiplicity — the hot paths dedup a request
        to unique keys before looking up, but the counters keep describing
        LINES (hit rate stays meaningful to an operator) while the
        residual keeps describing device rows."""
        packed: list[bytes | None] = []
        with self.lock:
            hits = misses = 0
            for j, k in enumerate(keys):
                row = self._entries.get(k)
                w = counts[j] if counts is not None else 1
                if row is None:
                    misses += w
                else:
                    self._entries.move_to_end(k)
                    hits += w
                packed.append(row)
            self.hits += hits
            self.misses += misses
        return packed

    def unpack(self, packed: list[bytes]) -> np.ndarray:
        """Batch-unpack packed rows to bool [len(packed), n_columns] in
        one ``np.unpackbits`` call — the per-row variant is ~20x slower
        on a repeat-heavy request (PERF.md §11)."""
        if not packed:
            return np.zeros((0, self.n_columns), dtype=bool)
        buf = np.frombuffer(b"".join(packed), dtype=np.uint8)
        return np.unpackbits(
            buf.reshape(len(packed), self._row_bytes),
            axis=1,
            count=self.n_columns,
        ).astype(bool)

    def lookup(self, keys: list[bytes]) -> list[np.ndarray | None]:
        """Per-key bit rows (bool [n_columns]) or None for misses —
        convenience wrapper over :meth:`lookup_packed` for tests and
        small callers; the engine/batcher hot paths stay packed."""
        packed = self.lookup_packed(keys)
        hit = [p for p in packed if p is not None]
        rows = self.unpack(hit)
        out: list[np.ndarray | None] = []
        j = 0
        for p in packed:
            if p is None:
                out.append(None)
            else:
                out.append(rows[j])
                j += 1
        return out

    def populate_rows(self, keys: list[bytes], rows: np.ndarray) -> None:
        """Insert freshly computed rows (bool [len(keys), n_columns]),
        packed in one ``np.packbits`` call, evicting LRU entries past the
        byte budget."""
        if not keys:
            return
        packed = np.packbits(np.asarray(rows, dtype=bool), axis=1)
        ready = [(k, packed[j].tobytes()) for j, k in enumerate(keys)]
        self._insert(ready)

    def populate(self, items: list[tuple[bytes, np.ndarray]]) -> None:
        """Insert freshly computed (key, bool-row) pairs — convenience
        wrapper over :meth:`populate_rows`."""
        if items:
            self.populate_rows(
                [k for k, _ in items], np.stack([r for _, r in items])
            )

    def set_budget(self, budget_bytes: int) -> None:
        """Re-arbitrate the byte budget live (fleet/budget.py pushes
        shares through ``POST /admin/budget``): shrink evicts LRU
        entries down to the new budget immediately."""
        with self.lock:
            self.budget_bytes = max(0, int(budget_bytes))
            while self.resident_bytes > self.budget_bytes and self._entries:
                self._entries.popitem(last=False)
                self.resident_bytes -= self._entry_cost
                self.evictions += 1

    def _insert(self, ready: list[tuple[bytes, bytes]]) -> None:
        with self.lock:
            for k, p in ready:
                if k in self._entries:
                    self._entries.move_to_end(k)
                    continue
                self._entries[k] = p
                self.resident_bytes += self._entry_cost
            while self.resident_bytes > self.budget_bytes and self._entries:
                self._entries.popitem(last=False)
                self.resident_bytes -= self._entry_cost
                self.evictions += 1

    def note_residual(self, rows: int, fanout: int) -> None:
        """Account one residual dispatch: ``rows`` unique device rows
        actually sent, ``fanout`` duplicate lines they fanned back out to."""
        with self.lock:
            self.residual_rows += rows
            self.dedup_fanout += fanout

    def flush(self, n_columns: int | None = None) -> None:
        """Wholesale invalidation — the reload-epoch path. Called inside
        ``apply_library``'s quiesced critical section, after every
        in-flight populate has drained, so a stale hit across a pattern
        swap is structurally impossible. ``n_columns`` re-binds the row
        width when the new library changes the bank's column count."""
        with self.lock:
            self._entries.clear()
            self.resident_bytes = 0
            self.epoch_flushes += 1
            if n_columns is not None and n_columns != self.n_columns:
                self._set_columns(n_columns)

    # ------------------------------------------------------- observability

    def stats(self) -> dict:
        with self.lock:
            return {
                "budgetMb": round(self.budget_bytes / (1024 * 1024), 3),
                "entries": len(self._entries),
                "residentBytes": self.resident_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "residualRows": self.residual_rows,
                "dedupFanout": self.dedup_fanout,
                "evictions": self.evictions,
                "epochFlushes": self.epoch_flushes,
            }


# /metrics views over LineCache.stats() / KeyInterner.stats() — read by
# the obs engine collector at scrape time (log_parser_tpu/obs), so the
# exposition and /trace/last can never disagree on these counters
CACHE_METRIC_SAMPLES = (
    ("hits", "logparser_line_cache_hits_total", {}),
    ("misses", "logparser_line_cache_misses_total", {}),
    ("evictions", "logparser_line_cache_evictions_total", {}),
    ("residentBytes", "logparser_line_cache_resident_bytes", {}),
)
INTERNER_METRIC_SAMPLES = (
    ("probeHits", "logparser_interner_probe_hits_total", {}),
    ("inserts", "logparser_interner_inserts_total", {}),
)


# ------------------------------------------------------------ miss-stream tap

DEFAULT_TAP_CAPACITY = 4096


class MissTap:
    """Sampled, bounded, drop-counted feed of line-cache misses to the
    template miner (:mod:`log_parser_tpu.mining`).

    The hot path calls :meth:`offer` once per unique miss line — one lock
    acquisition appending the ingest-normalized line bytes to a bounded
    deque. Nothing ever blocks and nothing is retried: when the queue is
    full the line is counted in ``dropped`` and forgotten. The miner is
    an optimization; the parse path is the product, so saturation must
    cost one counter bump, never latency.

    Sampling is a deterministic stride over the offer sequence number
    (``sample=0.25`` keeps every 4th offer), so a chaos drill or test
    replays bit-identically without an RNG on the hot path; skipped
    offers are counted in ``sampledOut``.

    The consumer (:meth:`drain`) waits on an event with a timeout: the
    miner thread wakes promptly under traffic and idles cheaply without
    polling the lock.
    """

    def __init__(
        self, capacity: int = DEFAULT_TAP_CAPACITY, sample: float = 1.0
    ):
        self.lock = threading.Lock()
        self.capacity = max(1, int(capacity))
        self.sample = min(max(float(sample), 0.0), 1.0)
        self._q: deque[tuple[bytes, int]] = deque()
        self._seq = 0  # offers seen, pre-sampling (stride numerator)
        self._kept = 0  # offers past the sampler so far
        self.tapped = 0
        self.dropped = 0
        self.sampled_out = 0
        self._event = threading.Event()
        self._closed = False

    def offer(self, line_bytes: bytes, count: int = 1) -> bool:
        """Non-blocking hot-path enqueue of one miss line (``count`` = its
        multiplicity in the request). Returns True iff enqueued."""
        with self.lock:
            if self._closed:
                return False
            self._seq += 1
            want = int(self._seq * self.sample)
            if want <= self._kept:
                self.sampled_out += 1
                return False
            self._kept = want
            if len(self._q) >= self.capacity:
                self.dropped += 1
                return False
            self._q.append((bytes(line_bytes), int(count)))
            self.tapped += 1
        self._event.set()
        return True

    def drain(
        self, max_items: int = 512, timeout: float | None = 0.25
    ) -> list[tuple[bytes, int]]:
        """Consumer side: up to ``max_items`` queued (line_bytes, count)
        pairs, waiting up to ``timeout`` seconds for the first one."""
        if timeout and not self._event.is_set():
            self._event.wait(timeout)
        out: list[tuple[bytes, int]] = []
        with self.lock:
            while self._q and len(out) < max_items:
                out.append(self._q.popleft())
            if not self._q:
                self._event.clear()
        return out

    def close(self) -> None:
        with self.lock:
            self._closed = True
            self._q.clear()
        self._event.set()

    def stats(self) -> dict:
        with self.lock:
            return {
                "capacity": self.capacity,
                "sample": self.sample,
                "queued": len(self._q),
                "tapped": self.tapped,
                "dropped": self.dropped,
                "sampledOut": self.sampled_out,
            }


# --------------------------------------------------------- host extraction


def _host_prev_next_dist(hits: np.ndarray) -> np.ndarray:
    """numpy mirror of ops/fused.py ``_prev_next_dist``: [B, S] bool hit
    columns -> [B, S] int32 distance to the nearest hit on either side,
    own row excluded, NO_HIT where none."""
    B, S = hits.shape
    col = np.arange(B, dtype=np.int64)[:, None]
    prev_incl = np.maximum.accumulate(np.where(hits, col, -1), axis=0)
    prev = np.concatenate(
        [np.full((1, S), -1, dtype=np.int64), prev_incl[:-1]], axis=0
    )
    nxt_incl = np.flip(
        np.minimum.accumulate(
            np.flip(np.where(hits, col, int(NO_HIT)), axis=0), axis=0
        ),
        axis=0,
    )
    nxt = np.concatenate(
        [nxt_incl[1:], np.full((1, S), int(NO_HIT), dtype=np.int64)], axis=0
    )
    d_prev = np.where(prev >= 0, col - prev, int(NO_HIT))
    d_next = np.where(nxt < int(NO_HIT), nxt - col, int(NO_HIT))
    return np.minimum(d_prev, d_next).astype(np.int32)


def _host_sequence_flags(
    sequences, t: FusedStaticTables, em: np.ndarray, idx: np.ndarray, n_lines: int
) -> np.ndarray:
    """numpy mirror of ops/fused.py ``sequence_flags_from_events`` at the
    record rows ``idx`` only: last event within ±SEQUENCE_NEAR_WINDOW of
    the primary via a prefix-count range-any, earlier events chained
    strictly backwards via inclusive prefix-cummax of last-hit line."""
    B = em.shape[0]
    eidx = np.arange(B, dtype=np.int64)[:, None]
    prev_incl = np.maximum.accumulate(np.where(em, eidx, -1), axis=0)
    prefix = np.concatenate(
        [np.zeros((1, em.shape[1]), dtype=np.int64), np.cumsum(em, axis=0)]
    )
    w = SEQUENCE_NEAR_WINDOW
    outs = []
    for seq in sequences:
        if not seq.event_columns:
            outs.append(np.zeros(idx.shape, dtype=bool))
            continue
        last_e = t.seq_col_pos[seq.event_columns[-1]]
        lo = np.clip(idx - w, 0, B)
        hi = np.clip(np.minimum(idx + w + 1, n_lines), 0, B)
        ok = (prefix[hi, last_e] - prefix[lo, last_e]) > 0
        cur = idx
        for col in reversed(seq.event_columns[:-1]):
            e = t.seq_col_pos[col]
            g = np.where(cur >= 1, prev_incl[np.clip(cur - 1, 0, B - 1), e], -1)
            ok = ok & (g >= 0)
            cur = np.clip(g, 0, B - 1)
        outs.append(ok)
    return np.stack(outs, axis=1)


def records_from_bits(
    bits: np.ndarray,
    n_lines: int,
    bank: PatternBank,
    tables: FusedStaticTables,
) -> MatchRecords:
    """The device extraction, replayed on the host from an assembled
    post-override bit matrix ``bits`` [n_lines, n_columns] (cached rows +
    residual rows + override splice). Mirrors ``FusedMatchScore._step``
    downstream of the cube — same discovery order (line-major then
    pattern: ``np.argwhere`` is row-major), same per-pattern slot layout
    (``pat_sec``/``pat_seq``/``pat_ctx_shape``), same integer semantics —
    so the returned records are bit-identical to what the device would
    have produced for the full batch. Arrays are exact-size (K = M):
    finalize_batch and _verify_approx slice ``[:n_matches]``, so no
    padding rows are needed."""
    B = int(n_lines)
    P = bank.n_patterns
    s_w = max(1, tables.s_max)
    q_w = max(1, tables.q_max)

    def _empty() -> MatchRecords:
        return MatchRecords(
            n_matches=0,
            line=np.zeros(0, dtype=np.int32),
            pattern=np.zeros(0, dtype=np.int32),
            sec_dist=np.full((0, s_w), NO_HIT, dtype=np.int32),
            seq_ok=np.zeros((0, q_w), dtype=bool),
            ctx_counts=np.zeros((0, 5), dtype=np.int32),
        )

    if P == 0 or B == 0:
        return _empty()

    pm = bits[:, bank.primary_columns]  # [B, P]
    matched = np.argwhere(pm)  # row-major == discovery order
    m = len(matched)
    if m == 0:
        return _empty()
    rec_line = matched[:, 0].astype(np.int32)
    rec_pat = matched[:, 1].astype(np.int32)

    # ---- proximity distances (per-pattern secondary slots) ----------------
    rec_dist = np.full((m, s_w), NO_HIT, dtype=np.int32)
    if len(tables.sec_cols):
        dist = _host_prev_next_dist(bits[:, tables.sec_cols])  # [B, S_entries]
        sec_idx = tables.pat_sec[rec_pat]  # [m, s_w]
        rec_dist = np.where(
            sec_idx >= 0,
            dist[rec_line[:, None], np.maximum(sec_idx, 0)],
            np.int32(NO_HIT),
        ).astype(np.int32)

    # ---- sequence flags (per-pattern sequence slots) ----------------------
    rec_seq = np.zeros((m, q_w), dtype=bool)
    if bank.sequences:
        em = bits[:, np.asarray(tables.seq_event_cols, dtype=np.int64)]
        flags = _host_sequence_flags(
            bank.sequences, tables, em, rec_line.astype(np.int64), B
        )  # [m, n_sequences]
        q_idx = tables.pat_seq[rec_pat]  # [m, q_w]
        rec_seq = np.where(
            q_idx >= 0,
            flags[np.arange(m)[:, None], np.maximum(q_idx, 0)],
            False,
        )

    # ---- context window counts -------------------------------------------
    err = bits[:, CTX_ERROR]
    warn = bits[:, CTX_WARN] & ~err
    stack = bits[:, CTX_STACK]
    exc = bits[:, CTX_EXCEPTION]
    flags4 = np.stack([err, warn, stack, exc], axis=1).astype(np.int64)  # [B, 4]
    ps = np.concatenate(
        [np.zeros((1, 4), dtype=np.int64), np.cumsum(flags4, axis=0)]
    )
    shape_ids = tables.pat_ctx_shape[rec_pat]  # [m]
    rec_ctx = np.zeros((m, 5), dtype=np.int32)
    rl = rec_line.astype(np.int64)
    for u, (has_rules, before, after) in enumerate(tables.ctx_shapes):
        sel = shape_ids == u
        if not sel.any():
            continue
        li = rl[sel]
        if not has_rules:
            # context = the matched line only (AnalysisService.java:135-139)
            counts = flags4[li]
            total = np.ones(len(li), dtype=np.int64)
        else:
            lo = np.clip(li - before, 0, B)
            hi = np.clip(np.minimum(li + 1 + after, n_lines), 0, B)
            counts = ps[hi] - ps[lo]
            total = hi - lo
        rec_ctx[sel] = np.concatenate(
            [counts, total[:, None]], axis=1
        ).astype(np.int32)

    return MatchRecords(
        n_matches=m,
        line=rec_line,
        pattern=rec_pat,
        sec_dist=rec_dist,
        seq_ok=rec_seq,
        ctx_counts=rec_ctx,
    )
