"""Host finalizer: exact f64 scores from integer match records.

The device program (ops/fused.py) emits per-match integer factor
components; this module evaluates the reference's seven-factor formula
(ScoringService.java:102-109) over them in true IEEE-double arithmetic —
the same number system the JVM uses — vectorized with numpy over the
M ≪ B·P matched records. Summation loops whose order the reference fixes
(secondaries in declaration order, ScoringService.java:172-186; sequences
in declaration order, :208-215) run as short Python loops over the padded
per-pattern axis so the accumulation order is preserved; everything else
is elementwise.

Also recovers the frequency read-before-record ordering
(ScoringService.java:84-88) directly from the record stream: records
arrive in discovery order, so the Nth record of a slot sees exactly N-1
in-batch priors — a stable-sort cumcount, no device work at all.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden.engine import (
    DENSITY_MIN_LINES,
    DENSITY_PENALTY,
    DENSITY_RATIO,
    ERROR_WEIGHT,
    EXCEPTION_WEIGHT,
    STACK_BONUS_CAP,
    STACK_WEIGHT,
    WARN_WEIGHT,
)
from log_parser_tpu.javamath import java_div
from log_parser_tpu.ops.fused import FusedStaticTables, MatchRecords, NO_HIT
from log_parser_tpu.patterns.bank import PatternBank


@dataclasses.dataclass
class FinalizedBatch:
    """Scores per match record (discovery order) + frequency bookkeeping.

    The per-factor arrays are the parity-debugging surface (SURVEY.md §5.5):
    every component of every score, in the exact f64 values that were
    multiplied — the structured replacement for the reference's per-factor
    debug logs (ScoringService.java:90-99)."""

    scores: np.ndarray  # float64 [M]
    line: np.ndarray  # int32 [M] 0-based
    pattern: np.ndarray  # int32 [M]
    slot_batch_counts: np.ndarray  # int64 [n_freq_slots]
    chronological: np.ndarray  # float64 [M]
    proximity: np.ndarray  # float64 [M]
    temporal: np.ndarray  # float64 [M]
    context: np.ndarray  # float64 [M]
    frequency_penalty: np.ndarray  # float64 [M]

    def factor_rows(self, bank) -> list[dict]:
        """One dict per match, JSON-ready. ``score`` = confidence ×
        severityMultiplier × chronological × proximity × temporal × context
        × (1 − frequencyPenalty), exactly (ScoringService.java:102-109)."""
        # bulk ndarray→Python conversion: per-column .tolist() (and one
        # fancy-index gather for the per-pattern columns) instead of ~10
        # scalar __getitem__ + int()/float() casts per match. ``.tolist()``
        # yields exactly the Python ints/floats the scalar casts produce.
        pat = np.asarray(self.pattern, dtype=np.int64)
        pat_l = pat.tolist()
        pids = [bank.patterns[p].id for p in pat_l]
        cols = zip(
            self.line.tolist(),
            pids,
            np.asarray(bank.confidence, dtype=np.float64)[pat].tolist(),
            np.asarray(bank.severity_multiplier, dtype=np.float64)[pat].tolist(),
            self.chronological.tolist(),
            self.proximity.tolist(),
            self.temporal.tolist(),
            self.context.tolist(),
            self.frequency_penalty.tolist(),
            self.scores.tolist(),
        )
        return [
            {
                "lineNumber": ln + 1,
                "patternId": pid,
                "confidence": conf,
                "severityMultiplier": sev,
                "chronological": chrono,
                "proximity": prox,
                "temporal": temp,
                "context": ctx,
                "frequencyPenalty": fp,
                "score": sc,
            }
            for ln, pid, conf, sev, chrono, prox, temp, ctx, fp, sc in cols
        ]


def _slot_cumcount(slots: np.ndarray) -> np.ndarray:
    """Exclusive per-value running count: out[i] = |{j < i : slots[j] ==
    slots[i]}| — the in-batch prior each match sees."""
    m = len(slots)
    if m == 0:
        return np.zeros(0, dtype=np.int64)
    order = np.argsort(slots, kind="stable")
    sorted_slots = slots[order]
    new_group = np.empty(m, dtype=bool)
    new_group[0] = True
    new_group[1:] = sorted_slots[1:] != sorted_slots[:-1]
    group_start = np.maximum.accumulate(np.where(new_group, np.arange(m), 0))
    cum = np.arange(m) - group_start
    out = np.empty(m, dtype=np.int64)
    out[order] = cum
    return out


def finalize_batch(
    bank: PatternBank,
    tables: FusedStaticTables,
    config: ScoringConfig,
    recs: MatchRecords,
    n_lines: int,
    freq_base: np.ndarray,
    freq_exists: np.ndarray,
) -> FinalizedBatch:
    """``freq_base``: float64 [n_freq_slots] windowed counts at batch start;
    ``freq_exists``: tracker-has-entry flags (an expired window still has an
    entry and takes the formula path, FrequencyTrackingService.java:69-83)."""
    m = recs.n_matches
    line = recs.line[:m].astype(np.int64)
    pat = recs.pattern[:m].astype(np.int64)

    if m == 0:
        z = np.zeros(0, dtype=np.float64)
        return FinalizedBatch(
            scores=z,
            line=recs.line[:0],
            pattern=recs.pattern[:0],
            slot_batch_counts=np.zeros(max(1, bank.n_freq_slots), dtype=np.int64),
            chronological=z, proximity=z, temporal=z, context=z,
            frequency_penalty=z,
        )

    conf = bank.confidence[pat]
    sev = bank.severity_multiplier[pat]

    # ---- chronological (ScoringService.java:123-151) ----------------------
    pos = line.astype(np.float64) / float(n_lines)
    early = float(config.chronological_early_bonus_threshold)
    penalty_thr = float(config.chronological_penalty_threshold)
    bonus_quot = java_div(config.chronological_max_early_bonus - 1.5, early)
    middle_quot = java_div(0.5, penalty_thr - early)
    with np.errstate(invalid="ignore"):
        chrono = np.where(
            pos <= early,
            1.5 + (early - pos) * bonus_quot,
            np.where(
                pos <= penalty_thr,
                1.0 + (penalty_thr - pos) * middle_quot,
                0.5 + (1.0 - pos),
            ),
        )

    # ---- proximity (ScoringService.java:161-190) --------------------------
    # short loop over the padded secondary axis preserves declaration-order
    # accumulation; distances are exact ints from the device
    prox_total = np.zeros(m, dtype=np.float64)
    if tables.s_max:
        sec_idx = tables.pat_sec[pat]  # [M, S_max]
        decay = np.float64(config.proximity_decay_constant)
        with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
            for j in range(tables.s_max):
                e = sec_idx[:, j]
                es = np.maximum(e, 0)
                d_int = recs.sec_dist[:m, j].astype(np.int64)
                found = (e >= 0) & (d_int < NO_HIT) & (d_int <= tables.sec_window[es])
                # Math.exp(-d / decay) in f64; decay 0 → -inf → exp → 0.0,
                # exactly Java's double semantics
                contrib = tables.sec_weight[es] * np.exp(
                    -d_int.astype(np.float64) / decay
                )
                prox_total += np.where(found, contrib, 0.0)
    prox = 1.0 + prox_total

    # ---- temporal (ScoringService.java:199-220) ---------------------------
    temp_total = np.zeros(m, dtype=np.float64)
    if tables.q_max:
        q_idx = tables.pat_seq[pat]  # [M, Q_max]
        for j in range(tables.q_max):
            q = q_idx[:, j]
            live = q >= 0
            bonus = tables.seq_bonus[np.maximum(q, 0)]
            temp_total += np.where(live & recs.seq_ok[:m, j], bonus, 0.0)
    temp = 1.0 + temp_total

    # ---- context (ContextAnalysisService.java:46-117) ---------------------
    err = recs.ctx_counts[:m, 0].astype(np.float64)
    warn = recs.ctx_counts[:m, 1].astype(np.float64)  # already err-shadowed
    stack = recs.ctx_counts[:m, 2].astype(np.float64)
    exc = recs.ctx_counts[:m, 3].astype(np.float64)
    total = recs.ctx_counts[:m, 4].astype(np.float64)
    ctx_score = (
        ERROR_WEIGHT * err + WARN_WEIGHT * warn + STACK_WEIGHT * stack
        + EXCEPTION_WEIGHT * exc
    )
    ctx_score += np.where(
        stack > 0, np.minimum(STACK_WEIGHT * stack, STACK_BONUS_CAP), 0.0
    )
    dense = (total > DENSITY_MIN_LINES) & ((stack + err) > total * DENSITY_RATIO)
    ctx_score = np.where(dense, ctx_score * DENSITY_PENALTY, ctx_score)
    ctx = np.minimum(1.0 + ctx_score, float(config.context_max_context_factor))

    # ---- frequency (FrequencyTrackingService.java:64-93, read-before-record
    # order of ScoringService.java:84-88) -----------------------------------
    slots = bank.freq_slot[pat].astype(np.int64)  # -1 = untracked
    prior = _slot_cumcount(slots)
    safe = np.maximum(slots, 0)
    hours = float(config.frequency_time_window_hours)
    if hours == 0.0:
        # zero window: every record expires instantly, windowed count is 0
        count_before = np.zeros(m, dtype=np.float64)
    else:
        count_before = freq_base[safe] + prior.astype(np.float64)
    thr = float(config.frequency_threshold)
    with np.errstate(divide="ignore", invalid="ignore"):
        rate = count_before / hours  # IEEE /0 → inf/nan, like Java
        raw = np.minimum(float(config.frequency_max_penalty), (rate - thr) / thr)
    penalty = np.where(rate <= thr, 0.0, raw)
    never_tracked = ~freq_exists[safe] & (prior == 0)
    penalty = np.where(never_tracked, 0.0, penalty)
    penalty = np.where(slots >= 0, penalty, 0.0)

    scores = conf * sev * chrono * prox * temp * ctx * (1.0 - penalty)

    n_slots = max(1, bank.n_freq_slots)
    tracked = slots >= 0
    slot_batch_counts = np.bincount(slots[tracked], minlength=n_slots).astype(np.int64)

    return FinalizedBatch(
        scores=scores,
        line=recs.line[:m],
        pattern=recs.pattern[:m],
        slot_batch_counts=slot_batch_counts,
        chronological=chrono,
        proximity=prox,
        temporal=temp,
        context=ctx,
        frequency_penalty=np.asarray(penalty, dtype=np.float64),
    )
