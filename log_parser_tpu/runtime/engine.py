"""AnalysisEngine — the TPU-backed replacement for the reference's
``AnalysisService.analyze`` (AnalysisService.java:50-122).

Pipeline per request:

1. ingest: fused Java-split + padded uint8 encode (native C++ scan when the
   extension is built, vectorized numpy otherwise) with lazy line
   materialization — AnalysisService.java:53 semantics without a million
   host string objects;
2. ONE fused device program: DFA-bank automaton execution over the line
   batch + integer factor-component extraction, compacted to K-capped
   match records (ops/fused.py). Host ``re`` verification only for
   device-inexact lines (non-ASCII / over-long) and automaton-unsupported
   regexes, injected as a cube override;
3. host finalizer: exact f64 seven-factor scores from the integer records
   (runtime/finalize.py) — better-than-device-f64 parity at O(matches)
   cost;
4. assemble ``AnalysisResult`` in discovery order (line-major, then
   pattern order — AnalysisService.java:89-113) with the same
   metadata/summary quirks as the reference.

Frequency state is the engine's only mutable state, mirrored from the
reference's ConcurrentHashMap (FrequencyTrackingService.java:25) but read
at batch granularity with exact per-match ordering recovered from the
record stream (read-before-record, ScoringService.java:84-88).
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
import uuid
from collections import deque
from typing import Callable

import numpy as np

from log_parser_tpu import _clock as pclock
from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden.engine import (
    GoldenFrequencyTracker,
    build_metadata,
    build_summary,
    extract_context,
)
from log_parser_tpu.models.analysis import AnalysisResult, MatchedEvent
from log_parser_tpu.models.pattern import PatternSet
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.native.ingest import Corpus
from log_parser_tpu.obs import Obs
from log_parser_tpu.ops.encode import _pad_rows
from log_parser_tpu.ops.fused import FusedMatchScore, FusedStaticTables
from log_parser_tpu.runtime import faults
from log_parser_tpu.runtime.linecache import (
    DEFAULT_LINE_CACHE_MB,
    KeyInterner,
    LineCache,
    dedup_slots,
    line_key,
    records_from_bits,
)
from log_parser_tpu.ops.match import DfaBank, MatcherBanks
from log_parser_tpu.patterns.bank import PatternBank
from log_parser_tpu.runtime.finalize import FinalizedBatch, finalize_batch
from log_parser_tpu.runtime.quarantine import (
    DEFAULT_BREAKER_COOLDOWN_S,
    DEFAULT_CAPACITY,
    DEFAULT_STRIKES,
    DEFAULT_TTL_S,
    PatternBreakerBoard,
    QuarantineTable,
    fingerprint as quarantine_fingerprint,
)
from log_parser_tpu.utils.trace import PhaseTrace

# Substrings identifying plain RuntimeErrors raised by the device layer
# *before* jit execution starts (jax raises these from xla_bridge /
# PJRT client setup, not as JaxRuntimeError).
_DEVICE_ERROR_MARKERS = (
    "Unable to initialize backend",
    "failed to initialize",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "RESOURCE_EXHAUSTED",
    "Device or resource busy",
)


def _raised_in_device_layer(exc: BaseException) -> bool:
    """True when any traceback frame of ``exc`` (or of an exception in its
    cause/context chain) belongs to a jax/jaxlib module — i.e. the error
    genuinely originated in the device stack, not in engine code that
    happens to quote device-sounding text.

    The cause/context chain matters: jax's default traceback filtering
    (``jax_traceback_filtering='auto'``) strips jax-internal frames from
    the primary traceback and re-parents the unfiltered exception via
    ``__cause__``/``__context__`` — inspecting only ``__traceback__``
    would misclassify genuine device errors as logic bugs."""
    seen: set[int] = set()
    current: BaseException | None = exc
    while current is not None and id(current) not in seen:
        seen.add(id(current))
        tb = current.__traceback__
        while tb is not None:
            mod = tb.tb_frame.f_globals.get("__name__", "")
            if mod == "jax" or mod.startswith(("jax.", "jaxlib")):
                return True
            tb = tb.tb_next
        current = current.__cause__ or current.__context__
    return False


def is_device_error(exc: BaseException) -> bool:
    """True only for failures of the device/XLA layer itself — the class of
    error the golden fallback exists for (SURVEY.md §5.3). Logic bugs
    (TypeError in assembly, bad config, ...) must propagate: serving them
    from the host path would hide the bug and, for large batches, convert a
    fast failure into a multi-minute pure-Python crawl (the round-1
    BENCH_r01 rc=124 failure mode).

    A plain RuntimeError counts only when BOTH a known device-layer marker
    appears in its message AND the exception was raised from a jax/jaxlib
    frame — a non-device RuntimeError that merely quotes such text (e.g. a
    log line or downstream response embedded in the message) propagates
    (ADVICE.md r2)."""
    import jax.errors

    if isinstance(exc, DeviceHungError):
        return True
    if isinstance(exc, faults.InjectedDeviceFault):
        # injected device-layer chaos reacts exactly like a dead backend;
        # faults injected elsewhere (ingest/finalize/transport) are plain
        # InjectedFault and take the propagate-to-500 path of a logic bug
        return True
    if isinstance(exc, jax.errors.JaxRuntimeError):
        return True
    if isinstance(exc, RuntimeError):
        msg = str(exc)
        return any(marker in msg for marker in _DEVICE_ERROR_MARKERS) and (
            _raised_in_device_layer(exc)
        )
    return False


class DeviceHungError(RuntimeError):
    """The device step exceeded the watchdog timeout (or the breaker is
    open from a previous hang). Classified as a device error so the
    golden fallback serves the request.

    ``pre_run`` distinguishes the circuit-open short-circuit (this
    request's step never entered the device — it proves nothing about
    the request) from an actual timeout: only the latter counts as a
    quarantine strike, and the batcher skips bisecting the former (the
    sub-batches would short-circuit identically)."""

    pre_run = False


class DeviceWatchdog:
    """Hang protection for the device step (SURVEY.md §5.3).

    A *crashing* backend raises and the golden fallback already serves
    the request; a *wedged* backend (dead tunnel, stuck runtime — e.g.
    the axon relay dying mid-session) just never returns, hanging every
    request. With a timeout configured
    (``LOG_PARSER_TPU_DEVICE_TIMEOUT_S`` or ``--device-timeout``), the
    device step runs in a worker thread: on timeout the request raises
    :class:`DeviceHungError` (→ golden fallback) and the circuit opens,
    so subsequent requests fall back IMMEDIATELY instead of entering
    the wedged backend. Abandoned workers keep waiting; the circuit
    closes when the LAST outstanding worker responds (a smaller
    request completing while another is still stuck must not re-open
    the front door), and any late error is logged so the root cause of
    the wedge reaches the operator.

    Half-open recovery: waiting for the last outstanding worker alone
    would leave the circuit stuck open forever when a worker NEVER
    responds (a truly lost backend thread). After ``cooldown_s``
    (default: the timeout itself; ``LOG_PARSER_TPU_BREAKER_COOLDOWN_S``
    overrides) the breaker goes half-open: exactly one trial request is
    admitted to the device path. Success closes the circuit even with
    abandoned workers still pending; a timeout or error re-arms the
    cool-down and the circuit stays open.

    Default OFF (0): a first request legitimately spends tens of
    seconds in XLA compilation, and only an operator knows a deadline
    that separates that from a wedge. Hung worker threads cannot be
    cancelled (XLA holds the wait with the GIL released) — they leak
    until the backend responds, bounded by the number of requests
    already in flight when the wedge began; once the circuit is open
    no new ones are created.
    """

    def __init__(self, timeout_s: float, cooldown_s: float | None = None):
        self.timeout_s = timeout_s
        if cooldown_s is None:
            cooldown_s = float(
                os.environ.get("LOG_PARSER_TPU_BREAKER_COOLDOWN_S", "0")
            ) or timeout_s
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._open = False
        self._opened_at = 0.0
        self._probing = False  # at most one half-open trial at a time
        self._inflight = 0

    @property
    def circuit_open(self) -> bool:
        with self._lock:
            return self._open

    def run(self, fn):
        if self.timeout_s <= 0:
            return fn()
        probe = False
        with self._lock:
            if self._open:
                if (
                    self.cooldown_s > 0
                    and not self._probing
                    and pclock.mono() - self._opened_at >= self.cooldown_s
                ):
                    # half-open: this request is the single recovery trial
                    self._probing = True
                    probe = True
                else:
                    exc = DeviceHungError(
                        "device backend still hung from a previous timeout "
                        "(circuit open); serving from the host path"
                    )
                    exc.pre_run = True
                    raise exc
            self._inflight += 1
        result: list = []
        error: list = []
        done = threading.Event()
        finished = [False]  # worker bookkeeping ran (under self._lock)
        abandoned = [False]  # caller gave up on this worker

        def worker() -> None:
            try:
                result.append(fn())
            except BaseException as exc:  # noqa: BLE001 - re-raised below
                error.append(exc)
            finally:
                with self._lock:
                    finished[0] = True
                    self._inflight -= 1
                    if self._inflight == 0:
                        # every outstanding worker has been answered:
                        # the backend is responsive again
                        self._open = False
                    late = abandoned[0]
                done.set()
                if late and error:
                    import logging

                    logging.getLogger(__name__).error(
                        "Abandoned device step eventually failed "
                        "(the wedge's root cause): %r",
                        error[0],
                        exc_info=error[0],
                    )

        threading.Thread(
            target=worker, name="device-watchdog", daemon=True
        ).start()
        if not done.wait(self.timeout_s):
            with self._lock:
                if not finished[0]:
                    # genuinely still stuck: trip the breaker. A worker
                    # that completed in the wait/lock gap falls through
                    # and is harvested below instead (its finally can no
                    # longer be un-done by this set).
                    abandoned[0] = True
                    self._open = True
                    self._opened_at = pclock.mono()
                    if probe:
                        # failed trial: re-arm the cool-down, next probe
                        # waits a full period again
                        self._probing = False
                    raise DeviceHungError(
                        f"device step exceeded {self.timeout_s:g}s; "
                        "serving from the host path until the backend "
                        "responds"
                    )
            done.wait()  # finished[0] is True: done.set() is imminent
        if probe:
            with self._lock:
                self._probing = False
                if error:
                    # the backend RESPONDED (not wedged) but with an error:
                    # don't close on an error — re-arm the cool-down and
                    # let the inflight==0 bookkeeping decide as before
                    self._opened_at = pclock.mono()
                else:
                    # trial succeeded: the backend serves again. Close even
                    # with abandoned workers still pending — the stuck-open
                    # fix this probe exists for.
                    self._open = False
        if error:
            raise error[0]
        return result[0]


class KernelTierStats:
    """Counters for the Pallas union-DFA kernel tier (GET /trace/last
    ``kernel`` block). One note per device dispatch — engine direct path,
    line-cache residual cubes, and the micro-batcher's vmapped batches
    all report here — so operators can see whether traffic actually
    rides the kernel and why not when it doesn't (REASONS codes,
    ops/matchdfa_pallas.py)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.enabled = False
        self.reason = "off"
        self.geometry: dict | None = None
        self.kernel_batches = 0
        self.kernel_rows = 0
        self.xla_batches = 0

    def note(
        self,
        rows: int,
        active: bool,
        enabled: bool,
        reason: str,
        geometry: dict | None = None,
    ):
        with self._lock:
            self.enabled = enabled
            self.reason = reason
            self.geometry = geometry
            if not enabled:
                return
            if active:
                self.kernel_batches += 1
                self.kernel_rows += rows
            else:
                self.xla_batches += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "reason": self.reason,
                "geometry": self.geometry,
                "kernelBatches": self.kernel_batches,
                "kernelRows": self.kernel_rows,
                "xlaBatches": self.xla_batches,
            }


_NULL_LOCK = contextlib.nullcontext()


class _Prepared:
    """One request's prepare-phase outputs, handed to the finish phase.
    ``data`` rides along so the finish phase can hand the original
    request to the shadow verifier."""

    __slots__ = ("start", "trace", "corpus", "recs", "data")

    def __init__(self, start, trace, corpus, recs, data=None):
        self.start = start
        self.trace = trace
        self.corpus = corpus
        self.recs = recs
        self.data = data


class AnalysisEngine:
    """Immutable compiled library + one fused device program + frequency state."""

    def __init__(
        self,
        pattern_sets: list[PatternSet],
        config: ScoringConfig | None = None,
        clock: Callable[[], float] = pclock.mono,
    ):
        self.config = config or ScoringConfig()
        # warm restarts must not re-pay multi-second XLA compiles
        from log_parser_tpu.utils.xlacache import enable_persistent_cache

        enable_persistent_cache()
        self.bank = PatternBank(pattern_sets)
        self.frequency = GoldenFrequencyTracker(self.config, clock=clock)

        self._host_cols = [
            i
            for i, c in enumerate(self.bank.columns)
            if c.dfa is None and c.exact_seqs is None
        ]
        self._device_cols = [
            i
            for i, c in enumerate(self.bank.columns)
            if c.dfa is not None or c.exact_seqs is not None
        ]
        # Host-column literal prefilter (VERDICT r3 #3): host-only
        # columns with required literals (lenient extraction,
        # bank._intern_column) get an AC pass over the device-encoded
        # bytes; only candidate lines pay host re. Literal-free host
        # columns keep the full per-request scan (warned at load).
        self._host_pref_cols: list[int] = []
        self._host_slow_cols: list[int] = []
        self._host_prefilter = None
        if self._host_cols:
            from log_parser_tpu.patterns.regex.ac import AhoCorasick

            lits: list[bytes] = []
            groups: list[int] = []
            for ci in self._host_cols:
                col = self.bank.columns[ci]
                if col.literals:
                    gi = len(self._host_pref_cols)
                    self._host_pref_cols.append(ci)
                    for lit in col.literals:
                        lits.append(lit.fold().text)
                        groups.append(gi)
                else:
                    self._host_slow_cols.append(ci)
            if self._host_pref_cols:
                self._host_prefilter = AhoCorasick.build_cached(lits, groups)
        # static per-pattern index tables (numpy, cheap); the full-bank
        # device programs below are built lazily — subclasses that override
        # _run_device (pattern sharding) never pay for them
        self.tables = FusedStaticTables(self.bank, self.config)
        self._matchers: MatcherBanks | None = None
        self._fused: FusedMatchScore | None = None
        # two concurrent _prepare calls (analyze_pipelined) must not both
        # build the lazy device programs — one multi-second compile each.
        # RLock: building `fused` takes the lock and then touches the
        # `matchers` property, which takes it again on the same thread
        self._init_lock = threading.RLock()
        self._golden = None
        # cheap insurance: a request whose device batch dies is re-served
        # from the golden host path (SURVEY.md §5.3). Disabled in the test
        # suite so device bugs can never hide behind the fallback.
        self.fallback_to_golden = (
            os.environ.get("LOG_PARSER_TPU_NO_FALLBACK") != "1"
        )
        # hang protection for a wedged (not crashing) backend — §5.3;
        # 0 disables (default: first-request XLA compiles are legitimate
        # long waits only the operator can bound)
        self.watchdog = DeviceWatchdog(
            float(os.environ.get("LOG_PARSER_TPU_DEVICE_TIMEOUT_S", "0"))
        )
        self._k_hint = 0  # previous request's match count → starting K bucket
        self._approx_pat_mask = None  # lazy — see _approx_patterns
        self._approx_sec = None  # lazy — see _approx_secondaries
        self._approx_token: tuple | None = None  # matcher identities the caches derive from
        # serializes frequency-coupled state (finish phase, admin routes,
        # golden fallback) across transports; the prepare phase (ingest +
        # device) deliberately runs OUTSIDE it — see analyze_pipelined
        self.state_lock = threading.Lock()
        # quiescence gate for hot pattern reload (runtime/reload.py):
        # every request enters _request_scope; apply_library waits for
        # active==0 and blocks NEW admissions while swapping, so in-flight
        # (and already-enqueued batched) requests finish on the old banks
        # and the next admission sees the new ones
        self._quiesce_cv = threading.Condition()
        self._active_requests = 0
        self._swap_pending = False
        self._scope_local = threading.local()
        # durable frequency state (runtime/journal.py) — None until
        # attach_journal(); reload bookkeeping for /trace/last
        self.journal = None
        self.reload_epoch = 0
        self.reload_count = 0
        self.reload_failures = 0
        self.last_reload_error: str | None = None
        # lint summary of the most recent reload attempt's candidate
        # library (runtime/reload.py lint_stage) — /trace/last "lint"
        self.last_lint: dict | None = None
        # observability (SURVEY.md §5.1/§5.5): per-phase timers and the full
        # factor breakdown of the most recent request
        self.last_trace: PhaseTrace | None = None
        self.trace_history: deque[PhaseTrace] = deque(maxlen=512)
        self.last_finalized: FinalizedBatch | None = None
        # observability plane (log_parser_tpu/obs): metrics registry +
        # request-trace ring + SLO tracker + profiler, rooted here so
        # every transport reaches one bundle through the engine it
        # already holds. Tenant engines REPLACE this with the primary's
        # bundle (runtime/tenancy.py) under their own tenant label.
        self.obs = Obs()
        self.obs_tenant = "default"
        self.obs.add_engine_collector(self)
        # how many requests this engine served from the golden host path
        # because the device layer failed (surfaced via GET /trace/last)
        self.fallback_count = 0
        # Pallas union-DFA kernel tier accounting (GET /trace/last)
        self.kernel_stats = KernelTierStats()
        # XLA cost-analysis cache for device-utilization accounting:
        # (rows, width) -> {"flops","bytes"} | None, filled by a
        # background lowering so the serving path never stalls on it
        self._cost_cache: dict[tuple, dict | None] = {}
        self._cost_lock = threading.Lock()
        # ... and how many were ROUTED there deliberately by admission
        # pressure (serve/admission.py ladder rung 2) — a separate counter,
        # because pressure routing is policy, not failure
        self.host_routed_count = 0
        # cross-request micro-batching scheduler (runtime/batcher.py);
        # None until enable_batching() — transports then route analyze
        # calls through analyze_batched
        self.batcher = None
        # exact-match line cache (runtime/linecache.py): None until
        # enable_line_cache() — repeat lines then skip the match cube
        self.line_cache = None
        self.key_interner = None
        # poison-request quarantine (runtime/quarantine.py): organic
        # device failures strike the request's fingerprint; at the
        # threshold repeats route straight to golden until TTL expiry
        self.quarantine = QuarantineTable(
            strikes=int(
                os.environ.get(
                    "LOG_PARSER_TPU_QUARANTINE_STRIKES", str(DEFAULT_STRIKES)
                )
            ),
            ttl_s=float(
                os.environ.get(
                    "LOG_PARSER_TPU_QUARANTINE_TTL_S", str(DEFAULT_TTL_S)
                )
            ),
            capacity=int(
                os.environ.get(
                    "LOG_PARSER_TPU_QUARANTINE_CAPACITY", str(DEFAULT_CAPACITY)
                )
            ),
            clock=clock,
        )
        # per-pattern circuit breakers tripped by shadow divergence: an
        # open breaker serves ONLY that pattern's columns from the exact
        # host regex (see _overrides) instead of degrading the engine
        self.breakers = PatternBreakerBoard(
            cooldown_s=float(
                os.environ.get(
                    "LOG_PARSER_TPU_PATTERN_BREAKER_COOLDOWN_S",
                    str(DEFAULT_BREAKER_COOLDOWN_S),
                )
            ),
            clock=clock,
        )
        self._breaker_map: dict[str, set[int]] | None = None
        self._breaker_map_bank = None
        # online shadow verification (ShadowVerifier below): sample
        # --shadow-rate of served requests, re-run on golden off the hot
        # path, compare scores at 1e-9; None until enable_shadow()
        self.shadow = None
        shadow_rate = float(os.environ.get("LOG_PARSER_TPU_SHADOW_RATE", "0") or 0)
        if shadow_rate > 0:
            self.enable_shadow(shadow_rate)
        # template miner (mining/): background consumer of the line-cache
        # miss stream; None until enable_miner()
        self.miner = None
        # chaos: pick up LOG_PARSER_TPU_FAULTS once per process (no-op
        # when unset or when a test installed a registry explicitly)
        faults.ensure_env()

    @property
    def skipped_patterns(self) -> list[tuple[str, str]]:
        return self.bank.skipped_patterns

    @property
    def matchers(self) -> MatcherBanks:
        if self._matchers is None:
            with self._init_lock:
                if self._matchers is None:
                    self._matchers = MatcherBanks(self.bank)
        return self._matchers

    @property
    def dfa_bank(self) -> DfaBank:
        return self.matchers.dfa_bank

    @property
    def fused(self) -> FusedMatchScore:
        if self._fused is None:
            with self._init_lock:
                if self._fused is None:
                    self._fused = FusedMatchScore(
                        self.bank, self.config, self.matchers
                    )
        return self._fused

    # -------------------------------------------------------------- overrides

    def _overrides(self, corpus: Corpus) -> tuple[np.ndarray, np.ndarray] | None:
        """Cube corrections the automaton path can't make itself: columns
        with no DFA (host regex over every line) and lines flagged
        device-inexact (non-ASCII bytes, over-long). None when the batch is
        fully device-exact — the common case, which then skips the
        override transfer entirely."""
        enc = corpus.encoded
        host_lines = np.flatnonzero(enc.needs_host[: corpus.n_lines])
        breaker_cols = self._breaker_columns()
        if not self._host_cols and not breaker_cols and len(host_lines) == 0:
            return None
        B = enc.u8.shape[0]
        n = corpus.n_lines
        mask = np.zeros((B, self.bank.n_columns), dtype=bool)
        val = np.zeros((B, self.bank.n_columns), dtype=bool)
        if self._host_cols:
            mask[:, self._host_cols] = True
            if self._host_slow_cols:
                # literal-free host columns: every line pays host re
                hosts = [
                    (c, self.bank.columns[c].host)
                    for c in self._host_slow_cols
                ]
                for i, line in enumerate(corpus.materialize()):
                    for col, host in hosts:
                        val[i, col] = bool(host.search(line))
            if self._host_pref_cols:
                # candidate lines only: AC over the folded device bytes
                # (required literals, so no true match escapes), plus
                # every needs_host line — truncated/non-ASCII encodings
                # can hide a literal from the device-side scan
                from log_parser_tpu.patterns.regex.ac import fold_lines_u8

                hits = self._host_prefilter.scan_lines(
                    fold_lines_u8(enc.u8[:n]), enc.lengths[:n]
                )
                cand_cols: list[np.ndarray] = []
                for gi in range(len(self._host_pref_cols)):
                    cand = ((hits[:, gi // 32] >> np.uint32(gi % 32)) & 1).astype(bool)
                    cand[host_lines] = True
                    cand_cols.append(np.flatnonzero(cand))
                needed = set()
                for cand in cand_cols:
                    needed.update(cand.tolist())
                text = {i: corpus.line(int(i)) for i in needed}
                for ci, cand in zip(self._host_pref_cols, cand_cols):
                    host = self.bank.columns[ci].host
                    for i in cand:
                        val[i, ci] = bool(host.search(text[int(i)]))
        if breaker_cols:
            # per-pattern breaker containment: an OPEN breaker's columns
            # are served from the exact host regex on every line — host
            # truth is exact, so a column shared with a healthy pattern
            # is corrected, never corrupted
            mask[:, breaker_cols] = True
            for i, line in enumerate(corpus.materialize()):
                for col in breaker_cols:
                    val[i, col] = bool(self.bank.columns[col].host.search(line))
        for i in host_lines:
            line = corpus.line(int(i))
            for col in self._device_cols:
                mask[i, col] = True
                val[i, col] = bool(self.bank.columns[col].host.search(line))
        return mask, val

    def _breaker_columns(self) -> list[int]:
        """Engine-bank columns of every pattern whose shadow breaker is
        currently OPEN (primary + secondary + sequence-event roles) —
        the override set that serves just those patterns from host truth.
        Empty in the steady state, so the common path costs one set
        check."""
        board = self.breakers
        if board is None:
            return []
        pids = board.overridden_patterns()
        if not pids:
            return []
        if self._breaker_map is None or self._breaker_map_bank is not self.bank:
            by_id: dict[str, set[int]] = {}
            for p, pat in enumerate(self.bank.patterns):
                by_id.setdefault(pat.id, set()).add(
                    int(self.bank.primary_columns[p])
                )
            for e in self.bank.secondaries:
                by_id.setdefault(
                    self.bank.patterns[e.pattern_idx].id, set()
                ).add(int(e.column))
            for s in self.bank.sequences:
                by_id.setdefault(
                    self.bank.patterns[s.pattern_idx].id, set()
                ).update(int(c) for c in s.event_columns)
            self._breaker_map = by_id
            self._breaker_map_bank = self.bank
        cols: set[int] = set()
        for pid in pids:
            cols.update(self._breaker_map.get(pid, ()))
        # columns with no DFA are already host-evaluated unconditionally
        cols.difference_update(self._host_cols)
        return sorted(cols)

    # ----------------------------------------------------- device-step hooks
    # ShardedEngine overrides these two to swap in the shard_map program;
    # everything else in analyze() is shared.

    def _approx_sources_token(self) -> tuple:
        """The matcher objects the approx caches derive from, compared by
        IDENTITY — overridden by engines with several device programs."""
        return (self.matchers,)

    def _check_approx_caches(self) -> None:
        """Drop the lazily-built approx caches whenever the matcher tier
        assignment they were computed from is replaced (ADVICE r4: tests
        swap ``self._matchers``; a stale cache would skip the host
        re-verification of truncated columns)."""
        token = self._approx_sources_token()
        prev = self._approx_token
        if (
            prev is None
            or len(prev) != len(token)
            or any(a is not b for a, b in zip(prev, token))
        ):
            self._approx_pat_mask = None
            self._approx_sec = None
            self._approx_token = token

    def _approx_patterns(self) -> np.ndarray:
        """bool [n_patterns]: patterns whose device-side primary column
        OVER-matches (truncated >31-position bitglush alternatives —
        ops/match.py approx_cols) and whose flagged events must be
        re-verified with the exact host regex before they count."""
        self._check_approx_caches()
        if self._approx_pat_mask is None:
            mask = np.zeros(max(1, self.bank.n_patterns), dtype=bool)
            for cols, bank, offset in self._approx_col_sources():
                if not cols:
                    continue
                cset = set(cols)
                for p in range(bank.n_patterns):
                    if int(bank.primary_columns[p]) in cset:
                        mask[offset + p] = True
            self._approx_pat_mask = mask
        return self._approx_pat_mask

    def _approx_col_sources(self):
        """(approx_cols, bank, global pattern offset) triples —
        overridden by engines whose device programs run on different
        banks (pattern sharding)."""
        return [(getattr(self.matchers, "approx_cols", []), self.bank, 0)]

    def _approx_global_cols(self) -> set:
        """Engine-bank column indexes whose device tier over-matches, in
        GLOBAL column coordinates — overridden by pattern sharding to
        translate block-local indexes."""
        return set(getattr(self.matchers, "approx_cols", []))

    def _approx_secondaries(self):
        """[(pattern_idx, slot, column, effective_window)] — secondary
        entries whose column may over-match on device, and whose record
        distances therefore need the exact host repair. Slot order
        mirrors FusedStaticTables.pat_sec (declaration order within the
        pattern). Conservative across sharded engines: an entry whose
        column is exact in the block that ran it still repairs cleanly
        (the claimed line verifies and the distance stands)."""
        self._check_approx_caches()
        if self._approx_sec is None:
            cols = self._approx_global_cols()
            out = []
            if cols:
                slot_of: dict[int, int] = {}
                for e in self.bank.secondaries:
                    j = slot_of.get(e.pattern_idx, 0)
                    slot_of[e.pattern_idx] = j + 1
                    if e.column in cols:
                        out.append(
                            (
                                e.pattern_idx,
                                j,
                                e.column,
                                min(
                                    self.config.proximity_max_window,
                                    e.window,
                                ),
                            )
                        )
            self._approx_sec = out
        return self._approx_sec

    def _verify_approx(self, corpus: Corpus, recs):
        """Exact host repair for approximate (truncated) device columns.
        Runs in ``_prepare`` — OUTSIDE the serialization lock — and
        before the frequency read, so counts, scores, ordering, and
        assembly all see exactly the reference's match/factor set
        (AnalysisService.java:93-95, ScoringService.java:315-347).

        Stage 1 (primary roles): drop records whose approximate primary
        column flagged a line the exact host regex rejects.
        Stage 2 (secondary roles): a truncated secondary only feeds the
        proximity distances. The device min-distance d names at most two
        lines (record line ± d); if either truly matches, d is exact
        (true hits are a subset of device hits, so the true minimum is
        never smaller). Otherwise both were prefix-only false positives
        and the true distance is recovered by an outward host scan
        bounded by the entry's effective window (beyond it the factor is
        zero either way)."""
        import dataclasses

        from log_parser_tpu.ops.fused import NO_HIT

        m = recs.n_matches
        if m == 0:
            return recs
        mask = self._approx_patterns()
        if mask.any():
            pat = recs.pattern[:m].astype(np.int64)
            cand = np.nonzero(mask[pat])[0]
            keep = np.ones(m, dtype=bool)
            for i in cand:
                col = self.bank.columns[
                    int(self.bank.primary_columns[int(pat[i])])
                ]
                keep[i] = (
                    col.host.search(corpus.line(int(recs.line[i])))
                    is not None
                )
            if not keep.all():
                m = int(keep.sum())
                recs = dataclasses.replace(
                    recs,
                    n_matches=m,
                    line=recs.line[: len(keep)][keep],
                    pattern=recs.pattern[: len(keep)][keep],
                    sec_dist=recs.sec_dist[: len(keep)][keep],
                    seq_ok=recs.seq_ok[: len(keep)][keep],
                    ctx_counts=recs.ctx_counts[: len(keep)][keep],
                )

        sec_entries = self._approx_secondaries()
        if not sec_entries or m == 0:
            return recs
        by_pattern: dict[int, list] = {}
        for p, j, col, w in sec_entries:
            by_pattern.setdefault(p, []).append((j, col, w))
        pat = recs.pattern[:m]
        approx_mask = np.zeros(max(1, self.bank.n_patterns), dtype=bool)
        approx_mask[list(by_pattern)] = True
        rows = np.flatnonzero(approx_mask[pat.astype(np.int64)])
        if rows.size == 0:
            return recs
        n = corpus.n_lines
        sec_dist = None  # copy-on-write
        for i in rows:
            line = int(recs.line[i])
            for j, col, w in by_pattern[int(pat[i])]:
                d = int(recs.sec_dist[i, j] if sec_dist is None else sec_dist[i, j])
                if d >= NO_HIT or d > w:
                    continue  # out of window: zero factor either way
                host = self.bank.columns[col].host
                if (
                    line - d >= 0
                    and host.search(corpus.line(line - d)) is not None
                ) or (
                    line + d < n
                    and host.search(corpus.line(line + d)) is not None
                ):
                    continue  # the claimed distance is exact
                if sec_dist is None:
                    sec_dist = recs.sec_dist[:m].copy()
                nd = NO_HIT
                for k in range(d + 1, w + 1):
                    if (
                        line - k >= 0
                        and host.search(corpus.line(line - k)) is not None
                    ) or (
                        line + k < n
                        and host.search(corpus.line(line + k)) is not None
                    ):
                        nd = k
                        break
                sec_dist[i, j] = nd
        if sec_dist is None:
            return recs
        return dataclasses.replace(
            recs,
            sec_dist=np.concatenate([sec_dist, recs.sec_dist[m:]], axis=0)
            if recs.sec_dist.shape[0] > m
            else sec_dist,
        )

    def _corpus_min_rows(self) -> int:
        return 8

    def _note_kernel_dispatch(self, batch_rows: int, width: int | None = None,
                              n_rows: int | None = None,
                              batch_slots: int | None = None,
                              dummy_slots: int | None = None) -> dict | None:
        """Kernel-tier + device-utilization accounting for one device
        dispatch: did the union groups ride the Pallas kernel for this
        cube batch size, and what did the dispatch cost (padded rows,
        dummy-slot waste, transition-plane bytes, cost-analysis FLOPs) —
        folded into the per-tenant ``logparser_device_*`` families so
        roofline math is a scrape, not a bench run. A fault fallback
        flips the matchers' reason to "fault" at trace time, so the
        batch lands in xlaBatches. Returns the dispatch attributes the
        span store records (``dispatch`` span vocabulary, obs/spans.py),
        or None pre-boot."""
        m = self._matchers
        if m is None:
            return None
        enabled = m.multidfa_use_pallas
        active = (
            enabled
            and m.multidfa_pallas_reason not in ("fault", "no_tile")
            and m.dfa_kernel_active(batch_rows)
        )
        geometry = m.dfa_kernel_geometry
        self.kernel_stats.note(
            batch_rows,
            active,
            enabled,
            m.multidfa_pallas_reason,
            geometry,
        )
        tier = "kernel" if active else "xla"
        attrs: dict = {"tier": tier, "rows": batch_rows,
                       "kernelReason": m.multidfa_pallas_reason}
        if width is not None:
            attrs["width"] = width
        slots = batch_slots or 1
        dummies = dummy_slots or 0
        padded_rows = batch_rows * slots
        dummy_rows = batch_rows * dummies
        if batch_slots is not None:
            attrs["batchSlots"] = slots
            attrs["dummySlots"] = dummies
            waste = dummies / slots if slots else 0.0
        elif n_rows is not None and batch_rows:
            # unbatched: the waste is the row padding past the real lines
            waste = (batch_rows - n_rows) / batch_rows
        else:
            waste = None
        if n_rows is not None:
            attrs["lines"] = n_rows
        if waste is not None:
            attrs["wasteRatio"] = round(waste, 4)
        if geometry:
            if geometry.get("planeBytes") is not None:
                attrs["planeBytes"] = geometry["planeBytes"]
            if geometry.get("vmemPerStep") is not None:
                attrs["vmemPerStep"] = geometry["vmemPerStep"]
        cost = self._dispatch_cost(batch_rows, width) if width else None
        flops = hbm = None
        if cost:
            flops = cost.get("flops")
            hbm = cost.get("bytes")
            if flops:
                attrs["flops"] = flops
            if hbm:
                attrs["hbmBytes"] = hbm
        self.obs.note_dispatch(
            self.obs_tenant, tier, padded_rows=padded_rows,
            dummy_rows=dummy_rows, waste=waste, flops=flops, hbm_bytes=hbm,
        )
        return attrs

    def _dispatch_cost(self, rows: int, width: int) -> dict | None:
        """``jax.jit(...).lower().cost_analysis()`` FLOPs/bytes for the
        cube step at one (rows, width) shape — computed ONCE per shape
        on a background thread (lowering costs hundreds of ms; the
        serving path must never pay it), then folded into every later
        dispatch of that shape. None while pending or when the backend
        exposes no cost model."""
        key = (int(rows), int(width))
        with self._cost_lock:
            if key in self._cost_cache:
                return self._cost_cache[key]
            self._cost_cache[key] = None  # pending marker

        def _lower():
            cost = None
            try:
                import jax.numpy as jnp

                lines = jnp.zeros(key, dtype=jnp.uint8)
                lens = jnp.zeros((key[0],), dtype=jnp.int32)
                n = jnp.asarray(key[0], dtype=jnp.int32)
                ca = self.fused._jit_cube_plain.lower(
                    lines, lens, n
                ).cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                cost = {
                    "flops": float(ca.get("flops", 0.0) or 0.0),
                    "bytes": float(ca.get("bytes accessed", 0.0) or 0.0),
                }
            except Exception:
                cost = None
            with self._cost_lock:
                self._cost_cache[key] = cost

        threading.Thread(
            target=_lower, name="dispatch-cost", daemon=True
        ).start()
        return None

    def _run_device(self, enc, n_lines: int, om, ov, trace=None):
        out = self.fused.run(
            enc.u8, enc.lengths, n_lines, om, ov, k_hint=self._k_hint
        )
        attrs = self._note_kernel_dispatch(
            enc.u8.shape[0], width=enc.u8.shape[1], n_rows=n_lines
        )
        if trace is not None and attrs:
            trace.span_attrs.update(attrs)
        return out

    def _run_cube(self, lines_u8, lengths, n_rows: int,
                  trace=None) -> np.ndarray:
        """Cube-only device program for the line-cache residual batch:
        pre-override match bits for ``n_rows`` independent lines (no
        extraction — that replays on the host from cached + fresh rows
        together, runtime/linecache.py)."""
        out = self.fused.cube_rows(lines_u8, lengths, n_rows)
        attrs = self._note_kernel_dispatch(
            lines_u8.shape[0], width=lines_u8.shape[1], n_rows=n_rows
        )
        if trace is not None and attrs:
            attrs = {**attrs, "residual": True}
            trace.span_attrs.update(attrs)
        return out

    # ------------------------------------------------------- golden fallback

    @property
    def golden_fallback(self):
        """Lazy golden (pure host) analyzer sharing this engine's frequency
        state — the insurance path when a device batch fails (SURVEY.md
        §5.3; the reference has no equivalent)."""
        if self._golden is None:
            from log_parser_tpu.golden.engine import GoldenAnalyzer

            self._golden = GoldenAnalyzer(self.bank.pattern_sets, self.config)
            self._golden.frequency = self.frequency
        return self._golden

    def _golden_serve(self, data: PodFailureData) -> AnalysisResult:
        """Run one request on the golden host path with the shared
        frequency tracker rolled back on ANY failure — golden records
        matches as it runs, and a request that dies partway through must
        not leak partial counts. Caller holds the lock (or is otherwise
        serialized)."""
        saved_freq = self.frequency._save_state()
        try:
            return self.golden_fallback.analyze(data)
        except Exception:
            self.frequency._load_state(saved_freq)
            raise

    # ------------------------------------------- durable state + hot reload

    @contextlib.contextmanager
    def _request_scope(self):
        """Count this thread as an active request for the duration.
        Re-entrant per thread (batched submit degrades to pipelined, which
        would otherwise self-deadlock against a pending swap); a pending
        :meth:`apply_library` blocks NEW top-level entries until the swap
        completes, and the swap waits until the count reaches zero."""
        local = self._scope_local
        if getattr(local, "depth", 0) > 0:
            local.depth += 1
            try:
                yield
            finally:
                local.depth -= 1
            return
        with self._quiesce_cv:
            while self._swap_pending:
                self._quiesce_cv.wait()
            self._active_requests += 1
        local.depth = 1
        try:
            yield
        finally:
            local.depth = 0
            with self._quiesce_cv:
                self._active_requests -= 1
                if self._active_requests == 0:
                    self._quiesce_cv.notify_all()

    def attach_journal(
        self,
        state_dir: str,
        *,
        fsync_ms: float = 50.0,
        snapshot_every: int = 512,
        wall=None,
    ):
        """Make frequency state durable: recover snapshot + journal tail
        from ``state_dir``, swap in a journaling tracker, start group-fsync
        and snapshot maintenance, and write the boot-baseline snapshot.
        Registers a best-effort ``atexit`` flush for non-serve embeddings
        (the serve path additionally flushes on SIGTERM drain).
        ``wall`` (tests) overrides the journal's wall clock so replayed
        ages are deterministic."""
        import atexit

        from log_parser_tpu.runtime.journal import (
            DurableFrequencyTracker,
            FrequencyJournal,
        )

        kw = {} if wall is None else {"wall": wall}
        journal = FrequencyJournal(
            state_dir, fsync_ms=fsync_ms, snapshot_every=snapshot_every, **kw
        )
        tracker = DurableFrequencyTracker(
            self.config, self.frequency.clock, journal
        )
        pre = self.frequency._save_state()
        if pre:
            # warm attach (tests, embeddings): fold pre-attach in-memory
            # entries into the recovered state; the _load_state barrier
            # makes the merged state the journal's new truth
            merged = tracker._save_state()
            for pid, ts in pre.items():
                merged[pid] = sorted(merged.get(pid, []) + list(ts))
            tracker._load_state(merged)
        with self.state_lock:
            self.frequency = tracker
            if self._golden is not None:
                self._golden.frequency = tracker
        self.journal = journal
        journal.start(tracker.snapshot, self.state_lock)
        # boot baseline: the recovered state becomes one durable snapshot
        # and the replayed tail is truncated away
        journal.snapshot_now()
        atexit.register(journal.flush)
        return journal

    def _install_library(self, source: "AnalysisEngine") -> None:
        """Transplant every library-derived component from ``source``
        (a fully-built engine of the same class family). Caller holds the
        state lock with the request gate quiesced. Subclasses with extra
        device programs (pattern sharding) extend this."""
        self.bank = source.bank
        self.tables = source.tables
        self._matchers = source._matchers
        self._fused = source._fused
        self._host_cols = source._host_cols
        self._device_cols = source._device_cols
        self._host_pref_cols = source._host_pref_cols
        self._host_slow_cols = source._host_slow_cols
        self._host_prefilter = source._host_prefilter
        self._golden = None  # lazily rebuilt against the new bank
        self._approx_pat_mask = None
        self._approx_sec = None
        self._approx_token = None
        self._k_hint = 0

    def apply_library(
        self,
        source: "AnalysisEngine",
        timeout_s: float = 30.0,
        pre_swap: Callable[[], None] | None = None,
    ) -> int:
        """Atomically swap this engine onto ``source``'s pattern library.

        Admission of new requests pauses, in-flight (and already-enqueued
        batched) requests drain on the OLD banks, then the swap happens
        under the state lock; frequency entries for pattern ids surviving
        into the new library carry over, the rest are dropped (their
        windowed history is meaningless without the pattern). ``pre_swap``
        runs inside the quiesced critical section — the distributed
        coordinator broadcasts the reload there so no request broadcast
        can interleave. Returns the new reload epoch."""
        deadline = pclock.mono() + timeout_s
        with self._quiesce_cv:
            if self._swap_pending:
                raise RuntimeError("another pattern reload is in progress")
            self._swap_pending = True
            try:
                while self._active_requests > 0:
                    remaining = deadline - pclock.mono()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"reload quiesce timed out after {timeout_s:g}s "
                            f"({self._active_requests} request(s) in flight)"
                        )
                    self._quiesce_cv.wait(remaining)
            except BaseException:
                self._swap_pending = False
                self._quiesce_cv.notify_all()
                raise
        try:
            with self.state_lock:
                if pre_swap is not None:
                    pre_swap()
                self._install_library(source)
                survivors = set(self.bank.freq_ids)
                for pid in list(self.frequency._frequencies):
                    if pid not in survivors:
                        del self.frequency._frequencies[pid]
                if self.batcher is not None:
                    from log_parser_tpu.ops.fused import FusedBatchMatchScore

                    self.batcher.program = FusedBatchMatchScore(self.fused)
                if self.line_cache is not None:
                    # wholesale epoch invalidation INSIDE the quiesced
                    # swap: no request is in flight, so no populate racing
                    # the flush can resurrect an old library's bits — a
                    # stale hit across a pattern swap is structurally
                    # impossible (tests/test_linecache.py pins it)
                    self.line_cache.flush(n_columns=self.bank.n_columns)
                self.reload_epoch += 1
                if self.journal is not None:
                    # the carry-over pruning above bypassed the tracker's
                    # journaling overrides; one barrier records the truth
                    self.journal.append_barrier(self.frequency.snapshot())
        finally:
            with self._quiesce_cv:
                self._swap_pending = False
                self._quiesce_cv.notify_all()
        return self.reload_epoch

    # --------------------------------------------------------------- analyze

    def analyze(
        self, data: PodFailureData, request_id: str | None = None
    ) -> AnalysisResult:
        """Sequential analyze — the single-caller entry point (tests,
        benches, the golden-parity harness). Transport front-ends that
        serve concurrent requests use :meth:`analyze_pipelined`.
        ``request_id``: the propagated trace id (X-Request-Id) this
        request carries through the obs trace ring."""
        return self._analyze(data, _NULL_LOCK, request_id)

    def analyze_pipelined(
        self, data: PodFailureData, request_id: str | None = None
    ) -> AnalysisResult:
        """Thread-safe analyze: ingest + device execution (the prepare
        phase, which touches no shared mutable state) runs OUTSIDE
        ``state_lock``, so request N+1's ingest/device work overlaps
        request N's host finalize — the frequency read-before-record
        boundary is the only true serialization point (SURVEY.md §5.2;
        the reference serializes nothing and data-races instead)."""
        return self._analyze(data, self.state_lock, request_id)

    def enable_batching(self, wait_ms: float = 2.0, batch_max: int = 8):
        """Attach and start the cross-request micro-batching scheduler
        (runtime/batcher.py): concurrent ``analyze_batched`` calls coalesce
        into one padded vmapped device batch per shape bucket. Only the
        single-device fused program supports the leading request axis —
        sharded/distributed engines keep the unbatched path."""
        from log_parser_tpu.runtime.batcher import MicroBatcher

        self.batcher = MicroBatcher(
            self, wait_ms=wait_ms, batch_max=batch_max
        ).start()
        return self.batcher

    def enable_line_cache(self, mb: float = DEFAULT_LINE_CACHE_MB):
        """Attach the exact-match line cache (runtime/linecache.py):
        per-line pre-override match-bit rows keyed by the hash of the
        ingest-normalized line bytes. Repeat lines skip the match cube;
        novel lines go to the device as a compacted residual batch and
        populate the cache on the way back. Single-device engines only —
        the residual program is the full-bank cube (sharded/distributed
        engines keep the uncached path; the serve layer gates the flag
        exactly like micro-batching)."""
        self.line_cache = LineCache(
            self.bank.n_columns, int(float(mb) * 1024 * 1024)
        )
        # two-level keying rides along: repeat lines resolve their
        # digest by vectorized probe + memcmp instead of blake2b
        # (content-pure, so reloads/breaker trips never touch it)
        self.key_interner = KeyInterner()
        return self.line_cache

    def enable_shadow(self, rate: float, seed: int | None = None):
        """Attach and start the online shadow verifier: ``rate`` of
        served device/batched requests are re-run on the golden host path
        off the hot path (cloned frequency state, never double-counted)
        and compared at 1e-9; a divergence trips the divergent pattern's
        breaker (see :class:`ShadowVerifier`). ``seed`` pins the sampling
        RNG (``LOG_PARSER_TPU_SHADOW_SEED`` when None)."""
        if seed is None:
            seed = int(os.environ.get("LOG_PARSER_TPU_SHADOW_SEED", "0"))
        if self.shadow is not None:
            self.shadow.close()
        self.shadow = ShadowVerifier(self, rate, seed=seed).start()
        return self.shadow

    def enable_miner(
        self,
        *,
        mode: str = "review",
        sample: float = 1.0,
        min_support: int = 8,
        state_dir: str | None = None,
        capacity: int | None = None,
        shadow_rate: float | None = None,
        stability: int = 4,
        autostart: bool = True,
    ):
        """Attach the template miner (mining/): line-cache misses feed a
        sampled bounded tap, a background thread clusters them into
        token templates, and stable templates become candidate patterns
        behind the admission pipeline (``--mined-patterns``). Requires
        the line cache — without a miss stream there is nothing to mine
        (the serve layer gates the flag accordingly). ``autostart=False``
        leaves the worker unstarted so tests and tools drive
        :meth:`TemplateMiner.pump` deterministically."""
        from log_parser_tpu.mining.miner import TemplateMiner
        from log_parser_tpu.runtime.linecache import DEFAULT_TAP_CAPACITY

        if self.line_cache is None:
            raise RuntimeError("enable_miner requires enable_line_cache first")
        if self.miner is not None:
            self.miner.stop()
        if capacity is None:
            capacity = int(
                os.environ.get(
                    "LOG_PARSER_TPU_MINER_TAP_CAPACITY", str(DEFAULT_TAP_CAPACITY)
                )
            )
        kwargs = {} if shadow_rate is None else {"shadow_rate": shadow_rate}
        self.miner = TemplateMiner(
            self,
            mode=mode,
            sample=sample,
            min_support=min_support,
            state_dir=state_dir,
            capacity=capacity,
            stability=stability,
            **kwargs,
        )
        if autostart:
            self.miner.start()
        return self.miner

    def analyze_batched(
        self,
        data: PodFailureData,
        deadline_ms: float | None = None,
        request_id: str | None = None,
    ) -> AnalysisResult:
        """Thread-safe analyze through the micro-batcher: this request may
        share its device step with concurrent callers, with per-request
        results, fallback, and frequency semantics identical to
        :meth:`analyze_pipelined` (which it degrades to when batching is
        off). ``deadline_ms``: remaining budget — a tight deadline pulls
        this request's batch flush earlier."""
        batcher = self.batcher
        if batcher is None:
            return self.analyze_pipelined(data, request_id=request_id)
        return batcher.submit(data, deadline_ms, request_id=request_id)

    def analyze_host_routed(
        self, data: PodFailureData, request_id: str | None = None
    ) -> AnalysisResult:
        """Serve one request from the golden host path because the
        admission gate routed it there under pressure (ladder rung 2,
        serve/admission.py) — NOT because anything failed. Same frequency
        state, same rollback-on-failure invariant as the error fallback,
        separate counter."""
        start = pclock.mono()
        with self._request_scope(), self.state_lock:
            self.host_routed_count += 1
            result = self._golden_serve(data)
        self._note_golden(start, "host", request_id, "ok")
        return result

    def _note_golden(
        self, start: float, route: str, request_id: str | None,
        outcome: str, error: str | None = None,
    ) -> None:
        """Ring entry for a golden-host-served request (host-routed,
        quarantined, fallback) — no device phases to report, but the
        request id and wall time still belong in the obs ring."""
        trace = PhaseTrace()
        trace.route = route
        trace.request_id = request_id
        self.obs.note_served(
            trace, start, self.obs_tenant, outcome=outcome, error=error
        )

    def _analyze(
        self, data: PodFailureData, lock, request_id: str | None = None
    ) -> AnalysisResult:
        with self._request_scope():
            return self._analyze_in_scope(data, lock, request_id)

    def _analyze_in_scope(
        self, data: PodFailureData, lock, request_id: str | None = None
    ) -> AnalysisResult:
        start = pclock.mono()
        fp = self._quarantine_check(data)
        if fp is not None:
            with lock:
                result = self._serve_quarantined(data, fp)
            self._note_golden(start, "device", request_id, "quarantined")
            return result
        try:
            prepared = self._prepare(data)
        except Exception as exc:
            with lock:
                return self._serve_fallback(
                    data, exc, request_id=request_id, start=start
                )
        prepared.trace.request_id = request_id
        # lock WAIT is a traced phase: under concurrency the finish
        # phases serialize here, and a latency decomposition that omits
        # the wait would misattribute it to HTTP/tunnel transport.
        # ``lock`` may be a real Lock (pipelined) or a nullcontext
        # (bare analyze), so enter/exit the context protocol directly.
        with prepared.trace.phase("lock_wait"):
            lock.__enter__()
        try:
            # roll frequency state back on ANY failure: a partially-run
            # request (e.g. one that died after recording its matches)
            # must not leave the tracker double-counted — whether golden
            # re-serves it or the client retries after a 500
            saved_freq = self.frequency._save_state()
            try:
                return self._finish(prepared)
            except Exception as exc:
                self.frequency._load_state(saved_freq)
                return self._serve_fallback(
                    data, exc,
                    request_id=request_id, start=prepared.start,
                    route=prepared.trace.route,
                )
        finally:
            lock.__exit__(None, None, None)

    def _quarantine_check(self, data: PodFailureData) -> str | None:
        """The request's fingerprint when it is actively quarantined,
        else None. The sha256 is only computed once any fingerprint is
        being tracked — the steady state pays one counter read."""
        q = self.quarantine
        if q is None or not q._table:
            return None
        fp = quarantine_fingerprint(data.logs or "")
        return fp if q.check(fp) else None

    def _serve_quarantined(self, data: PodFailureData, fp: str) -> AnalysisResult:
        """Serve a quarantined request straight from the golden host path
        — it never reaches the device step, the watchdog breaker, or a
        shared batch. Only when golden ALSO fails does the caller get a
        structured 429 + Retry-After (QuarantineRejected). Caller holds
        the lock."""
        from log_parser_tpu.runtime.quarantine import QuarantineRejected

        try:
            result = self._golden_serve(data)
        except Exception as exc:
            self.quarantine.note_rejected()
            raise QuarantineRejected(
                fp, self.quarantine.retry_after(fp)
            ) from exc
        self.quarantine.note_served()
        return result

    def _strike_worthy(self, exc: Exception) -> bool:
        """Does this device-classified failure accuse the REQUEST? Only
        organic CRASHES strike: injected backend chaos (device_raise)
        would quarantine innocent traffic, and a hang — circuit-open
        short-circuit or an actual watchdog timeout — accuses the
        BACKEND, whose containment is the watchdog breaker (an innocent
        request in flight when the device wedges, or the half-open probe
        itself, must stay device-eligible once the backend recovers).
        The injected poison pill (InjectedPoisonFault, the ``quarantine``
        fault site) is the deliberate exception — it simulates an
        organic poison."""
        if isinstance(exc, faults.InjectedPoisonFault):
            return True
        if isinstance(exc, faults.InjectedFault):
            return False
        if isinstance(exc, DeviceHungError):
            return False
        return True

    def _serve_fallback(
        self,
        data: PodFailureData,
        exc: Exception,
        request_id: str | None = None,
        start: float | None = None,
        route: str = "device",
    ) -> AnalysisResult:
        """Serve ``data`` from the golden host path if ``exc`` is a device
        failure and the fallback is enabled; re-raise otherwise. Caller
        holds the lock (frequency state is read and mutated here)."""
        if not self.fallback_to_golden or not is_device_error(exc):
            # logic bugs always propagate; device failures degrade to
            # the golden host path only when the fallback is enabled
            raise exc
        import logging

        self.fallback_count += 1
        if self._strike_worthy(exc):
            fp = quarantine_fingerprint(data.logs or "")
            if self.quarantine.strike(fp):
                logging.getLogger(__name__).warning(
                    "Quarantined request fingerprint %s… for %gs after "
                    "%d device-failure strike(s); repeats serve from the "
                    "host path without touching the device",
                    fp[:12],
                    self.quarantine.ttl_s,
                    self.quarantine.threshold,
                )
        logging.getLogger(__name__).exception(
            "Device batch failed (fallback #%d); serving this request "
            "from the golden host path",
            self.fallback_count,
        )
        # device-side observability does not describe this request
        self.last_trace = None
        self.last_finalized = None
        result = self._golden_serve(data)
        self._note_golden(
            start if start is not None else pclock.mono(),
            route, request_id, "fallback", error=type(exc).__name__,
        )
        return result

    def _prepare(self, data: PodFailureData) -> "_Prepared":
        """Ingest + overrides + the device batch: everything before the
        frequency read. Touches no shared mutable state beyond the
        ``_k_hint`` perf hint — safe to run concurrently with another
        request's :meth:`_finish`."""
        start = pclock.mono()
        trace = PhaseTrace()
        with trace.phase("ingest"):
            faults.fire("ingest")  # conlint: contained-by-caller (serve handler / batcher bisection)
            corpus = Corpus(data.logs or "", min_rows=self._corpus_min_rows())
            enc = corpus.encoded

        with trace.phase("overrides"):
            overrides = self._overrides(corpus)
        om, ov = overrides if overrides is not None else (None, None)

        cache = self.line_cache
        if cache is not None:
            return self._prepare_cached(data, start, trace, corpus, om, ov, cache)

        def _device_step():
            # chaos points INSIDE the watchdog worker: an injected hang
            # exercises the timeout/breaker exactly like a wedged backend;
            # the quarantine site is keyed by this request's content so a
            # match= spec can poison exactly one request
            faults.fire("quarantine", key=data.logs or "")  # conlint: contained-by-caller (watchdog.run)
            faults.fire("device")  # conlint: contained-by-caller (watchdog.run)
            return self._run_device(enc, corpus.n_lines, om, ov, trace=trace)

        with trace.phase("device"):
            recs = self.watchdog.run(_device_step)
        # capacity hint tracks the RAW device match count (the buffer the
        # device actually needs), before approx verification drops rows
        self._k_hint = recs.n_matches
        with trace.phase("verify"):
            recs = self._verify_approx(corpus, recs)
        return _Prepared(start, trace, corpus, recs, data)

    def _prepare_cached(
        self, data, start, trace, corpus, om, ov, cache: LineCache
    ) -> "_Prepared":
        """The routing-tier prepare path: per-line cache lookup, one
        compacted residual cube dispatch for the unique misses, host-side
        override splice + record extraction. A request whose lines are
        ALL cache hits never reaches the device step at all — it cannot
        trip the watchdog, cannot strike quarantine, and costs no device
        dispatch. Parity with :meth:`_prepare` is exact: the cache holds
        PRE-override bit rows (width-independent — zero padding is
        automaton-neutral and ``needs_host`` lines are never populated),
        the request's override cube is re-applied here, and
        ``records_from_bits`` mirrors the device extraction bit-for-bit."""
        enc = corpus.encoded
        n = corpus.n_lines
        with trace.phase("cache"):
            # dedup to unique lines FIRST (bytes-keyed dict, C speed),
            # then hash once per unique line: one device row per distinct
            # novel line (the in-request half of the dedup; the batcher
            # dedups across a whole flush the same way). Within one
            # request duplicate content always shares one needs_host
            # verdict (same bytes, same device width), so slot-level
            # bookkeeping indexed at the first appearance is exact.
            ded = dedup_slots(corpus, interner=self.key_interner)
            if ded is not None:
                # array-speed lane: lexsort grouping over the contiguous
                # byte view (same first-appearance slot order, same
                # digests — linecache.dedup_slots pins the parity)
                line_slot, rep_lines, keys, counts = ded
                uniq_lines = rep_lines.tolist()
                U = len(uniq_lines)
                counts = (
                    counts if U else np.zeros(1, dtype=np.int64)
                )
            else:
                # lone-surrogate corpora have no contiguous byte view —
                # keep the per-line dict loop
                slot_of: dict[bytes, int] = {}
                uniq_lines = []
                line_slot = np.empty(n, dtype=np.int64)
                for i in range(n):
                    lb = corpus.line_key_bytes(i)
                    s = slot_of.get(lb)
                    if s is None:
                        s = len(uniq_lines)
                        slot_of[lb] = s
                        uniq_lines.append(i)
                    line_slot[i] = s
                U = len(uniq_lines)
                keys = [line_key(lb) for lb in slot_of]  # insertion == slot order
                counts = np.bincount(line_slot, minlength=max(U, 1))
            packed = cache.lookup_packed(keys, counts=counts.tolist())
            miss_slots = [s for s in range(U) if packed[s] is None]

        fresh = None
        if miss_slots:
            miss_lines = [uniq_lines[s] for s in miss_slots]
            u = len(miss_lines)
            miner = self.miner
            if miner is not None:
                # miss-stream tap: one non-blocking bounded-queue offer
                # per unique novel line (sampling + drop accounting live
                # in the tap); the mining work itself happens on the
                # miner thread, never here
                cts = counts[miss_slots]
                for j, i in enumerate(miss_lines):
                    miner.tap.offer(corpus.line_key_bytes(i), int(cts[j]))
            pad = _pad_rows(u, self._corpus_min_rows())
            res_u8 = np.zeros((pad, enc.u8.shape[1]), dtype=np.uint8)
            res_len = np.zeros(pad, dtype=np.int32)
            res_u8[:u] = enc.u8[miss_lines]
            res_len[:u] = enc.lengths[miss_lines]

            def _device_step():
                # same chaos points as the uncached path — the residual
                # IS this request's device step, so a keyed poison spec
                # fires (and strikes) exactly as before
                faults.fire("quarantine", key=data.logs or "")  # conlint: contained-by-caller (watchdog.run)
                faults.fire("device")  # conlint: contained-by-caller (watchdog.run)
                return self._run_cube(res_u8, res_len, u, trace=trace)

            with trace.phase("device"):
                fresh = self.watchdog.run(_device_step)[:u]
            cache.note_residual(u, int(counts[miss_slots].sum()) - u)
            # needs_host lines are excluded: their truncated/replaced
            # encode is width-dependent, so their device bits are not a
            # function of the line content alone (harmless to LOOK UP —
            # their columns are fully overridden below — but never stored)
            keep = [
                j
                for j, i in enumerate(miss_lines)
                if not enc.needs_host[i]
            ]
            cache.populate_rows(
                [keys[miss_slots[j]] for j in keep], fresh[keep]
            )

        with trace.phase("extract"):
            if n:
                bits_u = np.zeros((U, cache.n_columns), dtype=bool)
                hit_slots = [s for s in range(U) if packed[s] is not None]
                if hit_slots:
                    bits_u[hit_slots] = cache.unpack(
                        [packed[s] for s in hit_slots]
                    )
                if fresh is not None:
                    bits_u[miss_slots] = fresh
                bits = bits_u[line_slot]  # fan unique rows back out
            else:
                bits = np.zeros((0, cache.n_columns), dtype=bool)
            if om is not None:
                # the per-request override splice: host-only columns,
                # needs_host lines, and OPEN-breaker patterns — applied on
                # the host over cached and fresh rows alike, which is what
                # makes a breaker trip an exact per-pattern invalidation
                bits = np.where(om[:n], ov[:n], bits)
            recs = records_from_bits(bits, n, self.bank, self.tables)
        self._k_hint = recs.n_matches
        with trace.phase("verify"):
            recs = self._verify_approx(corpus, recs)
        return _Prepared(start, trace, corpus, recs, data)

    def _finish(self, prepared: "_Prepared") -> AnalysisResult:
        """Frequency read → exact-f64 finalize → frequency record →
        assemble. Serialized under ``state_lock`` by concurrent callers:
        the read-before-record ordering (ScoringService.java:84-88) is
        only meaningful per-request-atomically."""
        start, trace, corpus, recs = (
            prepared.start,
            prepared.trace,
            prepared.corpus,
            prepared.recs,
        )
        # shadow sampling decides (and captures the pre-record tracker
        # state) HERE, under the lock: the golden re-run must read exactly
        # the windowed counts this request's finalize reads, cloned so it
        # can never double-count the live tracker
        shadow = self.shadow
        shadow_state = None
        if shadow is not None and prepared.data is not None and shadow.should_sample():
            shadow_state = self.frequency._save_state()
        # windowed frequency counts at batch start (pruned by the tracker);
        # "entry exists" is tracked separately — an expired window still has
        # an entry and takes the formula path, not the null early-return
        freq_base = np.zeros(max(1, self.bank.n_freq_slots), dtype=np.float64)
        freq_exists = np.zeros(max(1, self.bank.n_freq_slots), dtype=bool)
        for slot, pid in enumerate(self.bank.freq_ids):
            freq_base[slot] = self.frequency.get_windowed_count(pid)
            freq_exists[slot] = self.frequency.has_entry(pid)

        with trace.phase("finalize"):
            faults.fire("finalize")  # conlint: contained-by-caller (serve handler / batcher bisection)
            fin = finalize_batch(
                self.bank, self.tables, self.config, recs, corpus.n_lines,
                freq_base, freq_exists,
            )

        # record this batch's matches (after the read — ScoringService.java:84-88);
        # bulk per slot: one list extend instead of count Python calls
        # inside the only lock every concurrent request shares. Zero-count
        # slots are skipped wholesale: record_pattern_matches(pid, 0)
        # early-returns without creating an entry, so on hit-heavy traffic
        # (few matched patterns per batch) this touches matched slots only
        sbc = np.asarray(fin.slot_batch_counts[: self.bank.n_freq_slots])
        for slot in np.flatnonzero(sbc).tolist():
            self.frequency.record_pattern_matches(
                self.bank.freq_ids[slot], int(sbc[slot])
            )

        # records are already in discovery order (line-major, then pattern)
        with trace.phase("assemble"):
            # one bulk ndarray→Python conversion per column instead of
            # three per-element __getitem__/int()/float() calls per event
            # (``.tolist()`` yields the same Python ints/floats those
            # casts produce, element for element)
            events: list[MatchedEvent] = []
            patterns = self.bank.patterns
            for line_idx, pat_i, score in zip(
                fin.line.tolist(), fin.pattern.tolist(), fin.scores.tolist()
            ):
                pattern = patterns[pat_i]
                events.append(
                    MatchedEvent(
                        line_number=line_idx + 1,
                        matched_pattern=pattern,
                        context=extract_context(corpus, line_idx, pattern),
                        score=score,
                    )
                )

            result = AnalysisResult(
                events=events,
                analysis_id=str(uuid.uuid4()),
                metadata=build_metadata(start, corpus.n_lines, self.bank.pattern_sets),
                summary=build_summary(events),
            )
        self.last_trace = trace
        # bounded history for latency decomposition (bench_latency emits
        # device-phase percentiles beside the HTTP p99, so a reader can
        # split engine time from tunnel RTT — VERDICT r4 #7); deque
        # appends are thread-safe under concurrent _finish callers
        self.trace_history.append(trace)
        self.last_finalized = fin
        # per-phase histograms + the trace-ring entry for this request —
        # fed from the SAME PhaseTrace /trace/last exposes, so the two
        # surfaces can never disagree
        self.obs.note_served(
            trace, start, self.obs_tenant, n_lines=corpus.n_lines
        )
        if shadow_state is not None:
            shadow.submit(prepared.data, shadow_state, result)
        return result


class ShadowVerifier:
    """Online device-vs-golden verification off the hot path.

    The offline parity harness only proves parity for corpora someone
    thought to run; a silent device-vs-golden divergence on production
    traffic (a mistranslated regex corner, a tier bug on one byte
    sequence) would otherwise go unnoticed until the next offline run.
    This worker samples ``rate`` of served requests (decided under
    ``state_lock`` by a dedicated seeded RNG, so a sweep replays the same
    sampling decisions) and re-runs each on a golden analyzer whose
    frequency tracker is a CLONE of the pre-record state the device
    request read — the live tracker is never touched, so shadowing adds
    zero frequency drift and batched/unbatched scores stay bit-identical
    to a no-shadow run.

    Comparison is per event ``(line_number, pattern id, score)`` at 1e-9.
    On divergence: counters move (``/trace/last`` → ``shadow``),
    ``/q/health`` reports a DEGRADED ``shadow`` check, and the divergent
    pattern's breaker opens (:class:`PatternBreakerBoard`) — that pattern
    serves from the exact host regex while everything else stays
    on-device, then half-opens after the cool-down and the next forced
    shadow comparison closes or re-opens it.

    The ``shadow`` fault site fires in the worker per comparison; an
    injected raise is treated as a synthetic divergence on the request's
    first matched pattern (chaos drills the breaker ladder without
    needing a genuinely mistranslated pattern).
    """

    def __init__(
        self,
        engine: AnalysisEngine,
        rate: float,
        seed: int = 0,
        queue_max: int = 64,
        tolerance: float = 1e-9,
    ):
        self.engine = engine
        self.rate = min(1.0, max(0.0, float(rate)))
        self.tolerance = tolerance
        self.queue_max = max(1, int(queue_max))
        self._rng = random.Random(seed)
        self._cond = threading.Condition()
        self._jobs: deque = deque()
        self._pending = 0  # queued + in-flight comparisons
        self._closed = False
        self._thread: threading.Thread | None = None
        # counters (guarded by _cond; GET /trace/last "shadow")
        self.sampled = 0
        self.forced = 0
        self.compared = 0
        self.divergences = 0
        self.dropped = 0
        self.errors = 0
        self.last_divergence: dict | None = None
        # golden clone, rebuilt whenever the engine's bank is swapped
        self._golden = None
        self._golden_bank = None

    def start(self) -> "ShadowVerifier":
        self._thread = threading.Thread(
            target=self._worker, name="shadow-verifier", daemon=True
        )
        self._thread.start()
        return self

    def close(self, timeout_s: float = 10.0) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout_s)

    # ------------------------------------------------------------ sampling

    def should_sample(self) -> bool:
        """Called under ``state_lock`` (one RNG draw per served request —
        deterministic under a seed). A pending half-open breaker forces
        the sample so the probe actually resolves."""
        with self._cond:
            if self.engine.breakers.probe_pending():
                self.forced += 1
                self.sampled += 1
                return True
            if self.rate >= 1.0 or self._rng.random() < self.rate:
                self.sampled += 1
                return True
            return False

    def submit(self, data, freq_state: dict, result) -> None:
        """Hand one served request to the worker. Non-blocking: a full
        queue drops the sample (counted) rather than stalling serving."""
        events = [
            (e.line_number, e.matched_pattern.id, e.score)
            for e in result.events
        ]
        with self._cond:
            if self._closed:
                return
            if len(self._jobs) >= self.queue_max:
                self.dropped += 1
                return
            self._jobs.append((data, freq_state, events))
            self._pending += 1
            self._cond.notify_all()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every submitted comparison has been processed
        (tests and sweeps; serving never calls this)."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self._pending == 0, timeout_s
            )

    # -------------------------------------------------------------- worker

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._jobs and not self._closed:
                    self._cond.wait()
                if not self._jobs and self._closed:
                    return
                data, freq_state, device_events = self._jobs.popleft()
            try:
                self._compare(data, freq_state, device_events)
            except Exception:
                import logging

                with self._cond:
                    self.errors += 1
                logging.getLogger(__name__).exception(
                    "shadow verification failed (the request was already "
                    "served; this affects only the comparison)"
                )
            finally:
                with self._cond:
                    self._pending -= 1
                    self._cond.notify_all()

    def _golden_clone(self):
        from log_parser_tpu.golden.engine import GoldenAnalyzer

        bank = self.engine.bank
        if self._golden is None or self._golden_bank is not bank:
            self._golden = GoldenAnalyzer(
                bank.pattern_sets,
                self.engine.config,
                clock=self.engine.frequency.clock,
            )
            self._golden_bank = bank
        return self._golden

    def _compare(self, data, freq_state, device_events) -> None:
        synthetic = False
        try:
            faults.fire("shadow")
        except faults.InjectedFault:
            synthetic = True
        diverged: set[str] = set()
        seen: set[str] = {pid for _, pid, _ in device_events}
        if synthetic:
            # chaos: declare the request's first matched pattern divergent
            if device_events:
                diverged.add(device_events[0][1])
        else:
            golden = self._golden_clone()
            from log_parser_tpu.golden.engine import GoldenFrequencyTracker

            tracker = GoldenFrequencyTracker(
                self.engine.config, clock=self.engine.frequency.clock
            )
            tracker._load_state(freq_state)
            golden.frequency = tracker
            gresult = golden.analyze(data)
            dev = {(ln, pid): s for ln, pid, s in device_events}
            gol = {
                (e.line_number, e.matched_pattern.id): e.score
                for e in gresult.events
            }
            seen |= {pid for _, pid in gol}
            for key in dev.keys() | gol.keys():
                if key not in dev or key not in gol:
                    diverged.add(key[1])
                elif abs(dev[key] - gol[key]) > self.tolerance:
                    diverged.add(key[1])
        with self._cond:
            self.compared += 1
            if diverged:
                self.divergences += 1
                self.last_divergence = {
                    "patterns": sorted(diverged),
                    "synthetic": synthetic,
                }
        if diverged:
            import logging

            logging.getLogger(__name__).error(
                "Shadow divergence on pattern(s) %s%s — opening per-"
                "pattern breaker(s); those patterns serve from the host "
                "regex until a clean half-open probe",
                sorted(diverged),
                " (synthetic, injected)" if synthetic else "",
            )
            for pid in diverged:
                self.engine.breakers.trip(pid)
        self.engine.breakers.resolve(seen, diverged)

    # ------------------------------------------------------- observability

    def stats(self) -> dict:
        with self._cond:
            payload = {
                "rate": self.rate,
                "sampled": self.sampled,
                "forced": self.forced,
                "compared": self.compared,
                "divergences": self.divergences,
                "dropped": self.dropped,
                "errors": self.errors,
                "queueDepth": len(self._jobs),
                "breakers": self.engine.breakers.stats(),
            }
            if self.last_divergence is not None:
                payload["lastDivergence"] = self.last_divergence
            return payload
