"""AnalysisEngine — the TPU-backed replacement for the reference's
``AnalysisService.analyze`` (AnalysisService.java:50-122).

Pipeline per request:

1. split logs with Java semantics (AnalysisService.java:53);
2. encode lines into a padded uint8 batch (vectorized, host);
3. evaluate every matcher column: DFA bank on device for automaton-backed
   regexes, host ``re`` for the fallback set and for lines the device can't
   be exact on (non-ASCII / over-long);
4. one jitted scoring pass producing f64 scores for all (line, pattern)
   pairs plus the frequency batch counts;
5. assemble ``AnalysisResult`` in discovery order (line-major, then pattern
   order — AnalysisService.java:89-113) with the same metadata/summary
   quirks as the reference.

Frequency state is the engine's only mutable state, mirrored from the
reference's ConcurrentHashMap (FrequencyTrackingService.java:25) but read
and advanced at batch granularity with exact per-match ordering recovered
inside the kernel (read-before-record, ScoringService.java:84-88).
"""

from __future__ import annotations

import time
import uuid
from typing import Callable

import numpy as np

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden.engine import (
    GoldenFrequencyTracker,
    build_metadata,
    build_summary,
    extract_context,
)
from log_parser_tpu.golden.javacompat import java_split_lines
from log_parser_tpu.models.analysis import AnalysisResult, MatchedEvent
from log_parser_tpu.models.pattern import PatternSet
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.ops.match import DfaBank
from log_parser_tpu.ops.scoring import ScoringKernel
from log_parser_tpu.patterns.bank import PatternBank


class AnalysisEngine:
    """Immutable compiled library + jitted kernels + frequency state."""

    def __init__(
        self,
        pattern_sets: list[PatternSet],
        config: ScoringConfig | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config or ScoringConfig()
        self.bank = PatternBank(pattern_sets)
        self.kernel = ScoringKernel(self.bank, self.config)
        self.frequency = GoldenFrequencyTracker(self.config, clock=clock)

        self._dfa_cols = [
            i for i, c in enumerate(self.bank.columns) if c.dfa is not None
        ]
        self._host_cols = [
            i for i, c in enumerate(self.bank.columns) if c.dfa is None
        ]
        self.dfa_bank = DfaBank([self.bank.columns[i].dfa for i in self._dfa_cols])

    @property
    def skipped_patterns(self) -> list[tuple[str, str]]:
        return self.bank.skipped_patterns

    # ----------------------------------------------------------------- match

    def _match_cube(self, lines: list[str]) -> np.ndarray:
        """bool [B_padded, n_columns]; exact for every real line."""
        enc = encode_lines(lines)
        B = enc.u8.shape[0]
        cube = np.zeros((B, self.bank.n_columns), dtype=bool)
        if enc.n_lines == 0:
            return cube
        if self._dfa_cols:
            cube[:, self._dfa_cols] = self.dfa_bank.match(enc.u8, enc.lengths)
        # host passes: fallback columns on all lines; all columns on lines
        # the device can't be exact on (non-ASCII bytes, over-long lines)
        for col in self._host_cols:
            host = self.bank.columns[col].host
            for i in range(enc.n_lines):
                cube[i, col] = bool(host.search(lines[i]))
        host_lines = np.flatnonzero(enc.needs_host[: enc.n_lines])
        for i in host_lines:
            line = lines[i]
            for col in self._dfa_cols:
                cube[i, col] = bool(self.bank.columns[col].host.search(line))
        return cube

    # --------------------------------------------------------------- analyze

    def analyze(self, data: PodFailureData) -> AnalysisResult:
        start = time.monotonic()
        lines = java_split_lines(data.logs or "")
        cube = self._match_cube(lines)

        # windowed frequency counts at batch start (pruned by the tracker);
        # "entry exists" is tracked separately — an expired window still has
        # an entry and takes the formula path, not the null early-return
        freq_base = np.zeros(max(1, self.bank.n_freq_slots), dtype=np.float64)
        freq_exists = np.zeros(max(1, self.bank.n_freq_slots), dtype=bool)
        for slot, pid in enumerate(self.bank.freq_ids):
            freq_base[slot] = self.frequency.get_windowed_count(pid)
            freq_exists[slot] = self.frequency.has_entry(pid)

        batch = self.kernel.score_batch(cube, len(lines), freq_base, freq_exists)

        # record this batch's matches (after the read — ScoringService.java:84-88)
        for slot, count in enumerate(batch.slot_batch_counts[: self.bank.n_freq_slots]):
            for _ in range(int(count)):
                self.frequency.record_pattern_match(self.bank.freq_ids[slot])

        # discovery order: line-major then pattern order ⇔ row-major argwhere
        events: list[MatchedEvent] = []
        for line_idx, p_idx in np.argwhere(batch.primary_match):
            pattern = self.bank.patterns[p_idx]
            events.append(
                MatchedEvent(
                    line_number=int(line_idx) + 1,
                    matched_pattern=pattern,
                    context=extract_context(lines, int(line_idx), pattern),
                    score=float(batch.scores[line_idx, p_idx]),
                )
            )

        return AnalysisResult(
            events=events,
            analysis_id=str(uuid.uuid4()),
            metadata=build_metadata(start, len(lines), self.bank.pattern_sets),
            summary=build_summary(events),
        )
