"""GoldenAnalyzer — exact pure-Python replication of the reference pipeline.

This is the executable specification of the JVM semantics
(AnalysisService.java / ScoringService.java / ContextAnalysisService.java /
FrequencyTrackingService.java), including the quirks that matter for parity:

- events are returned in *discovery order* — line-major, then pattern-set
  order, then pattern order within the set (AnalysisService.java:89-113).
  docs/SCORING_ALGORITHM.md:191 claims events are sorted by score; the code
  never sorts.
- for each match, the frequency penalty is read *before* the match is
  recorded (ScoringService.java:84-88), and frequency state persists across
  matches and requests — so the Nth match of a pattern sees counts 1..N-1.
- context scoring's WARN branch is an ``else if`` after ERROR
  (ContextAnalysisService.java:64-70): a line matching both counts only as
  error.
- an unknown severity string ranks *below* INFO in the highest-severity
  computation (``indexOf == -1``, AnalysisService.java:206-211).

Two deliberate divergences, both NPE-shaped reference bugs we do not
reproduce:

- a pattern set whose ``patterns`` list is null is skipped. The reference
  NPEs in its match loop on such a set (AnalysisService.java:91-92 iterates
  ``getPatterns()`` without the null check the compile loop has at :57-59);
  crashing the request is a reference bug we do not reproduce.
- a null/absent ``severity`` is treated as ``""``: it takes the default
  severity multiplier 1.0 in scoring and ranks below INFO in the
  highest-severity computation (the ``indexOf == -1`` path). The reference
  calls ``.toUpperCase()`` on it unguarded (ScoringService.java:69,
  AnalysisService.java:201) and NPEs the whole request.
"""

from __future__ import annotations

import datetime
import logging
import math
import re
import time
import uuid
from typing import Callable

log = logging.getLogger(__name__)

from log_parser_tpu import _clock as pclock
from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden.javacompat import compile_java_regex, java_split_lines
from log_parser_tpu.javamath import java_div, java_min
from log_parser_tpu.models.analysis import (
    AnalysisMetadata,
    AnalysisResult,
    AnalysisSummary,
    EventContext,
    MatchedEvent,
    PatternFrequency,
)
from log_parser_tpu.models.pattern import Pattern, PatternSet
from log_parser_tpu.models.pod import PodFailureData

# ScoringService.java:30-36 — hardcoded, deliberately not configurable.
SEVERITY_MULTIPLIERS: dict[str, float] = {
    "CRITICAL": 5.0,
    "HIGH": 3.0,
    "MEDIUM": 2.0,
    "LOW": 1.5,
    "INFO": 1.0,
}

# AnalysisService.java:206 — severity ranking for the summary.
SEVERITY_ORDER = ["INFO", "LOW", "MEDIUM", "HIGH", "CRITICAL"]

# ContextAnalysisService.java:27-34 — the four hardcoded context regexes.
ERROR_PATTERN = compile_java_regex(r"\b(ERROR|FATAL|CRITICAL|SEVERE)\b", case_insensitive=True)
WARN_PATTERN = compile_java_regex(r"\b(WARN|WARNING)\b", case_insensitive=True)
STACK_TRACE_PATTERN = compile_java_regex(r"^\s*at\s+[\w\.\$]+\(.*\)\s*$")
EXCEPTION_PATTERN = compile_java_regex(r"\b\w*Exception\b|\b\w*Error\b")

# ContextAnalysisService.java:62-98 — per-line weights and penalty constants.
ERROR_WEIGHT = 0.4
WARN_WEIGHT = 0.2
STACK_WEIGHT = 0.1
EXCEPTION_WEIGHT = 0.3
STACK_BONUS_CAP = 0.5
DENSITY_PENALTY = 0.8
DENSITY_MIN_LINES = 10
DENSITY_RATIO = 0.7

# ScoringService.java:274 — sequence near-primary window.
SEQUENCE_NEAR_WINDOW = 5


class SnapshotValidationError(ValueError):
    """Client-supplied frequency snapshot failed validation (restore is
    all-or-nothing). A dedicated type so transports can classify it as a
    client error without catching every ValueError (ADVICE.md r2)."""


class GoldenFrequencyTracker:
    """FrequencyTrackingService.java:20-134 — cross-request sliding-window
    match counts keyed by pattern id."""

    def __init__(self, config: ScoringConfig, clock: Callable[[], float] = pclock.mono):
        self.config = config
        self.clock = clock
        self._frequencies: dict[str, PatternFrequency] = {}

    def record_pattern_match(self, pattern_id: str | None) -> None:
        """FrequencyTrackingService.java:41-56."""
        self.record_pattern_matches(pattern_id, 1)

    def record_pattern_matches(self, pattern_id: str | None, n: int) -> None:
        """Batched recording — one lock-held list extend instead of n
        Python calls (the engine's finish phase holds the request-serial
        state lock; a hit-heavy batch records millions of matches)."""
        if n <= 0 or pattern_id is None or pattern_id.strip() == "":
            return
        freq = self._frequencies.get(pattern_id)
        if freq is None:
            freq = PatternFrequency(
                self.config.frequency_time_window_hours * 3600.0, clock=self.clock
            )
            self._frequencies[pattern_id] = freq
        freq.increment_count_bulk(n)

    def calculate_frequency_penalty(self, pattern_id: str | None) -> float:
        """FrequencyTrackingService.java:64-93."""
        if pattern_id is None or pattern_id.strip() == "":
            return 0.0
        freq = self._frequencies.get(pattern_id)
        if freq is None:
            return 0.0
        rate = freq.get_hourly_rate()
        if rate <= self.config.frequency_threshold:
            return 0.0
        excess = rate - self.config.frequency_threshold
        # Java double semantics: threshold 0 or a NaN rate never throws
        return java_min(
            self.config.frequency_max_penalty,
            java_div(excess, self.config.frequency_threshold),
        )

    def get_frequency_statistics(self) -> dict[str, int]:
        """FrequencyTrackingService.java:110-115."""
        return {pid: f.get_current_count() for pid, f in self._frequencies.items()}

    def get_windowed_count(self, pattern_id: str) -> int:
        """Current in-window count for one pattern id (0 if never seen)."""
        freq = self._frequencies.get(pattern_id)
        return freq.get_current_count() if freq is not None else 0

    def has_entry(self, pattern_id: str) -> bool:
        """Whether the tracker has an entry at all — distinct from a zero
        windowed count (FrequencyTrackingService.java:69-71 early-returns
        0.0 only when no entry exists)."""
        return pattern_id in self._frequencies

    def reset_pattern_frequency(self, pattern_id: str) -> None:
        """FrequencyTrackingService.java:122-128."""
        freq = self._frequencies.get(pattern_id)
        if freq is not None:
            freq.reset()

    def reset_all_frequencies(self) -> None:
        """FrequencyTrackingService.java:131-134."""
        self._frequencies.clear()

    # ---- exact in-process state save/load (crash-containment rollback) ---

    def _save_state(self) -> dict[str, list[float]]:
        """Raw timestamp copy — exact, process-local (cf. :meth:`snapshot`,
        which is portable but clock-relative)."""
        return {pid: list(f._timestamps) for pid, f in self._frequencies.items()}

    def _load_state(self, state: dict[str, list[float]]) -> None:
        self._frequencies.clear()
        for pid, timestamps in state.items():
            freq = PatternFrequency(
                self.config.frequency_time_window_hours * 3600.0, clock=self.clock
            )
            freq._timestamps = list(timestamps)
            self._frequencies[pid] = freq

    # ---- snapshot/restore (SURVEY.md §5.4 — the reference loses this state
    # on restart; here it can round-trip across processes) -----------------

    def snapshot(self) -> dict[str, list[float]]:
        """Portable snapshot: per pattern id, the *age* in seconds of every
        in-window match (ages, not raw clock values — the monotonic clock
        is process-local)."""
        now = self.clock()
        out: dict[str, list[float]] = {}
        for pid, freq in self._frequencies.items():
            freq._prune(now)
            # A backwards wall step (NTP slew, VM pause) can leave recorded
            # timestamps ahead of `now`; the resulting negative age would be
            # rejected by restore() on the peer and brick replica seeding.
            # Clamp to zero: "matched just now" is the honest floor.
            out[pid] = [max(0.0, now - ts) for ts in freq._timestamps]
        return out

    def restore(self, ages: dict[str, list[float]]) -> None:
        """Rebuild tracker state from :meth:`snapshot` output: the snapshot
        REPLACES all existing state (ids absent from the payload are
        cleared — restore-onto-warm-engine must not produce a hybrid).
        Ages beyond the window are dropped on the next prune; negative ages
        (timestamps in the future, which would never prune and would
        inflate windowed counts forever) are rejected up front."""
        for age_list in ages.values():
            for a in age_list:
                if not (float(a) >= 0.0):  # also rejects NaN
                    raise SnapshotValidationError(
                        f"negative age in frequency snapshot: {a!r}"
                    )
        now = self.clock()
        self._frequencies.clear()
        for pid, age_list in ages.items():
            if not pid or not pid.strip():
                continue
            freq = PatternFrequency(
                self.config.frequency_time_window_hours * 3600.0, clock=self.clock
            )
            freq._timestamps = sorted(now - float(a) for a in age_list)
            self._frequencies[pid] = freq


def calculate_context_factor(context: EventContext | None, config: ScoringConfig) -> float:
    """ContextAnalysisService.java:46-117 — context factor with the else-if,
    the capped stack bonus, the density penalty, and the cap."""
    if context is None:
        return 1.0
    all_lines: list[str] = []
    if context.lines_before is not None:
        all_lines.extend(context.lines_before)
    if context.matched_line is not None:
        all_lines.append(context.matched_line)
    if context.lines_after is not None:
        all_lines.extend(context.lines_after)
    if not all_lines:
        return 1.0

    context_score = 0.0
    error_lines = warn_lines = stack_lines = exception_lines = 0
    for line in all_lines:
        if ERROR_PATTERN.search(line):
            error_lines += 1
            context_score += ERROR_WEIGHT
        elif WARN_PATTERN.search(line):
            warn_lines += 1
            context_score += WARN_WEIGHT
        if STACK_TRACE_PATTERN.search(line):
            stack_lines += 1
            context_score += STACK_WEIGHT
        if EXCEPTION_PATTERN.search(line):
            exception_lines += 1
            context_score += EXCEPTION_WEIGHT

    if stack_lines > 0:
        context_score += min(stack_lines * STACK_WEIGHT, STACK_BONUS_CAP)

    total = len(all_lines)
    if total > DENSITY_MIN_LINES and (stack_lines + error_lines) > total * DENSITY_RATIO:
        context_score *= DENSITY_PENALTY

    return min(1.0 + context_score, config.context_max_context_factor)


class GoldenAnalyzer:
    """The full reference pipeline: compile → match → score → assemble.

    Patterns are compiled exactly once, at construction (the documented intent
    of the reference — docs/SCORING_ALGORITHM.md:186 — rather than its actual
    per-request recompilation, AnalysisService.java:55-86).
    """

    def __init__(
        self,
        pattern_sets: list[PatternSet],
        config: ScoringConfig | None = None,
        clock: Callable[[], float] = pclock.mono,
    ):
        self.pattern_sets = pattern_sets
        self.config = config or ScoringConfig()
        self.frequency = GoldenFrequencyTracker(self.config, clock=clock)
        self._compiled: dict[str, re.Pattern[str]] = {}
        # flat (pattern, compiled primary) list in discovery order — set-major
        # then pattern order (AnalysisService.java:91-92) — hoisted out of the
        # per-line hot loop
        self._primaries: list[tuple[Pattern, re.Pattern[str]]] = []
        # patterns whose regexes this engine cannot express (e.g. possessive
        # quantifiers): logged and skipped per-pattern so one bad pattern
        # never takes down the whole library — mirroring the loader's
        # skip-bad-file resilience (PatternService.java:82-84). A documented
        # divergence: the JVM reference would compile and match these.
        self.skipped_patterns: list[tuple[str, str]] = []
        for ps in pattern_sets:
            for pattern in ps.patterns or []:
                try:
                    if pattern.primary_pattern is not None:
                        compiled = self._compile(pattern.primary_pattern.regex)
                    for sec in pattern.secondary_patterns or []:
                        self._compile(sec.regex)
                    for seq in pattern.sequence_patterns or []:
                        for ev in seq.events or []:
                            self._compile(ev.regex)
                except (ValueError, re.error) as exc:
                    log.error("Skipping pattern %r: %s", pattern.id, exc)
                    self.skipped_patterns.append((pattern.id, str(exc)))
                    continue
                if pattern.primary_pattern is not None:
                    self._primaries.append((pattern, compiled))

    def _compile(self, regex: str) -> re.Pattern[str]:
        pat = self._compiled.get(regex)
        if pat is None:
            pat = compile_java_regex(regex)
            self._compiled[regex] = pat
        return pat

    # ------------------------------------------------------------------ match

    def analyze(self, data: PodFailureData) -> AnalysisResult:
        """AnalysisService.java:50-122."""
        start = pclock.mono()
        lines = java_split_lines(data.logs or "")
        events: list[MatchedEvent] = []

        for line_idx, line in enumerate(lines):
            for pattern, compiled in self._primaries:
                if compiled.search(line):
                    event = MatchedEvent(
                        line_number=line_idx + 1,
                        matched_pattern=pattern,
                        context=self._extract_context(lines, line_idx, pattern),
                    )
                    event.score = self.calculate_score(event, lines)
                    events.append(event)

        result = AnalysisResult(
            events=events,
            analysis_id=str(uuid.uuid4()),
            metadata=self._build_metadata(start, lines),
            summary=self._build_summary(events),
        )
        return result

    def _extract_context(
        self, lines: list[str], match_idx: int, pattern: Pattern
    ) -> EventContext:
        return extract_context(lines, match_idx, pattern)

    # ---------------------------------------------------------------- scoring

    def calculate_score(self, event: MatchedEvent, lines: list[str]) -> float:
        """ScoringService.java:63-112 — the seven-factor product, with the
        frequency penalty read before the match is recorded (:84-88)."""
        pattern = event.matched_pattern
        assert pattern is not None and pattern.primary_pattern is not None
        base_confidence = pattern.primary_pattern.confidence
        severity_multiplier = SEVERITY_MULTIPLIERS.get((pattern.severity or "").upper(), 1.0)
        chronological = self._chronological_factor(event, lines)
        proximity = self._proximity_factor(event, lines)
        temporal = self._temporal_factor(event, lines)
        context = calculate_context_factor(event.context, self.config)
        penalty = self.frequency.calculate_frequency_penalty(pattern.id)
        self.frequency.record_pattern_match(pattern.id)
        return (
            base_confidence
            * severity_multiplier
            * chronological
            * proximity
            * temporal
            * context
            * (1.0 - penalty)
        )

    def _chronological_factor(self, event: MatchedEvent, lines: list[str]) -> float:
        """ScoringService.java:123-151 — three-zone piecewise linear."""
        cfg = self.config
        idx = event.line_number - 1
        position = idx / len(lines)
        # java_div: zero-valued thresholds divide by zero without throwing
        # (Java double semantics), matching the reference's behavior exactly
        if position <= cfg.chronological_early_bonus_threshold:
            bonus_range = cfg.chronological_max_early_bonus - 1.5
            return 1.5 + (cfg.chronological_early_bonus_threshold - position) * java_div(
                bonus_range, cfg.chronological_early_bonus_threshold
            )
        if position <= cfg.chronological_penalty_threshold:
            middle = (
                cfg.chronological_penalty_threshold - cfg.chronological_early_bonus_threshold
            )
            return 1.0 + (cfg.chronological_penalty_threshold - position) * java_div(0.5, middle)
        return 0.5 + (1.0 - position)

    def _proximity_factor(self, event: MatchedEvent, lines: list[str]) -> float:
        """ScoringService.java:161-190 — weighted exponential decay over the
        closest occurrence of each secondary pattern."""
        pattern = event.matched_pattern
        assert pattern is not None
        secondaries = pattern.secondary_patterns
        if not secondaries:
            return 1.0
        total = 0.0
        primary_idx = event.line_number - 1
        for sec in secondaries:
            distance = self._closest_secondary_distance(sec.regex, sec.proximity_window,
                                                        primary_idx, lines)
            if distance >= 0:
                total += sec.weight * math.exp(-distance / self.config.proximity_decay_constant)
        return 1.0 + total

    def _closest_secondary_distance(
        self, regex: str, proximity_window: int, primary_idx: int, lines: list[str]
    ) -> float:
        """ScoringService.java:315-347 — window = min(max_window, pattern
        window), primary line excluded."""
        window = min(self.config.proximity_max_window, proximity_window)
        start = max(0, primary_idx - window)
        end = min(len(lines), primary_idx + window + 1)
        compiled = self._compile(regex)
        closest = -1.0
        for i in range(start, end):
            if i == primary_idx:
                continue
            if compiled.search(lines[i]):
                distance = float(abs(i - primary_idx))
                if closest < 0 or distance < closest:
                    closest = distance
        return closest

    def _temporal_factor(self, event: MatchedEvent, lines: list[str]) -> float:
        """ScoringService.java:199-220."""
        pattern = event.matched_pattern
        assert pattern is not None
        sequences = pattern.sequence_patterns
        if not sequences:
            return 1.0
        total = 0.0
        for seq in sequences:
            if self._is_sequence_matched(seq, event, lines):
                total += seq.bonus_multiplier
        return 1.0 + total

    def _is_sequence_matched(self, sequence, event: MatchedEvent, lines: list[str]) -> bool:
        """ScoringService.java:230-262 — work backwards from the primary:
        the last event must sit within ±5 lines of the primary (:272-286);
        each earlier event must occur strictly before the previously found
        one, taking the nearest preceding occurrence (:296-305). Note the
        search index resets to the *primary* line after the near-window check
        (:250), not to where the last event actually matched."""
        events = sequence.events
        if not events:
            return False
        primary_idx = event.line_number - 1
        current = 0
        for i in range(len(events) - 1, -1, -1):
            seq_event = events[i]
            compiled = self._compile(seq_event.regex)
            if i == len(events) - 1:
                if not self._found_near(compiled, primary_idx, lines):
                    return False
                current = primary_idx
            else:
                found = self._find_before(compiled, current, lines)
                if found < 0:
                    return False
                current = found
        return True

    def _found_near(self, compiled: re.Pattern[str], primary_idx: int, lines: list[str]) -> bool:
        """ScoringService.java:272-286 — ±5-line window, clamped."""
        start = max(0, primary_idx - SEQUENCE_NEAR_WINDOW)
        end = min(len(lines), primary_idx + SEQUENCE_NEAR_WINDOW + 1)
        return any(compiled.search(lines[i]) for i in range(start, end))

    def _find_before(self, compiled: re.Pattern[str], before_idx: int, lines: list[str]) -> int:
        """ScoringService.java:296-305 — backward scan, nearest hit wins."""
        for i in range(before_idx - 1, -1, -1):
            if compiled.search(lines[i]):
                return i
        return -1

    # --------------------------------------------------------------- assembly

    def _build_metadata(self, start: float, lines: list[str]) -> AnalysisMetadata:
        return build_metadata(start, len(lines), self.pattern_sets)

    def _build_summary(self, events: list[MatchedEvent]) -> AnalysisSummary:
        return build_summary(events)


def build_metadata(
    start_monotonic: float, total_lines: int, pattern_sets: list[PatternSet]
) -> AnalysisMetadata:
    """AnalysisService.java:166-180 — patterns_used lists every loaded
    library id, matched or not."""
    return AnalysisMetadata(
        processing_time_ms=int((pclock.mono() - start_monotonic) * 1000),
        total_lines=total_lines,
        analyzed_at=datetime.datetime.now(datetime.timezone.utc).isoformat(),
        patterns_used=[
            ps.metadata.library_id if ps.metadata else None  # type: ignore[misc]
            for ps in pattern_sets
        ],
    )


def build_summary(events: list[MatchedEvent]) -> AnalysisSummary:
    """AnalysisService.java:188-215 — unknown severities rank below INFO
    (indexOf == -1)."""
    summary = AnalysisSummary(significant_events=len(events))
    if not events:
        summary.highest_severity = "NONE"
        summary.severity_distribution = {}
        return summary
    severities = [
        (e.matched_pattern.severity or "").upper() for e in events  # type: ignore[union-attr]
    ]
    distribution: dict[str, int] = {}
    for sev in severities:
        distribution[sev] = distribution.get(sev, 0) + 1
    summary.severity_distribution = distribution

    def rank(sev: str) -> int:
        return SEVERITY_ORDER.index(sev) if sev in SEVERITY_ORDER else -1

    summary.highest_severity = max(severities, key=rank)
    return summary


def extract_context(lines: list[str], match_idx: int, pattern: Pattern) -> EventContext:
    """AnalysisService.java:132-156 — shared by golden and TPU engines."""
    context = EventContext(matched_line=lines[match_idx])
    rules = pattern.context_extraction
    if rules is None:
        return context
    before_start = max(0, match_idx - rules.lines_before)
    context.lines_before = lines[before_start:match_idx]
    after_end = min(len(lines), match_idx + 1 + rules.lines_after)
    context.lines_after = lines[match_idx + 1 : after_end]
    return context
