"""Java-compatibility primitives: string splitting and the regex dialect.

Two behaviors of the JVM leak into the reference's observable semantics and
must be replicated bit-for-bit:

1. ``String.split("\\r?\\n")`` (AnalysisService.java:53) removes *trailing*
   empty strings from the result, and splitting the empty string yields
   ``[""]`` (one empty element). ``"a\\n\\n".split`` → ``["a"]``;
   ``"\\n\\n".split`` → ``[]``.

2. ``java.util.regex`` (AnalysisService.java:64) treats ``\\w``/``\\b``/
   ``\\d``/``\\s`` as ASCII classes by default, where Python 3's ``re`` is
   Unicode-aware. Compiling with ``re.ASCII`` restores Java's default
   semantics. ``Matcher.find()`` (AnalysisService.java:95) is substring
   search — Python's ``re.search``.
"""

from __future__ import annotations

import re

_LINE_SEP = re.compile(r"\r?\n")

# \p{Name} POSIX classes: stored as bare class *contents* so they can be
# spliced both standalone (wrapped in [...]) and inside a character class.
_POSIX_MAP = {
    "Alpha": "a-zA-Z",
    "Digit": "0-9",
    "Alnum": "a-zA-Z0-9",
    "Upper": "A-Z",
    "Lower": "a-z",
    "Space": r" \t\n\x0b\f\r",
    "Punct": r"!-/:-@\[-`{-~",
    "XDigit": "0-9a-fA-F",
}

_POSIX_RE = re.compile(r"\\([pP])\{(\w+)\}")
_NAMED_GROUP_RE = re.compile(r"\(\?<([A-Za-z][A-Za-z0-9]*)>")
_NAMED_BACKREF_RE = re.compile(r"\\k<([A-Za-z][A-Za-z0-9]*)>")
_BRACE_QUANT_RE = re.compile(r"\{\d+(?:,\d*)?\}")
_INLINE_FLAGS_RE = re.compile(r"\(\?[a-zA-Z-]+\)")


def java_split_lines(logs: str) -> list[str]:
    """``logs.split("\\r?\\n")`` with Java semantics (trailing empties dropped,
    empty input → one empty line)."""
    parts = _LINE_SEP.split(logs)
    if len(parts) == 1:
        # no separator found — Java returns the whole input, even if empty
        return parts
    while parts and parts[-1] == "":
        parts.pop()
    return parts


def translate_java_regex(pattern: str) -> str:
    """Translate the Java-regex dialect subset used by pattern libraries into
    an equivalent Python ``re`` pattern. Raises ``ValueError`` on constructs
    whose semantics cannot be preserved (possessive quantifiers, atomic
    groups, class unions/intersections, mid-pattern inline flags, unknown
    ``\\p`` classes).

    A character scanner — not regex-over-regex — so escapes (``C\\++`` is a
    literal ``+`` quantified, not possessive) and character-class context
    (``[?+]`` holds literals; ``[\\p{Alpha}_]`` splices class contents without
    nesting brackets) are handled correctly.

    Line-terminator semantics (input here is always one log line, which may
    contain a lone ``\\r`` but never ``\\n``): Java's default ``.`` excludes
    all line terminators where Python's excludes only ``\\n``, so ``.`` maps
    to ``[^\\n\\r\\x85\\u2028\\u2029]``; Java's ``$``/``\\Z`` match before a
    *final* line terminator where Python's ``$`` handles only ``\\n``, so
    both map to ``(?=\\r?\\Z)``; Java ``\\z`` is Python ``\\Z``.
    """
    out: list[str] = []
    i, n = 0, len(pattern)
    in_class = False

    def fail(what: str) -> ValueError:
        return ValueError(f"unsupported Java regex construct ({what}) in {pattern!r}")

    while i < n:
        c = pattern[i]
        if c == "\\":
            m = _POSIX_RE.match(pattern, i)
            if m:
                negated, name = m.group(1) == "P", m.group(2)
                if name not in _POSIX_MAP:
                    raise fail(f"\\p{{{name}}}")
                content = _POSIX_MAP[name]
                if in_class:
                    if negated:
                        raise fail("\\P inside character class")
                    out.append(content)
                else:
                    out.append(("[^" if negated else "[") + content + "]")
                i = m.end()
                continue
            m = _NAMED_BACKREF_RE.match(pattern, i)
            if m:  # Java \k<name> -> Python (?P=name)
                out.append(f"(?P={m.group(1)})")
                i = m.end()
                continue
            nxt = pattern[i + 1] if i + 1 < n else ""
            if not in_class:
                if nxt == "z":  # Java \z (absolute end) = Python \Z
                    out.append(r"\Z")
                    i += 2
                    continue
                if nxt == "Z":  # Java \Z (before final terminator)
                    out.append(r"(?=\r?\Z)")
                    i += 2
                    continue
                if nxt == "Q":
                    # Java \Q...\E literal quoting: Python re has no \Q,
                    # so splice the quoted run in escaped. Passing \Q
                    # through made re.compile reject and the whole
                    # pattern skip at boot — a parity gap against the
                    # Java engine, which accepts these. (In-class \Q is
                    # left alone: the device parser reads it as a
                    # literal 'Q' there, and the skip keeps both sides
                    # consistent.)
                    end = pattern.find("\\E", i + 2)
                    content = pattern[i + 2 : end if end >= 0 else n]
                    escaped = re.escape(content)
                    if escaped and escaped[0].isdigit():
                        # a bare leading digit could merge into a
                        # preceding numeric token (\1 + "2" -> \12, a
                        # different backreference): emit it as \xNN
                        escaped = f"\\x{ord(escaped[0]):02x}" + escaped[1:]
                    out.append(escaped)
                    i = (end + 2) if end >= 0 else n
                    continue
            out.append(pattern[i : i + 2])
            i += 2
            continue
        if in_class:
            if c == "]":
                in_class = False
            elif c == "[":
                raise fail("nested character class")
            elif c == "&" and pattern.startswith("&&", i):
                raise fail("class intersection &&")
            out.append(c)
            i += 1
            continue
        if c == "[":
            in_class = True
            out.append(c)
            i += 1
            if i < n and pattern[i] == "^":
                out.append("^")
                i += 1
            continue
        if c == ".":
            # Java default '.' excludes all line terminators
            out.append(r"[^\n\r\x85  ]")
            i += 1
            continue
        if c == "$":
            # Java $ (non-MULTILINE): end of input or before final terminator
            out.append(r"(?=\r?\Z)")
            i += 1
            continue
        if c == "(":
            if pattern.startswith("(?>", i):
                raise fail("atomic group")
            m = _NAMED_GROUP_RE.match(pattern, i)
            if m:  # Java (?<name>...) -> Python (?P<name>...)
                out.append(f"(?P<{m.group(1)}>")
                i = m.end()
                continue
            m = _INLINE_FLAGS_RE.match(pattern, i)
            if m and i > 0:
                # Python only allows global inline flags at position 0, and
                # Java scopes them to the enclosing group — unpreservable
                raise fail(f"mid-pattern inline flags {m.group(0)}")
            out.append(c)
            i += 1
            continue
        if c in "*+?":
            out.append(c)
            i += 1
            if i < n and pattern[i] == "+":
                raise fail("possessive quantifier")
            if i < n and pattern[i] == "?":  # lazy — same in Python
                out.append("?")
                i += 1
            continue
        if c == "{":
            m = _BRACE_QUANT_RE.match(pattern, i)
            if m:
                out.append(m.group(0))
                i = m.end()
                if i < n and pattern[i] == "+":
                    raise fail("possessive quantifier")
                continue
            out.append(c)
            i += 1
            continue
        out.append(c)
        i += 1
    return "".join(out)


def compile_java_regex(pattern: str, case_insensitive: bool = False) -> re.Pattern[str]:
    """Compile a Java-dialect regex with Java's default semantics
    (ASCII ``\\w``/``\\b``/``\\d``/``\\s``; Pattern.CASE_INSENSITIVE optional)."""
    flags = re.ASCII
    if case_insensitive:
        flags |= re.IGNORECASE
    return re.compile(translate_java_regex(pattern), flags)
