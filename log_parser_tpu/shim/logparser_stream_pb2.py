"""Message classes for ``proto/logparser_stream.proto`` — built by hand.

``logparser_pb2.py`` ships as protoc output (a serialized-descriptor
blob), but this image has no ``grpc_tools``/``protoc`` to regenerate it,
so the streaming messages register their :class:`FileDescriptorProto`
programmatically in the same default descriptor pool. The resulting
classes are wire-identical to what protoc would generate from the
``.proto`` (same package, field numbers, and types) — a JVM client
generates its stubs from ``proto/logparser_stream.proto`` with protoc as
usual and the bytes interoperate.

Two messages only; the frame payload stays JSON (the exact NDJSON frame
dicts of runtime/stream.py) so the schema evolves with FRAME_TYPES
without a protoc round-trip on either side.
"""

from __future__ import annotations

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

_FILE = "logparser_stream.proto"
_PACKAGE = "logparser"


def _file_descriptor_proto() -> descriptor_pb2.FileDescriptorProto:
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = _FILE
    fdp.package = _PACKAGE
    fdp.syntax = "proto3"

    fld = descriptor_pb2.FieldDescriptorProto
    chunk = fdp.message_type.add()
    chunk.name = "StreamChunk"
    f = chunk.field.add()
    f.name, f.number = "data", 1
    f.type, f.label = fld.TYPE_BYTES, fld.LABEL_OPTIONAL
    f = chunk.field.add()
    f.name, f.number = "close", 2
    f.type, f.label = fld.TYPE_BOOL, fld.LABEL_OPTIONAL

    frame = fdp.message_type.add()
    frame.name = "StreamFrame"
    f = frame.field.add()
    f.name, f.number = "json", 1
    f.type, f.label = fld.TYPE_STRING, fld.LABEL_OPTIONAL

    svc = fdp.service.add()
    svc.name = "LogParserStream"
    m = svc.method.add()
    m.name = "StreamParse"
    m.input_type = f".{_PACKAGE}.StreamChunk"
    m.output_type = f".{_PACKAGE}.StreamFrame"
    m.client_streaming = True
    m.server_streaming = True
    return fdp


_pool = descriptor_pool.Default()
try:
    _file_desc = _pool.FindFileByName(_FILE)
except KeyError:
    _file_desc = _pool.Add(_file_descriptor_proto())

StreamChunk = message_factory.GetMessageClass(
    _file_desc.message_types_by_name["StreamChunk"]
)
StreamFrame = message_factory.GetMessageClass(
    _file_desc.message_types_by_name["StreamFrame"]
)
