"""Transport-neutral implementation of the ``LogParser`` contract
(proto/logparser.proto) — shared by the framed-socket shim (server.py) and
the gRPC server (grpc_server.py).

One instance wraps one engine. Parse runs PIPELINED (ingest + device work
outside the engine's ``state_lock``; only the frequency-coupled finish
phase serializes — serve/http.py documents the scheme). The frequency
admin surface (mirroring FrequencyTrackingService.java:101-134) serializes
on the same engine-wide lock, shared with the HTTP front-end.
"""

from __future__ import annotations

import json
import time

from log_parser_tpu import _clock as pclock
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.runtime import faults
from log_parser_tpu.runtime.quarantine import QuarantineRejected
from log_parser_tpu.runtime.tenancy import TenantError, TenantRegistry
from log_parser_tpu.serve.admission import AdmissionRejected, shared_gate
from log_parser_tpu.shim import logparser_pb2 as pb


class InvalidPodError(ValueError):
    """Null/absent pod — the client error of Parse.java:45-49."""

    def __init__(self) -> None:
        super().__init__("Invalid PodFailureData provided")


# The closed set of exception types transports classify as CLIENT errors
# (gRPC INVALID_ARGUMENT / quiet shim error frames). Deliberately narrow:
# a broad `except ValueError` here would misclassify internal bugs — e.g.
# numpy shape mismatches in device assembly — as the caller's fault and
# swallow their tracebacks (ADVICE.md r2).
from log_parser_tpu.golden.engine import SnapshotValidationError  # noqa: E402

CLIENT_ERRORS = (
    InvalidPodError,
    SnapshotValidationError,
    json.JSONDecodeError,
    TenantError,
)


class LogParserService:
    """The six RPC bodies, protobuf-in/protobuf-out.

    Tenancy: every RPC takes an optional ``tenant_id`` resolved through
    the shared :class:`~log_parser_tpu.runtime.tenancy.TenantRegistry`
    (framed shim: ``method@tenant`` envelope suffix; gRPC: ``x-tenant``
    metadata). None runs as the default tenant — the engine this service
    wrapped — so tenant-unaware clients are untouched."""

    def __init__(self, engine, tenants: TenantRegistry | None = None):
        self.engine = engine
        # the engine's own state lock — one lock across every transport
        self.lock = engine.state_lock
        # ... and the engine's one admission gate (serve/admission.py):
        # saturating the shim sheds on HTTP and vice versa
        self.admission = shared_gate(engine)
        self.tenants = (
            tenants
            if tenants is not None
            else TenantRegistry(engine, gate=self.admission)
        )

    def _ctx(self, tenant_id):
        """Resolve to a PINNED context — every RPC body unpins it in a
        ``finally`` once the request is answered, so LRU eviction can
        never close the engine under a request in flight (including the
        stretch before admission.acquire)."""
        return self.tenants.resolve(tenant_id)

    # ----------------------------------------------------------------- parse

    def parse(
        self,
        req: pb.ParseRequest,
        tenant_id: str | None = None,
        request_id: str | None = None,
        transport: str = "shim",
    ) -> pb.ParseResponse:
        obs = getattr(self.engine, "obs", None)
        if obs is not None:
            request_id = obs.clean_request_id(request_id) or obs.new_request_id()
        started = pclock.mono()
        # holder lets _parse_leased report the admitted route back out so
        # the finally arm labels the request correctly on every outcome
        holder = {"route": "device"}
        status = 200
        detail = None
        try:
            faults.fire("shim")
            tctx = self._ctx(tenant_id)
            try:
                return self._parse_leased(req, tctx, request_id, holder)
            finally:
                tctx.unpin()
        except AdmissionRejected as exc:
            holder["route"] = "admission"
            status, detail = exc.status, exc.reason
            raise
        except QuarantineRejected as exc:
            status, detail = exc.status, "quarantined"
            raise
        except TenantError as exc:
            # keep the real tenant status in the trace ring — a migrated
            # tenant (307, TenantForwarded) must not be counted as a 400;
            # the exception message carries the new owner's URL for the
            # transport envelope (framed error frame / gRPC UNAVAILABLE)
            status, detail = exc.status, type(exc).__name__
            raise
        except CLIENT_ERRORS as exc:
            status, detail = 400, type(exc).__name__
            raise
        except Exception as exc:
            status, detail = 500, type(exc).__name__
            raise
        finally:
            if obs is not None:
                obs.note_request(
                    transport,
                    holder["route"],
                    status,
                    tenant_id or "default",
                    pclock.mono() - started,
                    request_id=request_id,
                    detail=detail,
                )

    def _parse_leased(
        self,
        req: pb.ParseRequest,
        tctx,
        request_id: str | None = None,
        holder: dict | None = None,
    ) -> pb.ParseResponse:
        engine = tctx.engine
        pod = json.loads(req.pod_json) if req.pod_json else None
        if pod is None:
            raise InvalidPodError()
        data = PodFailureData(pod=pod, logs=req.logs)
        # the shared gate may shed (AdmissionRejected propagates to the
        # transport: error envelope / RESOURCE_EXHAUSTED) or route this
        # request to the host path under pressure; the tenant quota
        # refines it exactly as on the HTTP path
        batcher = getattr(engine, "batcher", None)
        n_lines = (req.logs.count("\n") + 1) if req.logs else 0
        obs = getattr(engine, "obs", None)
        arrival = pclock.mono()
        try:
            route = self.admission.acquire(
                batchable=batcher is not None, tenant=tctx.quota,
                lines=n_lines,
            )
        except AdmissionRejected as exc:
            # the staged admission child attaches when parse()'s
            # note_request commits the shed request's trace
            if obs is not None and request_id:
                obs.spans.annotate(
                    request_id, "admission", pclock.mono() - arrival,
                    attrs={"verdict": exc.reason, "tenant": tctx.tenant_id},
                )
            raise
        if obs is not None and request_id:
            obs.spans.annotate(
                request_id, "admission", pclock.mono() - arrival,
                attrs={"verdict": route, "tenant": tctx.tenant_id},
            )
        if holder is not None:
            holder["route"] = (
                "host"
                if route == "host"
                else ("batched" if batcher is not None else "device")
            )
        try:
            if route == "host":
                result = engine.analyze_host_routed(data, request_id=request_id)
            elif batcher is not None:
                # micro-batching on (framed shim AND gRPC run through this
                # body): coalesce with concurrent arrivals under the
                # gate's default deadline budget
                result = engine.analyze_batched(
                    data,
                    self.admission.default_deadline_ms or None,
                    request_id=request_id,
                )
            else:
                # pipelined: only the finish phase takes self.lock (inside)
                result = engine.analyze_pipelined(data, request_id=request_id)
        finally:
            self.admission.release(tenant=tctx.quota)

        resp = pb.ParseResponse(analysis_id=result.analysis_id or "")
        for event in result.events:
            ctx = event.context
            pb_ctx = pb.EventContext()
            if ctx is not None:
                pb_ctx.matched_line = ctx.matched_line or ""
                if ctx.lines_before is not None:
                    pb_ctx.has_lines_before = True
                    pb_ctx.lines_before.extend(ctx.lines_before)
                if ctx.lines_after is not None:
                    pb_ctx.has_lines_after = True
                    pb_ctx.lines_after.extend(ctx.lines_after)
            resp.events.append(
                pb.MatchedEvent(
                    line_number=event.line_number,
                    pattern_json=json.dumps(
                        event.matched_pattern.to_dict(drop_none=True)
                    )
                    if event.matched_pattern is not None
                    else "",
                    context=pb_ctx,
                    score=event.score,
                )
            )
        md = result.metadata
        if md is not None:
            resp.metadata.processing_time_ms = md.processing_time_ms or 0
            resp.metadata.total_lines = md.total_lines or 0
            resp.metadata.analyzed_at = md.analyzed_at or ""
            resp.metadata.patterns_used.extend(
                x or "" for x in (md.patterns_used or [])
            )
        sm = result.summary
        if sm is not None:
            resp.summary.significant_events = sm.significant_events or 0
            resp.summary.highest_severity = sm.highest_severity or ""
            for sev, count in (sm.severity_distribution or {}).items():
                resp.summary.severity_distribution[sev] = count
        return resp

    # ---------------------------------------------------- health + frequency

    def health(
        self, req: pb.HealthRequest, tenant_id: str | None = None
    ) -> pb.HealthResponse:
        return pb.HealthResponse(status="UP")

    def frequency_stats(
        self, req: pb.FrequencyStatsRequest, tenant_id: str | None = None
    ) -> pb.FrequencyStatsResponse:
        tctx = self._ctx(tenant_id)
        try:
            eng = tctx.engine
            with eng.state_lock:
                stats = eng.frequency.get_frequency_statistics()
            return pb.FrequencyStatsResponse(windowed_counts=stats)
        finally:
            tctx.unpin()

    def frequency_reset(
        self, req: pb.FrequencyResetRequest, tenant_id: str | None = None
    ) -> pb.FrequencyResetResponse:
        tctx = self._ctx(tenant_id)
        try:
            eng = tctx.engine
            with eng.state_lock:
                if req.pattern_id:
                    eng.frequency.reset_pattern_frequency(req.pattern_id)
                else:
                    eng.frequency.reset_all_frequencies()
            return pb.FrequencyResetResponse()
        finally:
            tctx.unpin()

    def frequency_snapshot(
        self, req: pb.FrequencySnapshotRequest, tenant_id: str | None = None
    ) -> pb.FrequencySnapshotResponse:
        resp = pb.FrequencySnapshotResponse()
        tctx = self._ctx(tenant_id)
        try:
            eng = tctx.engine
            with eng.state_lock:
                snap = eng.frequency.snapshot()
            for pid, ages in snap.items():
                resp.ages[pid].ages_seconds.extend(ages)
            return resp
        finally:
            tctx.unpin()

    def frequency_restore(
        self, req: pb.FrequencyRestoreRequest, tenant_id: str | None = None
    ) -> pb.FrequencyRestoreResponse:
        tctx = self._ctx(tenant_id)
        try:
            eng = tctx.engine
            with eng.state_lock:
                eng.frequency.restore(
                    {pid: list(al.ages_seconds) for pid, al in req.ages.items()}
                )
            return pb.FrequencyRestoreResponse()
        finally:
            tctx.unpin()


# (method name, request type, response type) — the service surface, used by
# both transports to build their dispatch tables
RPCS = (
    ("Parse", pb.ParseRequest, pb.ParseResponse, "parse"),
    ("Health", pb.HealthRequest, pb.HealthResponse, "health"),
    ("FrequencyStats", pb.FrequencyStatsRequest, pb.FrequencyStatsResponse,
     "frequency_stats"),
    ("FrequencyReset", pb.FrequencyResetRequest, pb.FrequencyResetResponse,
     "frequency_reset"),
    ("FrequencySnapshot", pb.FrequencySnapshotRequest,
     pb.FrequencySnapshotResponse, "frequency_snapshot"),
    ("FrequencyRestore", pb.FrequencyRestoreRequest,
     pb.FrequencyRestoreResponse, "frequency_restore"),
)
