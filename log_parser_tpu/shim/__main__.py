"""CLI: ``python -m log_parser_tpu.shim --pattern-dir /shared/patterns``.

Runs the TPU backend behind the framed-protobuf shim contract on :9090 —
the process the reference's JVM front-end delegates its hot loop to.
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import os
import sys

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.patterns import load_pattern_directory
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.shim.server import make_shim_server


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="log_parser_tpu.shim")
    parser.add_argument("--pattern-dir", help="pattern YAML directory (pattern.directory)")
    parser.add_argument("--config", help="Java .properties config file")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=9090)
    parser.add_argument(
        "--grpc-port",
        type=int,
        default=None,
        help="also serve standard gRPC (service LogParser) on this port",
    )
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument(
        "--device-timeout",
        type=float,
        default=None,
        metavar="SECONDS",
        help="watchdog deadline for the device step (see serve --help)",
    )
    parser.add_argument(
        "--max-inflight", type=int, default=None,
        help="bound on concurrently-executing parses (see serve --help)",
    )
    parser.add_argument(
        "--max-queue", type=int, default=None,
        help="bound on queued parses before shedding (see serve --help)",
    )
    parser.add_argument(
        "--deadline-ms", type=float, default=None,
        help="default per-request deadline (see serve --help)",
    )
    parser.add_argument(
        "--drain-s", type=float, default=None,
        help="SIGTERM drain deadline (see serve --help)",
    )
    parser.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="fault-injection DSL (see serve --help)",
    )
    parser.add_argument("--fault-seed", type=int, default=None)
    args = parser.parse_args(argv)
    if args.device_timeout is not None:
        os.environ["LOG_PARSER_TPU_DEVICE_TIMEOUT_S"] = str(args.device_timeout)
    for flag, env_key in (
        (args.max_inflight, "LOG_PARSER_TPU_MAX_INFLIGHT"),
        (args.max_queue, "LOG_PARSER_TPU_MAX_QUEUE"),
        (args.deadline_ms, "LOG_PARSER_TPU_DEADLINE_MS"),
        (args.drain_s, "LOG_PARSER_TPU_DRAIN_S"),
        (args.faults, "LOG_PARSER_TPU_FAULTS"),
        (args.fault_seed, "LOG_PARSER_TPU_FAULT_SEED"),
    ):
        if flag is not None:
            os.environ[env_key] = str(flag)

    logging.basicConfig(
        level=args.log_level.upper(),
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s",
    )
    log = logging.getLogger("log_parser_tpu.shim")

    config = (
        ScoringConfig.from_properties_file(args.config)
        if args.config
        else ScoringConfig.from_env()
    )
    if args.pattern_dir:
        config = dataclasses.replace(config, pattern_directory=args.pattern_dir)
    if not config.pattern_directory:
        log.error("pattern.directory is required (--pattern-dir / config / env)")
        return 2

    engine = AnalysisEngine(load_pattern_directory(config.pattern_directory), config)
    server = make_shim_server(engine, args.host, args.port)
    grpc_server = None
    if args.grpc_port is not None:
        from log_parser_tpu.shim.grpc_server import make_grpc_server

        # share the framed server's service so both transports serialize
        # engine + frequency access on the same lock
        grpc_server, bound = make_grpc_server(
            engine, args.host, args.grpc_port, service=server.service
        )
        grpc_server.start()
        log.info("Shim serving gRPC (logparser.LogParser) on %s:%d", args.host, bound)
    # same drain path as the HTTP front-end: SIGTERM/SIGINT flip the
    # shared gate (both shim transports refuse new parses), in-flight
    # work finishes, then the framed accept loop stops and gRPC follows
    from log_parser_tpu.serve.admission import install_drain_handlers

    install_drain_handlers(server, server.admission, log)
    log.info("Shim serving framed protobuf on %s:%d", args.host, args.port)
    try:
        server.serve_forever()
        log.info("Drained; shutting down")
    except KeyboardInterrupt:  # pre-handler-install window only
        log.info("Shutting down")
    finally:
        if grpc_server is not None:
            grpc_server.stop(grace=1.0)
        server.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
