"""Reference shim client — the executable documentation of the wire
protocol for the JVM implementer (protobuf-java + a Socket is all the
front-end needs).

Retry contract: every RPC the shim exposes is idempotent (Parse derives
everything from the request payload; frequency evolution is the server's
own windowed state, identical whether a retry lands once or the original
eventually dies with the connection), so the client retries connect/read
failures with exponential backoff + jitter, reconnecting between
attempts, up to a bounded budget. An ``overloaded: ...; retry after Ns``
error envelope (the framed-wire analogue of HTTP 429 + ``Retry-After``,
shim/server.py) is honored the same way: sleep the server's hint
(capped), then retry. ``last_attempts`` on the client records how many
attempts the most recent call consumed — the shim's metadata channel.

Forward-follow contract: a live migration (runtime/migrate.py) or a
standby fence (runtime/replicate.py) answers
``tenant 'x' migrated to <url>[; retry after Ns]`` — the framed
rendering of the HTTP 307 + ``Location`` + ``Retry-After``. The client
follows it: honor the pacing hint, resolve the HTTP ``Location`` to a
framed shim address (``forward_resolver``; the default assumes the new
owner serves its shim on THIS client's port at the Location's host),
reconnect there, and resend the same frame — bounded by ``max_hops``
with loop detection, so a forwarding cycle surfaces the error instead
of orbiting it. CLI and test clients survive a mid-run migration
without manual retry; ``last_hops`` records what the most recent call
followed.
"""

from __future__ import annotations

import json
import logging
import random
import re
import socket
import time

from log_parser_tpu import _clock as pclock
from log_parser_tpu.runtime import pressure
from log_parser_tpu.shim import logparser_pb2 as pb
from log_parser_tpu.shim.framing import read_frame, write_frame

log = logging.getLogger(__name__)

# shim/server.py sheds with str(AdmissionRejected):
#   "overloaded: <reason>; retry after <N>s"
_RETRY_AFTER = re.compile(r"retry after (\d+(?:\.\d+)?)s")
# shim/server.py forwards with TenantForwarded.reason (+ pacing):
#   "tenant 'x' migrated to <url>[; retry after <N>s]"
_FORWARDED = re.compile(r"migrated to (\S+?)[;,]?(?:\s|$)")


def default_forward_resolver(location: str, port: int) -> tuple[str, int] | None:
    """HTTP ``Location`` -> framed shim address: the fleet convention is
    one shim port fleet-wide, so the new owner's shim lives at the
    Location's host on the SAME port this client already uses. Deploys
    with per-backend shim ports pass an explicit ``forward_resolver``."""
    import urllib.parse

    try:
        parsed = urllib.parse.urlparse(location)
    except ValueError:
        return None
    if not parsed.hostname:
        return None
    return parsed.hostname, port


class ShimClient:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 9090,
        *,
        retries: int = 2,
        backoff_s: float = 0.05,
        retry_after_cap_s: float = 5.0,
        max_hops: int = 3,
        forward_resolver=None,
        sleep=pclock.sleep,
        retry_budget: pressure.RetryBudget | None = None,
    ):
        self.host = host
        self.port = port
        self.retries = max(0, int(retries))
        self.backoff_s = backoff_s
        self.retry_after_cap_s = retry_after_cap_s
        self.max_hops = max(0, int(max_hops))
        # forward_resolver(location_url) -> (host, port) shim address,
        # or None to refuse the hop; default: Location host, same port
        self.forward_resolver = forward_resolver or (
            lambda loc: default_forward_resolver(loc, self.port)
        )
        self._sleep = sleep
        # explicit budget, else whatever controller the process installed
        # (runtime/pressure.py); None from both means retries are free
        self._retry_budget = retry_budget
        self.sheds = 0  # retries refused by the budget
        self.last_attempts = 0  # attempts consumed by the most recent call
        self.last_hops = 0  # forwards followed by the most recent call
        self.sock: socket.socket | None = None
        self._connect_with_retry()

    # ------------------------------------------------------------ transport

    def _connect(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        sock = socket.create_connection((self.host, self.port))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock = sock

    def _budget(self) -> pressure.RetryBudget | None:
        return (
            self._retry_budget
            if self._retry_budget is not None
            else pressure.retry_budget()
        )

    def _retry_allowed(self) -> bool:
        """Spend one retry token toward the current address; False
        means the budget is dry and the retry must shed."""
        budget = self._budget()
        if budget is None or budget.allow(f"shim:{self.host}:{self.port}"):
            return True
        self.sheds += 1
        return False

    def _connect_with_retry(self) -> None:
        for attempt in range(self.retries + 1):
            try:
                self._connect()
                return
            except OSError as exc:
                if attempt >= self.retries:
                    raise
                if not self._retry_allowed():
                    log.debug(
                        "shim connect to %s:%d: retry budget exhausted",
                        self.host, self.port,
                    )
                    raise
                delay = self._delay(attempt)
                log.debug(
                    "shim connect to %s:%d failed (%s); retry in %.3fs",
                    self.host, self.port, exc, delay,
                )
                self._sleep(delay)

    def _delay(self, attempt: int) -> float:
        # exponential backoff + jitter so a fleet of clients re-arriving
        # after a shim restart does not re-arrive in lockstep
        return self.backoff_s * (2 ** attempt) * (1.0 + random.random())

    def close(self) -> None:
        if self.sock is not None:
            self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------ rpc

    def call(self, method: str, message) -> pb.Envelope:
        """One framed RPC with bounded retry AND bounded forward-follow
        (see module docstring). The request frame is built once and
        resent verbatim on each attempt and each hop."""
        payload = pb.Envelope(
            method=method, payload=message.SerializeToString()
        ).SerializeToString()
        budget = self._budget()
        if budget is not None:
            budget.note_request(f"shim:{self.host}:{self.port}")
        self.last_hops = 0
        seen = {(self.host, self.port)}
        env = self._call_once(method, payload)
        while self.last_hops < self.max_hops:
            hint = self._forward_hint(env)
            if hint is None:
                break
            location, wait = hint
            addr = self.forward_resolver(location)
            if addr is None or tuple(addr) in seen:
                # unresolvable Location or a forwarding loop: surface
                # the envelope rather than orbit the cycle
                break
            if wait > 0:
                self._sleep(min(wait, self.retry_after_cap_s))
            self.host, self.port = addr  # ownership moved: so do we
            seen.add(tuple(addr))
            self.last_hops += 1
            log.debug("shim %s following forward to %s:%d",
                      method, self.host, self.port)
            self._connect_with_retry()
            env = self._call_once(method, payload)
        return env

    def _call_once(self, method: str, payload: bytes) -> pb.Envelope:
        """The bounded-retry send against the CURRENT address."""
        env = pb.Envelope()
        for attempt in range(self.retries + 1):
            self.last_attempts = attempt + 1
            try:
                write_frame(self.sock, payload)
                frame = read_frame(self.sock)
                if frame is None:
                    raise ConnectionError("shim server closed the connection")
                env = pb.Envelope()
                env.ParseFromString(frame)
            except (ConnectionError, OSError) as exc:
                if attempt >= self.retries:
                    raise
                if not self._retry_allowed():
                    return pb.Envelope(
                        method=method, error="retry budget exhausted"
                    )
                delay = self._delay(attempt)
                log.debug(
                    "shim %s attempt %d failed (%s); reconnect + retry in %.3fs",
                    method, attempt + 1, exc, delay,
                )
                self._sleep(delay)
                try:
                    self._connect()
                except OSError:
                    pass  # the next write fails fast and consumes the attempt
                continue
            hint = self._overload_hint(env)
            if hint is not None and attempt < self.retries:
                if not self._retry_allowed():
                    return env  # dry budget: surface the shed envelope
                # shed, not failed: wait out the server's own hint
                self._sleep(min(hint, self.retry_after_cap_s))
                continue
            return env
        return env  # budget spent on sheds: hand the caller the envelope

    @staticmethod
    def _overload_hint(env: pb.Envelope) -> float | None:
        """Server-suggested backoff seconds from a shed envelope, else
        None (including errors that are real failures, not sheds)."""
        if not env.error.startswith("overloaded"):
            return None
        m = _RETRY_AFTER.search(env.error)
        return float(m.group(1)) if m else 1.0

    @staticmethod
    def _forward_hint(env: pb.Envelope) -> tuple[str, float] | None:
        """(Location, pacing seconds) from a forward envelope, else None."""
        m = _FORWARDED.search(env.error or "")
        if m is None:
            return None
        after = _RETRY_AFTER.search(env.error)
        return m.group(1), float(after.group(1)) if after else 0.0

    # ---------------------------------------------------------- convenience

    def parse(self, pod: dict | None, logs: str) -> pb.ParseResponse:
        env = self.call(
            "Parse",
            pb.ParseRequest(
                pod_json=json.dumps(pod) if pod is not None else "", logs=logs
            ),
        )
        if env.error:
            raise ValueError(env.error)
        resp = pb.ParseResponse()
        resp.ParseFromString(env.payload)
        return resp

    def health(self) -> str:
        env = self.call("Health", pb.HealthRequest())
        if env.error:
            raise ValueError(env.error)
        resp = pb.HealthResponse()
        resp.ParseFromString(env.payload)
        return resp.status
