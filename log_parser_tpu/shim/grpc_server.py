"""Standard gRPC transport for the ``LogParser`` service.

``proto/logparser.proto`` declares ``service LogParser``; a JVM front-end
(the reference's Quarkus app, pom.xml:47-59) generates Java stubs with
protoc + protoc-gen-grpc-java and calls these RPCs directly — no
hand-written socket code (VERDICT.md round-1 missing #5).

This image ships the ``grpcio`` runtime but not ``grpc_tools``, so the
Python side registers the service with :func:`grpc.method_handlers_generic_handler`
from the same RPC table the framed transport uses — wire-identical to what
generated ``_pb2_grpc`` stubs would produce (same method paths
``/logparser.LogParser/<Method>``, same protobuf framing). Import is gated
so environments without grpcio still get the framed transport.
"""

from __future__ import annotations

import json

from log_parser_tpu.runtime.quarantine import QuarantineRejected
from log_parser_tpu.runtime.tenancy import TenantError
from log_parser_tpu.serve.admission import AdmissionRejected
from log_parser_tpu.shim.service import CLIENT_ERRORS, RPCS, LogParserService

SERVICE_NAME = "logparser.LogParser"
STREAM_SERVICE_NAME = "logparser.LogParserStream"

try:  # gate: grpcio is present in this image but is not a hard dependency
    import grpc

    HAVE_GRPC = True
except ImportError:  # pragma: no cover
    grpc = None
    HAVE_GRPC = False


def _tenant_of(context) -> str | None:
    """Tenant id from ``x-tenant`` invocation metadata (the gRPC twin of
    the HTTP ``X-Tenant`` header); absent metadata is the default tenant."""
    for key, value in context.invocation_metadata() or ():
        if key == "x-tenant":
            return value or None
    return None


def _request_id_of(context) -> str | None:
    """Correlation id from ``x-request-id`` invocation metadata — the gRPC
    twin of the HTTP ``X-Request-Id`` header; the service mints one when
    absent."""
    for key, value in context.invocation_metadata() or ():
        if key == "x-request-id":
            return value or None
    return None


def _tenant_code(exc: TenantError):
    """Status for a refused tenant resolution: unknown tenant (404) is
    NOT_FOUND — a typo or a not-yet-provisioned tenant — while a
    malformed id (400) is INVALID_ARGUMENT, the same split the HTTP
    transport answers. A migrated-away tenant (307, TenantForwarded) is
    UNAVAILABLE: the status message carries the new owner's URL (same
    text the HTTP 307 body sends) so the caller can re-resolve — gRPC
    has no redirect status, and UNAVAILABLE is the retryable class."""
    if exc.status == 307:
        return grpc.StatusCode.UNAVAILABLE
    return (
        grpc.StatusCode.NOT_FOUND
        if exc.status == 404
        else grpc.StatusCode.INVALID_ARGUMENT
    )


def _handlers(service: LogParserService):
    def wrap(fn, is_parse=False):
        def unary(request, context):
            try:
                if is_parse:
                    # Parse carries the correlation id + transport label so
                    # the request lands in the shared trace ring and the
                    # requests_total{transport="grpc"} series
                    result = fn(
                        request,
                        tenant_id=_tenant_of(context),
                        request_id=_request_id_of(context),
                        transport="grpc",
                    )
                else:
                    result = fn(request, tenant_id=_tenant_of(context))
                if is_parse and not context.is_active():
                    # the caller cancelled / vanished while we computed:
                    # the response write is moot — same dropped-responses
                    # signal the HTTP and framed transports count
                    obs = getattr(service.engine, "obs", None)
                    if obs is not None:
                        obs.note_dropped("grpc")
                return result
            except AdmissionRejected as exc:
                # overload ladder: shed maps to RESOURCE_EXHAUSTED, a
                # draining server to UNAVAILABLE — both carry the retry
                # hint in the status message
                context.abort(
                    grpc.StatusCode.UNAVAILABLE
                    if exc.reason == "draining"
                    else grpc.StatusCode.RESOURCE_EXHAUSTED,
                    str(exc),
                )
            except QuarantineRejected as exc:
                # poison fingerprint whose golden path also failed: same
                # back-off semantics as a shed, scoped to one request
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(exc))
            except TenantError as exc:
                # before the CLIENT_ERRORS clause (TenantError is in it):
                # unknown tenant must surface as NOT_FOUND, not be
                # flattened into INVALID_ARGUMENT with the malformed ids
                context.abort(_tenant_code(exc), str(exc))
            except CLIENT_ERRORS as exc:
                # client errors only: null pod, malformed JSON, invalid
                # snapshot payloads. Internal bugs that surface as plain
                # ValueError must reach the INTERNAL branch with their
                # traceback (ADVICE.md r2).
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
            except Exception as exc:  # contained per request
                context.abort(grpc.StatusCode.INTERNAL, str(exc))

        return unary

    return {
        name: grpc.unary_unary_rpc_method_handler(
            wrap(getattr(service, attr), is_parse=(attr == "parse")),
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
        for name, req_t, resp_t, attr in RPCS
    }


def _stream_handlers(service: LogParserService):
    """The ``LogParserStream.StreamParse`` bidi handler: byte chunks in,
    JSON frames out — the gRPC twin of ``POST /parse/stream``. Both
    transports resolve :func:`~log_parser_tpu.runtime.stream.shared_manager`,
    so their sessions share one admission budget, TTL reaper, and
    ``/trace/last`` counter block. ``x-tenant`` metadata pins the session
    to that tenant's engine (and therefore its bank epoch) for its whole
    life, exactly like the HTTP stream path."""
    from log_parser_tpu.shim import logparser_stream_pb2 as spb

    def stream_parse(request_iterator, context):
        from log_parser_tpu.runtime.stream import shared_manager

        try:
            ctx = service.tenants.resolve(_tenant_of(context))
        except TenantError as exc:
            context.abort(_tenant_code(exc), str(exc))
        # the resolve lease holds until the RPC ends (generator close
        # included), so eviction can never shut this tenant's stream
        # manager down between resolution and the session open
        try:
            mgr = shared_manager(ctx.engine)
            try:
                sess = mgr.open()
            except AdmissionRejected as exc:
                context.abort(
                    grpc.StatusCode.UNAVAILABLE
                    if exc.reason == "draining"
                    else grpc.StatusCode.RESOURCE_EXHAUSTED,
                    str(exc),
                )
            try:
                for chunk in request_iterator:
                    if chunk.data:
                        for frame in sess.feed(bytes(chunk.data)):
                            yield spb.StreamFrame(json=json.dumps(frame))
                    if sess.closed:
                        # the session died on a fault/poison error frame:
                        # the frame already went out, end the RPC cleanly
                        return
                    if chunk.close:
                        break
                # explicit close chunk or client half-close: either way
                # the final frames (and any tail-line scoring) flush here
                for frame in sess.close():
                    yield spb.StreamFrame(json=json.dumps(frame))
            finally:
                if not sess.closed:
                    # client vanished mid-stream (cancel / network drop)
                    sess.kill("disconnect")
        finally:
            ctx.unpin()

    return {
        "StreamParse": grpc.stream_stream_rpc_method_handler(
            stream_parse,
            request_deserializer=spb.StreamChunk.FromString,
            response_serializer=spb.StreamFrame.SerializeToString,
        )
    }


def make_grpc_server(
    engine,
    host: str = "127.0.0.1",
    port: int = 9095,
    max_workers: int = 8,
    service: LogParserService | None = None,
    stream: bool = True,
    tenants=None,
):
    """Build (server, bound_port). Raises RuntimeError without grpcio.

    Pass ``service`` to share one :class:`LogParserService` (and therefore
    ONE engine lock) with another transport — required when the framed shim
    serves the same engine, or the two transports would race on frequency
    state through separate locks. ``stream=False`` leaves the
    ``LogParserStream`` service unregistered (UNIMPLEMENTED to callers) —
    for sharded/distributed engines, whose session layer is gated off the
    same way ``serve`` gates ``POST /parse/stream``."""
    if not HAVE_GRPC:
        raise RuntimeError(
            "grpcio is not installed; use the framed transport "
            "(log_parser_tpu.shim.make_shim_server) instead"
        )
    from concurrent import futures

    if service is None:
        service = LogParserService(engine, tenants=tenants)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    handlers = [grpc.method_handlers_generic_handler(SERVICE_NAME, _handlers(service))]
    if stream:
        handlers.append(
            grpc.method_handlers_generic_handler(
                STREAM_SERVICE_NAME, _stream_handlers(service)
            )
        )
    server.add_generic_rpc_handlers(tuple(handlers))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"could not bind gRPC server to {host}:{port}")
    return server, bound


def make_channel_stubs(channel):
    """Client-side callables for one channel, keyed by method name — the
    Python analogue of a generated stub (tests + local tooling)."""
    return {
        name: channel.unary_unary(
            f"/{SERVICE_NAME}/{name}",
            request_serializer=req_t.SerializeToString,
            response_deserializer=resp_t.FromString,
        )
        for name, req_t, resp_t, _attr in RPCS
    }


def make_stream_stub(channel):
    """Client-side ``StreamParse`` callable: pass an iterator of
    StreamChunk, iterate StreamFrame back."""
    from log_parser_tpu.shim import logparser_stream_pb2 as spb

    return channel.stream_stream(
        f"/{STREAM_SERVICE_NAME}/StreamParse",
        request_serializer=spb.StreamChunk.SerializeToString,
        response_deserializer=spb.StreamFrame.FromString,
    )
