"""JVM↔TPU shim: framed-protobuf contract (proto/logparser.proto).

``logparser_pb2`` is generated — regenerate after editing the proto:
``protoc --python_out=log_parser_tpu/shim --proto_path=proto proto/logparser.proto``
"""

from log_parser_tpu.shim.client import ShimClient
from log_parser_tpu.shim.grpc_server import (
    HAVE_GRPC,
    make_grpc_server,
    make_stream_stub,
)
from log_parser_tpu.shim.server import ShimServer, make_shim_server
from log_parser_tpu.shim.service import LogParserService

__all__ = [
    "HAVE_GRPC",
    "LogParserService",
    "ShimClient",
    "ShimServer",
    "make_grpc_server",
    "make_shim_server",
    "make_stream_stub",
]
