"""Framed-socket shim server: the TPU backend behind the Envelope contract.

This is the dependency-free transport of the north star's deployment shape:
the reference's Quarkus/common-lib front-end stays intact and forwards
``PodFailureData`` here instead of running the JVM hot loop; this server
answers with the full ``AnalysisResult`` (discovery-order events, exact
scores) plus the frequency admin surface. See proto/logparser.proto for
the contract and framing.py for the wire format; grpc_server.py exposes
the same :class:`~log_parser_tpu.shim.service.LogParserService` over
standard gRPC.
"""

from __future__ import annotations

import logging
import socketserver

from log_parser_tpu.runtime.quarantine import QuarantineRejected
from log_parser_tpu.runtime.tenancy import TenantForwarded
from log_parser_tpu.serve.admission import AdmissionRejected
from log_parser_tpu.shim import logparser_pb2 as pb
from log_parser_tpu.shim.framing import FramingError, read_frame, write_frame
from log_parser_tpu.shim.service import CLIENT_ERRORS, RPCS, LogParserService

log = logging.getLogger(__name__)


class ShimServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, address: tuple[str, int], engine, tenants=None):
        super().__init__(address, _Handler)
        self.service = LogParserService(engine, tenants=tenants)
        # dispatch: method name -> (request ctor, bound service method)
        self.dispatch = {
            name: (req_t, getattr(self.service, attr))
            for name, req_t, _resp_t, attr in RPCS
        }
        # warm-standby replication (runtime/replicate.py): set by the
        # embedding process to answer ReplicaFeed/Promote envelopes —
        # the framed twin of POST /admin/replica/feed + /admin/promote
        self.replicator = None

    @property
    def engine(self):
        return self.service.engine

    @property
    def analyze_lock(self):
        return self.service.lock

    @property
    def admission(self):
        return self.service.admission


class _Handler(socketserver.BaseRequestHandler):
    server: ShimServer

    def handle(self) -> None:
        sock = self.request
        while True:
            try:
                frame = read_frame(sock)
            except FramingError as exc:
                log.warning("shim connection dropped: %s", exc)
                return
            if frame is None:
                return
            envelope = pb.Envelope()
            try:
                envelope.ParseFromString(frame)
                # tenancy rides the envelope as a method suffix
                # ("Parse@acme") so the wire contract needs no new field;
                # bare methods run as the default tenant
                method, _, tenant = envelope.method.partition("@")
                if method == "Metrics":
                    # Prometheus text exposition over the framed transport:
                    # the same registry render the HTTP /metrics serves, so
                    # shim-only deployments scrape without a second port
                    obs = getattr(self.server.engine, "obs", None)
                    response = pb.Envelope(
                        method=envelope.method,
                        payload=(
                            obs.registry.render().encode()
                            if obs is not None
                            else b""
                        ),
                    )
                elif method in ("ReplicaFeed", "Promote"):
                    # replication protocol over the framed transport: the
                    # envelope payload is the same JSON body the HTTP admin
                    # routes take; a refusal answers payload=position JSON
                    # + error text so the sender re-syncs (or demotes) from
                    # the framed reply exactly like an HTTP 409 body
                    from log_parser_tpu.runtime.replicate import (
                        ReplicationError,
                    )

                    rep = self.server.replicator
                    if rep is None:
                        response = pb.Envelope(
                            method=envelope.method,
                            error="replication is not enabled",
                        )
                    else:
                        import json as _json

                        try:
                            body = _json.loads(
                                envelope.payload.decode("utf-8") or "{}"
                            )
                            doc = (
                                rep.feed(body)
                                if method == "ReplicaFeed"
                                else rep.promote(
                                    reason=str(body.get("reason") or "shim")
                                    if isinstance(body, dict)
                                    else "shim"
                                )
                            )
                            response = pb.Envelope(
                                method=envelope.method,
                                payload=_json.dumps(doc).encode(),
                            )
                        except ReplicationError as exc:
                            response = pb.Envelope(
                                method=envelope.method,
                                payload=_json.dumps(exc.to_json()).encode(),
                                error=str(exc),
                            )
                elif (entry := self.server.dispatch.get(method)) is None:
                    response = pb.Envelope(
                        method=envelope.method,
                        error=f"unknown method {method!r}",
                    )
                else:
                    req_t, fn = entry
                    req = req_t()
                    req.ParseFromString(envelope.payload)
                    response = pb.Envelope(
                        method=envelope.method,
                        payload=fn(
                            req, tenant_id=tenant or None
                        ).SerializeToString(),
                    )
            except (AdmissionRejected, QuarantineRejected) as exc:
                # expected under overload/drain (shed) or for a poison
                # fingerprint whose golden path also failed (quarantine):
                # shed quietly, the client reads the retry hint out of
                # the error text
                log.info("shim request shed on %s: %s", envelope.method, exc)
                response = pb.Envelope(method=envelope.method, error=str(exc))
            except TenantForwarded as exc:
                # the framed rendering of the HTTP 307: the Location is
                # already in the reason text, the Retry-After pacing is
                # appended so a following client (shim/client.py,
                # fleet/router.py framed front) can honor both
                log.info("shim request forwarded on %s: %s",
                         envelope.method, exc)
                response = pb.Envelope(
                    method=envelope.method,
                    error=f"{exc.reason}; retry after {exc.retry_after_s}s",
                )
            except CLIENT_ERRORS as exc:
                # expected client errors only (null pod, malformed JSON,
                # invalid snapshot payload): no traceback, keep the log
                # quiet. Internal bugs that happen to raise ValueError hit
                # the generic branch below with a full traceback.
                log.info("shim client error on %s: %s", envelope.method, exc)
                response = pb.Envelope(method=envelope.method, error=str(exc))
            except Exception as exc:  # contained per request
                log.exception("shim call failed")
                response = pb.Envelope(method=envelope.method, error=str(exc))
            try:
                write_frame(sock, response.SerializeToString())
            except OSError:
                # client hung up before the answer went out — same signal
                # the HTTP layer counts as a dropped response
                obs = getattr(self.server.engine, "obs", None)
                if obs is not None:
                    obs.note_dropped("shim")
                log.warning("shim client gone before response write")
                return


def make_shim_server(
    engine, host: str = "127.0.0.1", port: int = 9090, tenants=None
) -> ShimServer:
    return ShimServer((host, port), engine, tenants=tenants)
