"""Shim server: the TPU backend behind the framed-protobuf contract.

This is the process boundary of the north star's deployment shape: the
reference's Quarkus/common-lib front-end stays intact and forwards
``PodFailureData`` here instead of running the JVM hot loop; this server
answers with the full ``AnalysisResult`` (discovery-order events, exact
scores) plus the frequency admin surface. See proto/logparser.proto for
the contract and framing.py for the wire format.
"""

from __future__ import annotations

import json
import logging
import socketserver
import threading

from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.shim import logparser_pb2 as pb
from log_parser_tpu.shim.framing import FramingError, read_frame, write_frame

log = logging.getLogger(__name__)


class ShimServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, address: tuple[str, int], engine):
        super().__init__(address, _Handler)
        self.engine = engine
        self.analyze_lock = threading.Lock()


class _Handler(socketserver.BaseRequestHandler):
    server: ShimServer

    def handle(self) -> None:
        sock = self.request
        while True:
            try:
                frame = read_frame(sock)
            except FramingError as exc:
                log.warning("shim connection dropped: %s", exc)
                return
            if frame is None:
                return
            envelope = pb.Envelope()
            try:
                envelope.ParseFromString(frame)
                response = self._dispatch(envelope)
            except Exception as exc:  # contained per request
                log.exception("shim call failed")
                response = pb.Envelope(method=envelope.method, error=str(exc))
            write_frame(sock, response.SerializeToString())

    # ------------------------------------------------------------- dispatch

    def _dispatch(self, env: pb.Envelope) -> pb.Envelope:
        engine = self.server.engine
        method = env.method
        if method == "Parse":
            return self._parse(env)
        if method == "Health":
            return _reply(method, pb.HealthResponse(status="UP"))
        # frequency state is shared with in-flight Parse calls on other
        # connections — all admin access goes through the same lock
        if method == "FrequencyStats":
            with self.server.analyze_lock:
                stats = engine.frequency.get_frequency_statistics()
            return _reply(
                method, pb.FrequencyStatsResponse(windowed_counts=stats)
            )
        if method == "FrequencyReset":
            req = pb.FrequencyResetRequest()
            req.ParseFromString(env.payload)
            with self.server.analyze_lock:
                if req.pattern_id:
                    engine.frequency.reset_pattern_frequency(req.pattern_id)
                else:
                    engine.frequency.reset_all_frequencies()
            return _reply(method, pb.FrequencyResetResponse())
        if method == "FrequencySnapshot":
            resp = pb.FrequencySnapshotResponse()
            with self.server.analyze_lock:
                snap = engine.frequency.snapshot()
            for pid, ages in snap.items():
                resp.ages[pid].ages_seconds.extend(ages)
            return _reply(method, resp)
        if method == "FrequencyRestore":
            req = pb.FrequencyRestoreRequest()
            req.ParseFromString(env.payload)
            with self.server.analyze_lock:
                engine.frequency.restore(
                    {pid: list(al.ages_seconds) for pid, al in req.ages.items()}
                )
            return _reply(method, pb.FrequencyRestoreResponse())
        return pb.Envelope(method=method, error=f"unknown method {method!r}")

    def _parse(self, env: pb.Envelope) -> pb.Envelope:
        req = pb.ParseRequest()
        req.ParseFromString(env.payload)
        # Parse.java:45-49 — a null pod is a client error
        pod = json.loads(req.pod_json) if req.pod_json else None
        if pod is None:
            return pb.Envelope(
                method="Parse", error="Invalid PodFailureData provided"
            )
        data = PodFailureData(pod=pod, logs=req.logs)
        with self.server.analyze_lock:
            result = self.server.engine.analyze(data)

        resp = pb.ParseResponse(analysis_id=result.analysis_id or "")
        for event in result.events:
            ctx = event.context
            pb_ctx = pb.EventContext()
            if ctx is not None:
                pb_ctx.matched_line = ctx.matched_line or ""
                if ctx.lines_before is not None:
                    pb_ctx.has_lines_before = True
                    pb_ctx.lines_before.extend(ctx.lines_before)
                if ctx.lines_after is not None:
                    pb_ctx.has_lines_after = True
                    pb_ctx.lines_after.extend(ctx.lines_after)
            resp.events.append(
                pb.MatchedEvent(
                    line_number=event.line_number,
                    pattern_json=json.dumps(
                        event.matched_pattern.to_dict(drop_none=True)
                    )
                    if event.matched_pattern is not None
                    else "",
                    context=pb_ctx,
                    score=event.score,
                )
            )
        md = result.metadata
        if md is not None:
            resp.metadata.processing_time_ms = md.processing_time_ms or 0
            resp.metadata.total_lines = md.total_lines or 0
            resp.metadata.analyzed_at = md.analyzed_at or ""
            resp.metadata.patterns_used.extend(
                x or "" for x in (md.patterns_used or [])
            )
        sm = result.summary
        if sm is not None:
            resp.summary.significant_events = sm.significant_events or 0
            resp.summary.highest_severity = sm.highest_severity or ""
            for sev, count in (sm.severity_distribution or {}).items():
                resp.summary.severity_distribution[sev] = count
        return _reply("Parse", resp)


def _reply(method: str, message) -> pb.Envelope:
    return pb.Envelope(method=method, payload=message.SerializeToString())


def make_shim_server(engine, host: str = "127.0.0.1", port: int = 9090) -> ShimServer:
    return ShimServer((host, port), engine)
