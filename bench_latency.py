"""Streaming benchmark: continuous ``/parse`` micro-batches, p50/p99 latency.

Implements BASELINE.md config 5. The reference publishes no latency numbers
(BASELINE.md — `README.md` and docs contain none), so the target is
"establish". Default drives the engine directly; ``--http`` exercises the
full REST stack on a local server for end-to-end request latency.

Prints exactly one JSON line:
    {"metric": ..., "value": p99_ms, "unit": "ms", "vs_baseline": p50_ms}

``--sweep`` switches to the micro-batching concurrency sweep (ISSUE 3
acceptance): 1/4/16/64 concurrent clients x batching off/on against the
engine directly, per-level p50/p99 plus aggregate lines/sec. The
headline value is the 16-client batching-ON throughput, vs_baseline the
16-client OFF throughput, with the full curve in ``sweep``. Defaults to
small 64-line corpora (where per-request dispatch overhead dominates
and coalescing pays); ``--lines`` overrides.

``--stream`` switches to the follow-mode time-to-first-detection
scenario (ISSUE 9 acceptance): each corpus is replayed as a streaming
session in ``--chunk-lines``-line chunks at a fixed ``--chunk-cadence-ms``
arrival pace (default 5 ms; 0 = back-to-back compute-only), and TTFD is
the wall time from replay start to the first ``emit`` frame — measured
against blob-mode end-to-end latency on same-shaped corpora, where
end-to-end charges blob mode the full replay window (collect-then-POST
cannot fire until the tail has finished arriving) plus one-shot
``analyze()``. The headline value is p50 TTFD, vs_baseline the blob-mode
p50; the full percentiles, the TTFD/blob ratio, and the session counter
block ride in the artifact. Combine with
``--repeat-ratio``/``--line-cache-mb`` for the repeat-heavy tail-follow
shape the streaming layer is built for.
"""

from __future__ import annotations

import json
import sys
import threading
import time

import bench_common  # noqa: F401  (sets LOG_PARSER_TPU_NO_FALLBACK=1 on import)

SWEEP = "--sweep" in sys.argv
BATCH_LINES = (
    int(sys.argv[sys.argv.index("--lines") + 1])
    if "--lines" in sys.argv
    else (16 if SWEEP else 512)
)
REQUESTS = int(sys.argv[sys.argv.index("--requests") + 1]) if "--requests" in sys.argv else 60
USE_HTTP = "--http" in sys.argv
SWEEP_LEVELS = (1, 4, 16, 64)
SWEEP_WAIT_MS = (
    float(sys.argv[sys.argv.index("--batch-wait-ms") + 1])
    if "--batch-wait-ms" in sys.argv
    else 12.0
)
SWEEP_BATCH_MAX = (
    int(sys.argv[sys.argv.index("--batch-max") + 1])
    if "--batch-max" in sys.argv
    else 16
)
# N concurrent clients: measures how well the pipelined serving path
# (engine.analyze_pipelined) overlaps ingest/device work across requests;
# 1 = the sequential stream
CONCURRENCY = (
    int(sys.argv[sys.argv.index("--concurrency") + 1])
    if "--concurrency" in sys.argv
    else 1
)
# --repeat-ratio R: ~R of each micro-batch's lines become zipf template
# draws (bench_common.REPEAT_TEMPLATES), the rest stay unique per (i, j).
# --line-cache-mb MB: serve through the exact-match line cache
# (runtime/linecache.py); 0/absent = cache off.
REPEAT_RATIO = (
    float(sys.argv[sys.argv.index("--repeat-ratio") + 1])
    if "--repeat-ratio" in sys.argv
    else None
)
LINE_CACHE_MB = (
    float(sys.argv[sys.argv.index("--line-cache-mb") + 1])
    if "--line-cache-mb" in sys.argv
    else 0.0
)
# --stream: follow-mode TTFD scenario (runtime/stream.py sessions)
STREAM = "--stream" in sys.argv
CHUNK_LINES = (
    int(sys.argv[sys.argv.index("--chunk-lines") + 1])
    if "--chunk-lines" in sys.argv
    else 16
)
CHUNK_CADENCE_MS = (
    float(sys.argv[sys.argv.index("--chunk-cadence-ms") + 1])
    if "--chunk-cadence-ms" in sys.argv
    else 5.0
)


def micro_batch(i: int, n: int) -> str:
    if REPEAT_RATIO is not None:
        # pure function of (i, j) via hash01 so the sweep prewarm, which
        # regenerates content by index, sees identical lines and shapes
        rows = []
        for j in range(n):
            u = i * 131 + j
            if bench_common.hash01(u) < REPEAT_RATIO:
                rows.append(
                    bench_common.zipf_template(
                        bench_common.hash01(u ^ 0x9E3779B9)
                    )
                )
            else:
                rows.append(f"INFO tick {i}.{j} status=ok")
        return "\n".join(rows)
    rows = []
    for j in range(n):
        m = (i * 131 + j) % 97
        if m == 11:
            rows.append("java.lang.OutOfMemoryError: Java heap space")
        elif m == 13:
            rows.append("dial tcp 10.0.0.7:5432: Connection refused")
        elif m == 17:
            rows.append("ERROR request failed with IllegalStateException")
        else:
            rows.append(f"INFO tick {i}.{j} status=ok")
    return "\n".join(rows)


def metric_suffix() -> str:
    s = ""
    if REPEAT_RATIO is not None:
        s += f"_rr{int(round(REPEAT_RATIO * 100)):02d}"
    if LINE_CACHE_MB > 0:
        s += "_lc"
    return s


def percentile(sorted_vals: list[float], q: float) -> float:
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def sweep_main() -> None:
    metric = f"parse_agg_lines_per_s_c16_batched_{BATCH_LINES}line" + metric_suffix()
    platform = bench_common.probe_backend(metric, "lines/s")

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    engine = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
    if LINE_CACHE_MB > 0:
        engine.enable_line_cache(LINE_CACHE_MB)

    def run_level(batching: bool, c: int, per_client: int) -> dict:
        per_thread: list[list[float]] = [[] for _ in range(c)]

        def client(ci: int):
            def inner() -> None:
                for j in range(per_client):
                    data = PodFailureData(
                        pod={"metadata": {"name": "sweep"}},
                        logs=micro_batch(ci * per_client + j, BATCH_LINES),
                    )
                    t0 = time.perf_counter()
                    if batching:
                        engine.analyze_batched(data)
                    else:
                        engine.analyze_pipelined(data)
                    per_thread[ci].append((time.perf_counter() - t0) * 1e3)

            return inner

        n_requests = c * per_client
        budget_s = max(bench_common.DRAIN_FLOOR_S, 10.0 * n_requests)
        mode = "on" if batching else "off"
        t0 = time.perf_counter()
        bench_common.run_bounded(
            [client(ci) for ci in range(c)],
            budget_s,
            metric,
            "lines/s",
            platform,
            f"sweep c{c} batching={mode}",
        )
        wall = time.perf_counter() - t0
        lat = sorted(x for vals in per_thread for x in vals)
        return {
            "concurrency": c,
            "batching": mode,
            "requests": n_requests,
            "wall_s": round(wall, 3),
            "lines_per_sec": round(n_requests * BATCH_LINES / wall, 1),
            "p50_ms": round(percentile(lat, 0.50), 3),
            "p99_ms": round(percentile(lat, 0.99), 3),
        }

    def prewarm_batcher(batcher) -> None:
        """Compile every (R, B, T) shape the sweep can realize BEFORE the
        timed levels: group the request stream's corpora by encoded shape,
        then coalesce exact power-of-two batches of each group through the
        real batcher path. Without this, stray XLA compiles of the vmapped
        program land inside a timed window and read as 4-second p99s."""
        from log_parser_tpu.native.ingest import Corpus

        by_shape: dict[tuple, list[int]] = {}
        for i in range(97):  # the micro_batch content cycle
            corpus = Corpus(
                micro_batch(i, BATCH_LINES),
                min_rows=engine._corpus_min_rows(),
            )
            by_shape.setdefault(corpus.encoded.u8.shape, []).append(i)
        old_wait = batcher.wait_s
        batcher.wait_s = 0.25  # hold each round open until fully enqueued
        try:
            for idxs in by_shape.values():
                r = 1
                while r <= batcher.batch_max:
                    pend = [
                        batcher._enqueue(
                            PodFailureData(
                                pod={"metadata": {"name": "warm"}},
                                logs=micro_batch(i, BATCH_LINES),
                            ),
                            None,
                        )
                        for i in (idxs * r)[:r]
                    ]
                    for p in pend:
                        p.done.wait()
                    r <<= 1
        finally:
            batcher.wait_s = old_wait

    curve = []
    batcher_stats = None
    for batching in (False, True):
        if batching:
            batcher = engine.enable_batching(
                wait_ms=SWEEP_WAIT_MS, batch_max=SWEEP_BATCH_MAX
            )
            bounded = bench_common.bounded_runner(metric, "lines/s", platform)
            bounded(
                lambda: prewarm_batcher(batcher),
                bench_common.PROBE_TIMEOUT_S,
                "batch prewarm",
            )
        for c in SWEEP_LEVELS:
            # warmup round (untimed): the unbatched R=1 shapes, and with
            # batching on the residual scheduler timing at this fan-in
            run_level(batching, c, 2)
            curve.append(run_level(batching, c, max(3, REQUESTS // c)))
        if batching:
            batcher_stats = engine.batcher.stats()
            engine.batcher.close()
            engine.batcher = None

    def level(mode: str, c: int) -> dict:
        return next(
            r for r in curve if r["batching"] == mode and r["concurrency"] == c
        )

    extra = {}
    if REPEAT_RATIO is not None:
        extra["repeat_ratio"] = REPEAT_RATIO
    if engine.line_cache is not None:
        extra["line_cache_mb"] = LINE_CACHE_MB
        extra["line_cache"] = engine.line_cache.stats()
    bench_common.emit(
        metric,
        level("on", 16)["lines_per_sec"],
        "lines/s",
        level("off", 16)["lines_per_sec"],
        platform,
        lines_per_request=BATCH_LINES,
        batch_wait_ms=SWEEP_WAIT_MS,
        batch_max=SWEEP_BATCH_MAX,
        sweep=curve,
        batcher=batcher_stats,
        **extra,
    )


def stream_corpus(i: int) -> list[str]:
    rows = micro_batch(i, BATCH_LINES).split("\n")
    if REPEAT_RATIO is not None:
        # the repeat-template pool is all noise by construction, so a
        # --repeat-ratio corpus would never produce a detection and TTFD
        # would be undefined — overlay the plain path's detection cycle
        # (same ~2% density) on top of the repeat-heavy traffic
        for j in range(len(rows)):
            m = (i * 131 + j) % 97
            if m == 11:
                rows[j] = "java.lang.OutOfMemoryError: Java heap space"
            elif m == 13:
                rows[j] = "dial tcp 10.0.0.7:5432: Connection refused"
    return rows


def stream_main() -> None:
    metric = (
        f"stream_ttfd_p50_ms_{BATCH_LINES}line_chunk{CHUNK_LINES}"
        + metric_suffix()
    )
    platform = bench_common.probe_backend(metric, "ms")

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine
    from log_parser_tpu.runtime.stream import StreamManager

    engine = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
    if LINE_CACHE_MB > 0:
        engine.enable_line_cache(LINE_CACHE_MB)
    mgr = StreamManager(engine, ttl_s=0, start_reaper=False)

    def chunks_of(rows: list[str]) -> list[bytes]:
        return [
            ("\n".join(rows[k : k + CHUNK_LINES]) + "\n").encode()
            for k in range(0, len(rows), CHUNK_LINES)
        ]

    n_chunks = (BATCH_LINES + CHUNK_LINES - 1) // CHUNK_LINES

    def run_blob(i: int) -> None:
        # blob mode can only fire once the whole tail has arrived: charge
        # the full replay window (every chunk at the fixed cadence) before
        # the one-shot analyze — that wait IS blob-mode end-to-end latency
        # under the same arrival process the sessions see
        if CHUNK_CADENCE_MS > 0:
            time.sleep(n_chunks * CHUNK_CADENCE_MS / 1e3)
        engine.analyze(
            PodFailureData(
                pod={"metadata": {"name": "stream"}},
                logs="\n".join(stream_corpus(i)),
            )
        )

    def run_stream(i: int) -> float | None:
        """Replay corpus ``i`` as a follow-mode session at the fixed chunk
        cadence; TTFD is first-byte-fed to first ``emit`` frame. Once the
        first detection is out the tail is moot for this metric, so the
        session closes (untimed) instead of draining the remaining
        chunks."""
        sess = mgr.open()
        ttfd_ms = None
        try:
            t0 = time.perf_counter()
            for chunk in chunks_of(stream_corpus(i)):
                if CHUNK_CADENCE_MS > 0:
                    time.sleep(CHUNK_CADENCE_MS / 1e3)
                frames = sess.feed(chunk)
                assert not any(f["type"] == "error" for f in frames), frames
                if any(f["type"] == "emit" for f in frames):
                    ttfd_ms = (time.perf_counter() - t0) * 1e3
                    break
        finally:
            sess.close()
        return ttfd_ms

    bounded = bench_common.bounded_runner(metric, "ms", platform)

    def warmup() -> None:
        # compile both shape families before timing: the blob-mode
        # full-corpus batch and the chunk-sized residual batches the
        # session feed path realizes
        for i in range(3):
            run_blob(i)
            run_stream(REQUESTS + i)

    bounded(warmup, bench_common.PROBE_TIMEOUT_S, "warmup")

    blob_ms: list[float] = []
    ttfd_ms: list[float] = []
    misses = 0
    budget_s = max(bench_common.DRAIN_FLOOR_S, 10.0 * REQUESTS)

    def timed_blob() -> None:
        for i in range(3, REQUESTS + 3):
            t0 = time.perf_counter()
            run_blob(i)
            blob_ms.append((time.perf_counter() - t0) * 1e3)

    def timed_stream() -> None:
        nonlocal misses
        # offset index range: same line population and repeat-template
        # pool as the blob phase, but no request is byte-identical to one
        # the cache just served whole
        for i in range(REQUESTS + 3, 2 * REQUESTS + 3):
            t = run_stream(i)
            if t is None:
                misses += 1
            else:
                ttfd_ms.append(t)

    bounded(timed_blob, budget_s, "blob-mode baseline")
    bounded(timed_stream, budget_s, "stream ttfd")
    blob_ms.sort()
    ttfd_ms.sort()
    assert ttfd_ms, "no streaming session ever produced an emit frame"

    p50_ttfd = round(percentile(ttfd_ms, 0.50), 3)
    p50_blob = round(percentile(blob_ms, 0.50), 3)
    extra: dict[str, object] = {
        "n_requests": REQUESTS,
        "chunk_lines": CHUNK_LINES,
        "chunk_cadence_ms": CHUNK_CADENCE_MS,
        "ttfd_ms": {"p50": p50_ttfd, "p99": round(percentile(ttfd_ms, 0.99), 3)},
        "blob_ms": {"p50": p50_blob, "p99": round(percentile(blob_ms, 0.99), 3)},
        "ttfd_over_blob_p50": round(p50_ttfd / p50_blob, 4),
        "ttfd_misses": misses,
        "stream": mgr.stats(),
    }
    if REPEAT_RATIO is not None:
        extra["repeat_ratio"] = REPEAT_RATIO
    if engine.line_cache is not None:
        extra["line_cache_mb"] = LINE_CACHE_MB
        extra["line_cache"] = engine.line_cache.stats()
    bench_common.emit(metric, p50_ttfd, "ms", p50_blob, platform, **extra)


def main() -> None:
    if SWEEP:
        return sweep_main()
    if STREAM:
        return stream_main()
    suffix = "_http" if USE_HTTP else ""
    if CONCURRENCY > 1:
        suffix += f"_c{CONCURRENCY}"
    metric = (
        f"parse_latency_p99_ms_{BATCH_LINES}line_microbatch"
        + suffix
        + metric_suffix()
    )
    platform = bench_common.probe_backend(metric, "ms")

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    engine = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
    if LINE_CACHE_MB > 0:
        engine.enable_line_cache(LINE_CACHE_MB)

    if USE_HTTP:
        import urllib.request

        from log_parser_tpu.serve.http import make_server

        server = make_server(engine, host="127.0.0.1", port=0)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()

        def run_one(i: int) -> None:
            body = json.dumps(
                {"pod": {"metadata": {"name": "stream"}},
                 "logs": micro_batch(i, BATCH_LINES)}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/parse", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
                json.load(resp)
    else:
        def run_one(i: int) -> None:
            data = PodFailureData(
                pod={"metadata": {"name": "stream"}},
                logs=micro_batch(i, BATCH_LINES),
            )
            # the direct path must also go through the thread-safe entry
            # point when clients are concurrent: bare analyze() has no
            # internal locking and would race frequency state
            if CONCURRENCY > 1:
                engine.analyze_pipelined(data)
            else:
                engine.analyze(data)

    # EVERY phase — warmup, serial stream, concurrent fan-out — runs
    # through the shared wedge wrappers: a backend that stops returning
    # mid-request must yield a {"value": null} diagnostics exit, not an
    # rc=124 hang. Single-worker phases ride bounded_runner; the
    # concurrent fan-out uses run_bounded directly.
    bounded = bench_common.bounded_runner(metric, "ms", platform)

    def warmup() -> None:
        for i in range(3):  # compile every shape bucket the stream hits
            run_one(i)

    # warmup budget: first-compile on TPU is 20-40s; through a cold
    # tunneled runtime it has been observed past 100s — match the probe
    # harness's total budget before calling it a wedge
    bounded(warmup, bench_common.PROBE_TIMEOUT_S, "warmup")

    lat: list[float] = []
    # measurement budget: a generous per-request ceiling times the whole
    # run — observed p99 is ~0.2 s/request, so 10 s/request only trips on
    # a genuinely wedged backend, never a slow-but-live one
    budget_s = max(bench_common.DRAIN_FLOOR_S, 10.0 * REQUESTS)
    if CONCURRENCY > 1:
        chunks = [list(range(c, REQUESTS, CONCURRENCY)) for c in range(CONCURRENCY)]
        per_thread: list[list[float]] = [[] for _ in range(CONCURRENCY)]

        def client(c: int):
            def inner() -> None:
                for i in chunks[c]:
                    t0 = time.perf_counter()
                    run_one(i)
                    per_thread[c].append((time.perf_counter() - t0) * 1e3)

            return inner

        bench_common.run_bounded(
            [client(c) for c in range(CONCURRENCY)],
            budget_s,
            metric,
            "ms",
            platform,
            "stream",
        )
        for vals in per_thread:
            lat.extend(vals)
    else:

        def serial() -> None:
            for i in range(REQUESTS):
                t0 = time.perf_counter()
                run_one(i)
                lat.append((time.perf_counter() - t0) * 1e3)

        bounded(serial, budget_s, "stream")
    lat.sort()

    # decompose request latency into engine phases (VERDICT r4 #7): the
    # HTTP/tunnel share of p99 is (request p99 - engine-total p99), and
    # device_step_ms is the device dispatch+sync phase alone — config-5
    # on the tunneled chip is RTT-dominated (~6 ms CPU floor for
    # identical host code), and without this split an engine regression
    # is indistinguishable from tunnel weather in the artifact
    traces = list(engine.trace_history)[-REQUESTS:]
    phase_pcts: dict[str, object] = {}
    if traces:
        for name in ("device", "ingest", "finalize", "lock_wait"):
            vals = sorted(1e3 * t.as_dict().get(name, 0.0) for t in traces)
            phase_pcts[f"{name}_ms"] = {
                "p50": round(percentile(vals, 0.50), 3),
                "p99": round(percentile(vals, 0.99), 3),
            }
        totals = sorted(1e3 * t.total for t in traces)
        phase_pcts["engine_total_ms"] = {
            "p50": round(percentile(totals, 0.50), 3),
            "p99": round(percentile(totals, 0.99), 3),
        }
        # the trace deque is bounded (maxlen 512): when --requests
        # exceeds it, the phase stats cover only this tail window while
        # the headline p99 covers the whole run — say so in the artifact
        phase_pcts["phase_sample_n"] = len(traces)

    if REPEAT_RATIO is not None:
        phase_pcts["repeat_ratio"] = REPEAT_RATIO
    if engine.line_cache is not None:
        phase_pcts["line_cache_mb"] = LINE_CACHE_MB
        phase_pcts["line_cache"] = engine.line_cache.stats()
    bench_common.emit(
        metric,
        round(percentile(lat, 0.99), 3),
        "ms",
        round(percentile(lat, 0.50), 3),
        platform,
        n_requests=REQUESTS,
        **phase_pcts,
    )


if __name__ == "__main__":
    main()
