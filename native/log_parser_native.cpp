// Native runtime components for log_parser_tpu.
//
// Two subsystems, exposed with a C ABI for ctypes:
//
//  1. Ingest: one-pass Java-semantics log splitting (String.split("\r?\n"),
//     AnalysisService.java:53 — trailing empty lines dropped, lone "\r" is
//     not a separator) fused with padded-uint8 batch encoding for the
//     device matcher. Replaces the Python/numpy host hot path so a 1M-line
//     corpus never materializes per-line Python strings.
//
//  2. DFA builder: NFA -> byte-class-compressed DFA subset construction
//     with zero-width assertion resolution (the same algorithm as
//     patterns/regex/dfa.py), plus Moore partition-refinement minimization
//     and byte-class recompression. C++ because determinizing a 10k-regex
//     library is minutes of Python set churn but sub-second here.
//
// No external dependencies; built with `g++ -O3 -shared -fPIC`.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// 1. Ingest
// ---------------------------------------------------------------------------

// Pass 1: count lines (after Java trailing-empty removal) and max byte
// length. Returns n_lines; *out_max_len receives the longest line's bytes.
int64_t lpn_split_scan(const uint8_t* buf, int64_t n, int64_t* out_max_len) {
    int64_t n_parts = 0;       // parts emitted so far
    int64_t last_nonempty = 0; // parts up to and including the last non-empty
    int64_t max_len = 0;
    int64_t start = 0;
    bool saw_sep = false;
    for (int64_t i = 0; i < n; ++i) {
        if (buf[i] == '\n') {
            saw_sep = true;
            int64_t end = i;
            if (end > start && buf[end - 1] == '\r') --end;
            int64_t len = end - start;
            ++n_parts;
            if (len > 0) {
                last_nonempty = n_parts;
                if (len > max_len) max_len = len;
            }
            start = i + 1;
        }
    }
    // final part (after the last separator, or the whole input)
    {
        int64_t len = n - start;
        ++n_parts;
        if (len > 0) {
            last_nonempty = n_parts;
            if (len > max_len) max_len = len;
        }
    }
    if (!saw_sep) {
        // Java: no separator found -> the whole input, even when empty
        *out_max_len = max_len;
        return 1;
    }
    *out_max_len = max_len;
    return last_nonempty; // trailing empties dropped
}

// Pass 2: fill the padded batch. u8 is a zeroed [rows, width] buffer;
// starts/ends receive byte offsets of each line within buf (for lazy string
// decode on the host); lengths receives min(len, width); needs_host is set
// when a line has non-ASCII or NUL bytes within the clipped window or
// exceeds max_line_bytes. NUL routes to host so the device automata can
// treat byte 0 as padding-only (no byteset admits it), which lets the
// bit-tier steppers drop their per-byte end-of-line gating.
void lpn_split_fill(const uint8_t* buf, int64_t n, int64_t n_lines,
                    uint8_t* u8, int64_t width, int32_t* lengths,
                    uint8_t* needs_host, int64_t* starts, int64_t* ends,
                    int64_t max_line_bytes) {
    int64_t start = 0;
    int64_t row = 0;
    for (int64_t i = 0; i <= n && row < n_lines; ++i) {
        bool at_end = (i == n);
        if (!at_end && buf[i] != '\n') continue;
        int64_t end = i;
        if (!at_end && end > start && buf[end - 1] == '\r') --end;
        int64_t len = end - start;
        int64_t clipped = len < width ? len : width;
        uint8_t* dst = u8 + row * width;
        std::memcpy(dst, buf + start, static_cast<size_t>(clipped));
        uint8_t non_ascii = 0;
        bool has_nul = false;
        for (int64_t j = 0; j < clipped; ++j) {
            non_ascii |= dst[j] & 0x80;
            has_nul = has_nul || (dst[j] == 0);
        }
        lengths[row] = static_cast<int32_t>(clipped);
        needs_host[row] = (non_ascii != 0) || has_nul || (len > max_line_bytes);
        starts[row] = start;
        ends[row] = end;
        ++row;
        start = i + 1;
    }
}

// True byte length of every line (before width clipping) — the prepass
// the width-capping heuristic needs before the batch can be allocated.
// This is deliberately a third walk over the blob (scan → lengths →
// fill): lengths must exist before the width decision, the width before
// the allocation the fill writes into, and a memchr-speed pass is ~15ms
// per GB — noise next to the fill. Keep the split/CRLF semantics in the
// three loops identical.
void lpn_split_lengths(const uint8_t* buf, int64_t n, int64_t n_lines,
                       int32_t* out) {
    int64_t start = 0;
    int64_t row = 0;
    for (int64_t i = 0; i <= n && row < n_lines; ++i) {
        bool at_end = (i == n);
        if (!at_end && buf[i] != '\n') continue;
        int64_t end = i;
        if (!at_end && end > start && buf[end - 1] == '\r') --end;
        int64_t len = end - start;
        out[row++] = len > INT32_MAX ? INT32_MAX : static_cast<int32_t>(len);
        start = i + 1;
    }
}

// ---------------------------------------------------------------------------
// 2. DFA builder
// ---------------------------------------------------------------------------

// Assertion condition codes on epsilon edges (matches nfa.py's "^$bB").
enum Cond : int8_t { COND_NONE = 0, COND_BOL = 1, COND_EOL = 2, COND_B = 3, COND_NB = 4 };
// Left-context classes inside a DFA state (matches dfa.py).
enum Left : int32_t { L_BEGIN = 0, L_NONWORD = 1, L_WORD = 2 };

namespace {

struct DfaResult {
    std::vector<int32_t> trans;      // [n_states * n_classes]
    std::vector<int32_t> byte_class; // [256]
    std::vector<uint8_t> accept;     // [n_states]
    int32_t n_states = 0;
    int32_t n_classes = 0;
    int32_t start = 0;
};

struct VecHash {
    size_t operator()(const std::vector<int32_t>& v) const {
        size_t h = 0x9e3779b97f4a7c15ull ^ v.size();
        for (int32_t x : v) h = (h ^ static_cast<size_t>(x)) * 0x100000001b3ull;
        return h;
    }
};

struct Nfa {
    int32_t n_states;
    int32_t start;
    int32_t fin;
    // CSR epsilon edges
    const int64_t* eps_off;
    const int8_t* eps_cond;
    const int32_t* eps_dst;
    // CSR byte transitions (byteset ids)
    const int64_t* t_off;
    const int32_t* t_bs;
    const int32_t* t_dst;
    const uint8_t* bytesets; // [n_bs][32] bitmask
    const uint8_t* word_mask; // [32]
};

inline bool bs_has(const uint8_t* mask, int b) {
    return (mask[b >> 3] >> (b & 7)) & 1;
}

// Epsilon closure under (left, right_word) assertion context.
// right_word: 1/0, or -1 for end-of-input. Result: sorted state vector.
void closure(const Nfa& nfa, const std::vector<int32_t>& core, int32_t left,
             int right_word, std::vector<int32_t>& out,
             std::vector<uint8_t>& in_set, std::vector<int32_t>& stack) {
    bool left_word = left == L_WORD;
    bool at_start = left == L_BEGIN;
    bool at_end = right_word < 0;
    bool rw = right_word > 0;
    out.clear();
    stack.clear();
    for (int32_t s : core) {
        if (!in_set[s]) { in_set[s] = 1; out.push_back(s); stack.push_back(s); }
    }
    while (!stack.empty()) {
        int32_t s = stack.back();
        stack.pop_back();
        for (int64_t e = nfa.eps_off[s]; e < nfa.eps_off[s + 1]; ++e) {
            int32_t dst = nfa.eps_dst[e];
            if (in_set[dst]) continue;
            bool ok;
            switch (nfa.eps_cond[e]) {
                case COND_NONE: ok = true; break;
                case COND_BOL: ok = at_start; break;
                case COND_EOL: ok = at_end; break;
                case COND_B: ok = left_word != (at_end ? false : rw); break;
                case COND_NB: ok = left_word == (at_end ? false : rw); break;
                default: ok = false; break;
            }
            if (ok) { in_set[dst] = 1; out.push_back(dst); stack.push_back(dst); }
        }
    }
    for (int32_t s : out) in_set[s] = 0; // reset scratch
    std::sort(out.begin(), out.end());
}

bool contains(const std::vector<int32_t>& sorted_vec, int32_t x) {
    return std::binary_search(sorted_vec.begin(), sorted_vec.end(), x);
}

// Moore partition-refinement minimization + byte-class recompression.
void minimize(DfaResult& d) {
    int32_t n = d.n_states, c = d.n_classes;
    std::vector<int32_t> part(n);
    for (int32_t s = 0; s < n; ++s) part[s] = d.accept[s] ? 1 : 0;
    int32_t n_parts = 2;
    std::vector<int32_t> key(c + 1);
    for (;;) {
        std::unordered_map<std::vector<int32_t>, int32_t, VecHash> sig;
        std::vector<int32_t> next(n);
        for (int32_t s = 0; s < n; ++s) {
            key[0] = part[s];
            for (int32_t k = 0; k < c; ++k) key[k + 1] = part[d.trans[s * c + k]];
            auto it = sig.find(key);
            if (it == sig.end()) {
                int32_t id = static_cast<int32_t>(sig.size());
                sig.emplace(key, id);
                next[s] = id;
            } else {
                next[s] = it->second;
            }
        }
        int32_t m = static_cast<int32_t>(sig.size());
        part.swap(next);
        if (m == n_parts) break;
        n_parts = m;
    }
    // build minimized table (representative per partition)
    std::vector<int32_t> rep(n_parts, -1);
    for (int32_t s = 0; s < n; ++s) if (rep[part[s]] < 0) rep[part[s]] = s;
    std::vector<int32_t> mtrans(static_cast<size_t>(n_parts) * c);
    std::vector<uint8_t> macc(n_parts);
    for (int32_t p = 0; p < n_parts; ++p) {
        int32_t s = rep[p];
        macc[p] = d.accept[s];
        for (int32_t k = 0; k < c; ++k) mtrans[p * c + k] = part[d.trans[s * c + k]];
    }
    int32_t mstart = part[d.start];
    // byte-class recompression: merge now-identical transition columns
    std::unordered_map<std::vector<int32_t>, int32_t, VecHash> colsig;
    std::vector<int32_t> colmap(c);
    std::vector<int32_t> col(n_parts);
    for (int32_t k = 0; k < c; ++k) {
        for (int32_t p = 0; p < n_parts; ++p) col[p] = mtrans[p * c + k];
        auto it = colsig.find(col);
        if (it == colsig.end()) {
            int32_t id = static_cast<int32_t>(colsig.size());
            colsig.emplace(col, id);
            colmap[k] = id;
        } else {
            colmap[k] = it->second;
        }
    }
    int32_t nc = static_cast<int32_t>(colsig.size());
    std::vector<int32_t> ftrans(static_cast<size_t>(n_parts) * nc);
    for (int32_t k = 0; k < c; ++k)
        for (int32_t p = 0; p < n_parts; ++p)
            ftrans[p * nc + colmap[k]] = mtrans[p * c + k];
    for (int b = 0; b < 256; ++b) d.byte_class[b] = colmap[d.byte_class[b]];
    d.trans.swap(ftrans);
    d.accept.swap(macc);
    d.n_states = n_parts;
    d.n_classes = nc;
    d.start = mstart;
}

} // namespace

// Build a DFA from a flat NFA. Returns an opaque handle (read with
// lpn_dfa_read, free with lpn_dfa_free) or nullptr with *err set:
//   1 = state cap exceeded.
void* lpn_dfa_build(int32_t n_nfa_states, int32_t start, int32_t fin,
                    const int64_t* eps_off, const int8_t* eps_cond,
                    const int32_t* eps_dst, const int64_t* t_off,
                    const int32_t* t_bs, const int32_t* t_dst,
                    const uint8_t* bytesets, int32_t n_bytesets,
                    const uint8_t* word_mask, int32_t max_states,
                    int32_t do_minimize, int32_t* out_n_states,
                    int32_t* out_n_classes, int32_t* out_start,
                    int32_t* err) {
    *err = 0;
    if (max_states < 1) { *err = 1; return nullptr; } // can't even intern start
    Nfa nfa{n_nfa_states, start, fin, eps_off, eps_cond, eps_dst,
            t_off, t_bs, t_dst, bytesets, word_mask};

    // --- byte classes: refine every byteset + word membership -------------
    std::vector<int32_t> byte_class(256);
    std::vector<int> reps;
    {
        std::unordered_map<std::vector<int32_t>, int32_t, VecHash> sigs;
        std::vector<int32_t> sig(n_bytesets + 1);
        for (int b = 0; b < 256; ++b) {
            for (int32_t i = 0; i < n_bytesets; ++i)
                sig[i] = bs_has(bytesets + static_cast<size_t>(i) * 32, b);
            sig[n_bytesets] = bs_has(word_mask, b);
            auto it = sigs.find(sig);
            if (it == sigs.end()) {
                int32_t cls = static_cast<int32_t>(sigs.size());
                sigs.emplace(sig, cls);
                reps.push_back(b);
                byte_class[b] = cls;
            } else {
                byte_class[b] = it->second;
            }
        }
    }
    int32_t n_classes = static_cast<int32_t>(reps.size());

    // --- subset construction ---------------------------------------------
    auto* d = new DfaResult();
    d->byte_class = byte_class;
    d->n_classes = n_classes;
    // state 0 = MATCHED sink (absorbing, accepting)
    d->trans.assign(n_classes, 0);
    d->accept.assign(1, 1);

    // key: sorted core states + left tag appended
    std::unordered_map<std::vector<int32_t>, int32_t, VecHash> intern;
    std::vector<std::vector<int32_t>> cores; // per dfa state (id >= 1): key
    std::vector<uint8_t> in_set(n_nfa_states, 0);
    std::vector<int32_t> cl, stack, moved;

    auto intern_state = [&](std::vector<int32_t>&& key) -> int32_t {
        auto it = intern.find(key);
        if (it != intern.end()) return it->second;
        int32_t sid = static_cast<int32_t>(cores.size()) + 1;
        if (sid > max_states) return -1;
        intern.emplace(key, sid);
        cores.push_back(std::move(key));
        d->trans.resize(static_cast<size_t>(sid + 1) * n_classes, -1);
        d->accept.push_back(0);
        return sid;
    };

    std::vector<int32_t> start_key{start, L_BEGIN};
    d->start = intern_state(std::move(start_key));

    for (int32_t sid = d->start; sid <= static_cast<int32_t>(cores.size()); ++sid) {
        // copy: `cores` reallocates as intern_state appends mid-loop
        std::vector<int32_t> key = cores[sid - 1];
        std::vector<int32_t> core(key.begin(), key.end() - 1);
        int32_t left = key.back();
        // end-of-input acceptance
        closure(nfa, core, left, -1, cl, in_set, stack);
        d->accept[sid] = contains(cl, fin) ? 1 : 0;
        for (int32_t k = 0; k < n_classes; ++k) {
            int rep = reps[k];
            bool rw = bs_has(word_mask, rep);
            closure(nfa, core, left, rw ? 1 : 0, cl, in_set, stack);
            if (contains(cl, fin)) {
                d->trans[static_cast<size_t>(sid) * n_classes + k] = 0; // MATCHED
                continue;
            }
            moved.clear();
            for (int32_t s : cl) {
                for (int64_t e = t_off[s]; e < t_off[s + 1]; ++e) {
                    if (bs_has(bytesets + static_cast<size_t>(t_bs[e]) * 32, rep))
                        moved.push_back(t_dst[e]);
                }
            }
            std::sort(moved.begin(), moved.end());
            moved.erase(std::unique(moved.begin(), moved.end()), moved.end());
            std::vector<int32_t> mkey(moved);
            mkey.push_back(rw ? L_WORD : L_NONWORD);
            int32_t dst = intern_state(std::move(mkey));
            if (dst < 0) { *err = 1; delete d; return nullptr; }
            d->trans[static_cast<size_t>(sid) * n_classes + k] = dst;
        }
    }
    d->n_states = static_cast<int32_t>(cores.size()) + 1;

    if (do_minimize) minimize(*d);

    *out_n_states = d->n_states;
    *out_n_classes = d->n_classes;
    *out_start = d->start;
    return d;
}

void lpn_dfa_read(void* handle, int32_t* trans, int32_t* byte_class,
                  uint8_t* accept) {
    auto* d = static_cast<DfaResult*>(handle);
    std::memcpy(trans, d->trans.data(), d->trans.size() * sizeof(int32_t));
    std::memcpy(byte_class, d->byte_class.data(), 256 * sizeof(int32_t));
    std::memcpy(accept, d->accept.data(), d->accept.size());
}

void lpn_dfa_free(void* handle) { delete static_cast<DfaResult*>(handle); }

// ---------------------------------------------------------------------------
// 3. Union multi-pattern DFA builder
// ---------------------------------------------------------------------------
//
// Determinizes the UNION of R pattern NFAs (merged by the Python side into
// one arena with a shared unanchored start) into one DFA whose states carry
// sticky per-pattern output bitmask words — the device then runs R patterns
// with ONE [B] state gather per byte instead of a [B, R] gather
// (patterns/regex/multidfa.py documents the design and the TPU measurement
// that motivates it). Same assertion-aware closure as the single builder;
// no MATCHED sink (each pattern latches independently via output bits read
// from the pre-transition state under the incoming byte's word-ness).

namespace {

struct MultiDfaResult {
    std::vector<int32_t> trans;        // [n_states * n_classes]
    std::vector<int32_t> byte_class;   // [256]
    std::vector<int32_t> cls_word;     // [n_classes] 0/1
    std::vector<uint32_t> out2;        // [n_states * 2 * n_words]
    std::vector<uint32_t> accept_w;    // [n_states * n_words]
    int32_t n_states = 0;
    int32_t n_classes = 0;
    int32_t n_words = 0;
    int32_t start = 0;
};

// Moore minimization for the multi-DFA: initial partition by the full
// output signature (out2 nonword/word rows + end-accept words), refinement
// on transitions, then byte-class recompression with word-ness kept in the
// column signature so cls_word stays well-defined.
void minimize_multi(MultiDfaResult& d) {
    int32_t n = d.n_states, c = d.n_classes, w = d.n_words;
    std::vector<int32_t> part(n);
    {
        std::unordered_map<std::vector<int32_t>, int32_t, VecHash> sigs;
        std::vector<int32_t> sig(3 * w);
        for (int32_t s = 0; s < n; ++s) {
            for (int32_t k = 0; k < w; ++k) {
                sig[k] = static_cast<int32_t>(d.out2[(s * 2) * w + k]);
                sig[w + k] = static_cast<int32_t>(d.out2[(s * 2 + 1) * w + k]);
                sig[2 * w + k] = static_cast<int32_t>(d.accept_w[s * w + k]);
            }
            auto it = sigs.find(sig);
            if (it == sigs.end()) {
                int32_t id = static_cast<int32_t>(sigs.size());
                sigs.emplace(sig, id);
                part[s] = id;
            } else {
                part[s] = it->second;
            }
        }
    }
    int32_t n_parts = -1;
    std::vector<int32_t> key(c + 1);
    for (;;) {
        std::unordered_map<std::vector<int32_t>, int32_t, VecHash> sig;
        std::vector<int32_t> next(n);
        for (int32_t s = 0; s < n; ++s) {
            key[0] = part[s];
            for (int32_t k = 0; k < c; ++k) key[k + 1] = part[d.trans[s * c + k]];
            auto it = sig.find(key);
            if (it == sig.end()) {
                int32_t id = static_cast<int32_t>(sig.size());
                sig.emplace(key, id);
                next[s] = id;
            } else {
                next[s] = it->second;
            }
        }
        int32_t m = static_cast<int32_t>(sig.size());
        part.swap(next);
        if (m == n_parts) break;
        n_parts = m;
    }
    std::vector<int32_t> rep(n_parts, -1);
    for (int32_t s = 0; s < n; ++s) if (rep[part[s]] < 0) rep[part[s]] = s;
    std::vector<int32_t> mtrans(static_cast<size_t>(n_parts) * c);
    std::vector<uint32_t> mout(static_cast<size_t>(n_parts) * 2 * w);
    std::vector<uint32_t> macc(static_cast<size_t>(n_parts) * w);
    for (int32_t p = 0; p < n_parts; ++p) {
        int32_t s = rep[p];
        for (int32_t k = 0; k < c; ++k) mtrans[p * c + k] = part[d.trans[s * c + k]];
        for (int32_t k = 0; k < w; ++k) {
            mout[(p * 2) * w + k] = d.out2[(s * 2) * w + k];
            mout[(p * 2 + 1) * w + k] = d.out2[(s * 2 + 1) * w + k];
            macc[p * w + k] = d.accept_w[s * w + k];
        }
    }
    int32_t mstart = part[d.start];
    // byte-class recompression; word-ness is part of the column signature
    std::unordered_map<std::vector<int32_t>, int32_t, VecHash> colsig;
    std::vector<int32_t> colmap(c);
    std::vector<int32_t> new_word;
    std::vector<int32_t> col(n_parts + 1);
    for (int32_t k = 0; k < c; ++k) {
        col[0] = d.cls_word[k];
        for (int32_t p = 0; p < n_parts; ++p) col[p + 1] = mtrans[p * c + k];
        auto it = colsig.find(col);
        if (it == colsig.end()) {
            int32_t id = static_cast<int32_t>(colsig.size());
            colsig.emplace(col, id);
            colmap[k] = id;
            new_word.push_back(d.cls_word[k]);
        } else {
            colmap[k] = it->second;
        }
    }
    int32_t nc = static_cast<int32_t>(colsig.size());
    std::vector<int32_t> ftrans(static_cast<size_t>(n_parts) * nc);
    for (int32_t k = 0; k < c; ++k)
        for (int32_t p = 0; p < n_parts; ++p)
            ftrans[p * nc + colmap[k]] = mtrans[p * c + k];
    for (int b = 0; b < 256; ++b) d.byte_class[b] = colmap[d.byte_class[b]];
    d.trans.swap(ftrans);
    d.out2.swap(mout);
    d.accept_w.swap(macc);
    d.cls_word.swap(new_word);
    d.n_states = n_parts;
    d.n_classes = nc;
    d.start = mstart;
}

} // namespace

// Build the union multi-DFA. `finals[i]` is pattern i's final NFA state in
// the merged arena. Returns an opaque handle (read with lpn_multi_dfa_read,
// free with lpn_multi_dfa_free) or nullptr with *err = 1 on state blowup.
void* lpn_multi_dfa_build(
    int32_t n_nfa_states, int32_t start, const int64_t* eps_off,
    const int8_t* eps_cond, const int32_t* eps_dst, const int64_t* t_off,
    const int32_t* t_bs, const int32_t* t_dst, const uint8_t* bytesets,
    int32_t n_bytesets, const uint8_t* word_mask, const int32_t* finals,
    int32_t n_patterns, int32_t max_states, int32_t do_minimize,
    int32_t* out_n_states, int32_t* out_n_classes, int32_t* out_n_words,
    int32_t* out_start, int32_t* err) {
    *err = 0;
    if (max_states < 1) { *err = 1; return nullptr; }
    Nfa nfa{n_nfa_states, start, -1, eps_off, eps_cond, eps_dst,
            t_off, t_bs, t_dst, bytesets, word_mask};
    int32_t n_words = (n_patterns + 31) / 32;
    if (n_words < 1) n_words = 1;

    std::vector<int32_t> byte_class(256);
    std::vector<int> reps;
    {
        std::unordered_map<std::vector<int32_t>, int32_t, VecHash> sigs;
        std::vector<int32_t> sig(n_bytesets + 1);
        for (int b = 0; b < 256; ++b) {
            for (int32_t i = 0; i < n_bytesets; ++i)
                sig[i] = bs_has(bytesets + static_cast<size_t>(i) * 32, b);
            sig[n_bytesets] = bs_has(word_mask, b);
            auto it = sigs.find(sig);
            if (it == sigs.end()) {
                int32_t cls = static_cast<int32_t>(sigs.size());
                sigs.emplace(sig, cls);
                reps.push_back(b);
                byte_class[b] = cls;
            } else {
                byte_class[b] = it->second;
            }
        }
    }
    int32_t n_classes = static_cast<int32_t>(reps.size());

    // final NFA state -> pattern bit (finals are distinct by construction)
    std::unordered_map<int32_t, int32_t> final_bit;
    for (int32_t i = 0; i < n_patterns; ++i) final_bit.emplace(finals[i], i);

    auto* d = new MultiDfaResult();
    d->byte_class = byte_class;
    d->n_classes = n_classes;
    d->n_words = n_words;
    d->cls_word.resize(n_classes);
    for (int32_t k = 0; k < n_classes; ++k)
        d->cls_word[k] = bs_has(word_mask, reps[k]) ? 1 : 0;

    std::unordered_map<std::vector<int32_t>, int32_t, VecHash> intern;
    std::vector<std::vector<int32_t>> cores;
    std::vector<uint8_t> in_set(n_nfa_states, 0);
    std::vector<int32_t> cl_nw, cl_w, cl_end, stack, moved;

    auto intern_state = [&](std::vector<int32_t>&& key) -> int32_t {
        auto it = intern.find(key);
        if (it != intern.end()) return it->second;
        int32_t sid = static_cast<int32_t>(cores.size());
        if (sid >= max_states) return -1;
        intern.emplace(key, sid);
        cores.push_back(std::move(key));
        d->trans.resize(static_cast<size_t>(sid + 1) * n_classes, -1);
        d->out2.resize(static_cast<size_t>(sid + 1) * 2 * n_words, 0);
        d->accept_w.resize(static_cast<size_t>(sid + 1) * n_words, 0);
        return sid;
    };
    auto set_bits = [&](const std::vector<int32_t>& closed, uint32_t* words) {
        for (int32_t s : closed) {
            auto it = final_bit.find(s);
            if (it != final_bit.end())
                words[it->second / 32] |=
                    (uint32_t{1} << (it->second % 32));
        }
    };

    std::vector<int32_t> start_key{start, L_BEGIN};
    d->start = intern_state(std::move(start_key));

    for (int32_t sid = d->start; sid < static_cast<int32_t>(cores.size()); ++sid) {
        // copy: `cores` reallocates as intern_state appends mid-loop
        std::vector<int32_t> key = cores[sid];
        std::vector<int32_t> core(key.begin(), key.end() - 1);
        int32_t left = key.back();
        closure(nfa, core, left, 0, cl_nw, in_set, stack);
        closure(nfa, core, left, 1, cl_w, in_set, stack);
        closure(nfa, core, left, -1, cl_end, in_set, stack);
        set_bits(cl_nw, d->out2.data() + static_cast<size_t>(sid) * 2 * n_words);
        set_bits(cl_w,
                 d->out2.data() + (static_cast<size_t>(sid) * 2 + 1) * n_words);
        set_bits(cl_end, d->accept_w.data() + static_cast<size_t>(sid) * n_words);
        for (int32_t k = 0; k < n_classes; ++k) {
            int rep = reps[k];
            bool rw = bs_has(word_mask, rep);
            const std::vector<int32_t>& cl = rw ? cl_w : cl_nw;
            moved.clear();
            for (int32_t s : cl) {
                for (int64_t e = t_off[s]; e < t_off[s + 1]; ++e) {
                    if (bs_has(bytesets + static_cast<size_t>(t_bs[e]) * 32, rep))
                        moved.push_back(t_dst[e]);
                }
            }
            std::sort(moved.begin(), moved.end());
            moved.erase(std::unique(moved.begin(), moved.end()), moved.end());
            std::vector<int32_t> mkey(moved);
            mkey.push_back(rw ? L_WORD : L_NONWORD);
            int32_t dst = intern_state(std::move(mkey));
            if (dst < 0) { *err = 1; delete d; return nullptr; }
            d->trans[static_cast<size_t>(sid) * n_classes + k] = dst;
        }
    }
    d->n_states = static_cast<int32_t>(cores.size());

    if (do_minimize) minimize_multi(*d);

    *out_n_states = d->n_states;
    *out_n_classes = d->n_classes;
    *out_n_words = d->n_words;
    *out_start = d->start;
    return d;
}

void lpn_multi_dfa_read(void* handle, int32_t* trans, int32_t* byte_class,
                        int32_t* cls_word, uint32_t* out2,
                        uint32_t* accept_words) {
    auto* d = static_cast<MultiDfaResult*>(handle);
    std::memcpy(trans, d->trans.data(), d->trans.size() * sizeof(int32_t));
    std::memcpy(byte_class, d->byte_class.data(), 256 * sizeof(int32_t));
    std::memcpy(cls_word, d->cls_word.data(),
                d->cls_word.size() * sizeof(int32_t));
    std::memcpy(out2, d->out2.data(), d->out2.size() * sizeof(uint32_t));
    std::memcpy(accept_words, d->accept_w.data(),
                d->accept_w.size() * sizeof(uint32_t));
}

void lpn_multi_dfa_free(void* handle) {
    delete static_cast<MultiDfaResult*>(handle);
}

} // extern "C"
