// Native runtime components for log_parser_tpu.
//
// Two subsystems, exposed with a C ABI for ctypes:
//
//  1. Ingest: one-pass Java-semantics log splitting (String.split("\r?\n"),
//     AnalysisService.java:53 — trailing empty lines dropped, lone "\r" is
//     not a separator) fused with padded-uint8 batch encoding for the
//     device matcher. Replaces the Python/numpy host hot path so a 1M-line
//     corpus never materializes per-line Python strings.
//
//  2. DFA builder: NFA -> byte-class-compressed DFA subset construction
//     with zero-width assertion resolution (the same algorithm as
//     patterns/regex/dfa.py), plus Moore partition-refinement minimization
//     and byte-class recompression. C++ because determinizing a 10k-regex
//     library is minutes of Python set churn but sub-second here.
//
// No external dependencies; built with `g++ -O3 -shared -fPIC`.

#include <algorithm>
#include <array>
#include <cstdint>
#include <cstring>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// 1. Ingest
// ---------------------------------------------------------------------------

// Pass 1: count lines (after Java trailing-empty removal) and max byte
// length. Returns n_lines; *out_max_len receives the longest line's bytes.
int64_t lpn_split_scan(const uint8_t* buf, int64_t n, int64_t* out_max_len) {
    int64_t n_parts = 0;       // parts emitted so far
    int64_t last_nonempty = 0; // parts up to and including the last non-empty
    int64_t max_len = 0;
    int64_t start = 0;
    bool saw_sep = false;
    for (int64_t i = 0; i < n; ++i) {
        if (buf[i] == '\n') {
            saw_sep = true;
            int64_t end = i;
            if (end > start && buf[end - 1] == '\r') --end;
            int64_t len = end - start;
            ++n_parts;
            if (len > 0) {
                last_nonempty = n_parts;
                if (len > max_len) max_len = len;
            }
            start = i + 1;
        }
    }
    // final part (after the last separator, or the whole input)
    {
        int64_t len = n - start;
        ++n_parts;
        if (len > 0) {
            last_nonempty = n_parts;
            if (len > max_len) max_len = len;
        }
    }
    if (!saw_sep) {
        // Java: no separator found -> the whole input, even when empty
        *out_max_len = max_len;
        return 1;
    }
    *out_max_len = max_len;
    return last_nonempty; // trailing empties dropped
}

// Pass 2: fill the padded batch. u8 is a zeroed [rows, width] buffer;
// starts/ends receive byte offsets of each line within buf (for lazy string
// decode on the host); lengths receives min(len, width); needs_host is set
// when a line has non-ASCII or NUL bytes within the clipped window or
// exceeds max_line_bytes. NUL routes to host so the device automata can
// treat byte 0 as padding-only (no byteset admits it), which lets the
// bit-tier steppers drop their per-byte end-of-line gating.
void lpn_split_fill(const uint8_t* buf, int64_t n, int64_t n_lines,
                    uint8_t* u8, int64_t width, int32_t* lengths,
                    uint8_t* needs_host, int64_t* starts, int64_t* ends,
                    int64_t max_line_bytes) {
    int64_t start = 0;
    int64_t row = 0;
    for (int64_t i = 0; i <= n && row < n_lines; ++i) {
        bool at_end = (i == n);
        if (!at_end && buf[i] != '\n') continue;
        int64_t end = i;
        if (!at_end && end > start && buf[end - 1] == '\r') --end;
        int64_t len = end - start;
        int64_t clipped = len < width ? len : width;
        uint8_t* dst = u8 + row * width;
        std::memcpy(dst, buf + start, static_cast<size_t>(clipped));
        uint8_t non_ascii = 0;
        bool has_nul = false;
        for (int64_t j = 0; j < clipped; ++j) {
            non_ascii |= dst[j] & 0x80;
            has_nul = has_nul || (dst[j] == 0);
        }
        lengths[row] = static_cast<int32_t>(clipped);
        needs_host[row] = (non_ascii != 0) || has_nul || (len > max_line_bytes);
        starts[row] = start;
        ends[row] = end;
        ++row;
        start = i + 1;
    }
}

// True byte length of every line (before width clipping) — the prepass
// the width-capping heuristic needs before the batch can be allocated.
// This is deliberately a third walk over the blob (scan → lengths →
// fill): lengths must exist before the width decision, the width before
// the allocation the fill writes into, and a memchr-speed pass is ~15ms
// per GB — noise next to the fill. Keep the split/CRLF semantics in the
// three loops identical.
void lpn_split_lengths(const uint8_t* buf, int64_t n, int64_t n_lines,
                       int32_t* out) {
    int64_t start = 0;
    int64_t row = 0;
    for (int64_t i = 0; i <= n && row < n_lines; ++i) {
        bool at_end = (i == n);
        if (!at_end && buf[i] != '\n') continue;
        int64_t end = i;
        if (!at_end && end > start && buf[end - 1] == '\r') --end;
        int64_t len = end - start;
        out[row++] = len > INT32_MAX ? INT32_MAX : static_cast<int32_t>(len);
        start = i + 1;
    }
}

// ---------------------------------------------------------------------------
// 2. DFA builder
// ---------------------------------------------------------------------------

// Assertion condition codes on epsilon edges (matches nfa.py's "^$bB").
enum Cond : int8_t { COND_NONE = 0, COND_BOL = 1, COND_EOL = 2, COND_B = 3, COND_NB = 4 };
// Left-context classes inside a DFA state (matches dfa.py).
enum Left : int32_t { L_BEGIN = 0, L_NONWORD = 1, L_WORD = 2 };

namespace {

struct DfaResult {
    std::vector<int32_t> trans;      // [n_states * n_classes]
    std::vector<int32_t> byte_class; // [256]
    std::vector<uint8_t> accept;     // [n_states]
    int32_t n_states = 0;
    int32_t n_classes = 0;
    int32_t start = 0;
};

struct VecHash {
    size_t operator()(const std::vector<int32_t>& v) const {
        size_t h = 0x9e3779b97f4a7c15ull ^ v.size();
        for (int32_t x : v) h = (h ^ static_cast<size_t>(x)) * 0x100000001b3ull;
        return h;
    }
};

struct Nfa {
    int32_t n_states;
    int32_t start;
    int32_t fin;
    // CSR epsilon edges
    const int64_t* eps_off;
    const int8_t* eps_cond;
    const int32_t* eps_dst;
    // CSR byte transitions (byteset ids)
    const int64_t* t_off;
    const int32_t* t_bs;
    const int32_t* t_dst;
    const uint8_t* bytesets; // [n_bs][32] bitmask
    const uint8_t* word_mask; // [32]
    int32_t n_bytesets = 0;
};

inline bool bs_has(const uint8_t* mask, int b) {
    return (mask[b >> 3] >> (b & 7)) & 1;
}

// Epsilon closure under (left, right_word) assertion context.
// right_word: 1/0, or -1 for end-of-input. Result: sorted state vector.
void closure(const Nfa& nfa, const std::vector<int32_t>& core, int32_t left,
             int right_word, std::vector<int32_t>& out,
             std::vector<uint8_t>& in_set, std::vector<int32_t>& stack) {
    bool left_word = left == L_WORD;
    bool at_start = left == L_BEGIN;
    bool at_end = right_word < 0;
    bool rw = right_word > 0;
    out.clear();
    stack.clear();
    for (int32_t s : core) {
        if (!in_set[s]) { in_set[s] = 1; out.push_back(s); stack.push_back(s); }
    }
    while (!stack.empty()) {
        int32_t s = stack.back();
        stack.pop_back();
        for (int64_t e = nfa.eps_off[s]; e < nfa.eps_off[s + 1]; ++e) {
            int32_t dst = nfa.eps_dst[e];
            if (in_set[dst]) continue;
            bool ok;
            switch (nfa.eps_cond[e]) {
                case COND_NONE: ok = true; break;
                case COND_BOL: ok = at_start; break;
                case COND_EOL: ok = at_end; break;
                case COND_B: ok = left_word != (at_end ? false : rw); break;
                case COND_NB: ok = left_word == (at_end ? false : rw); break;
                default: ok = false; break;
            }
            if (ok) { in_set[dst] = 1; out.push_back(dst); stack.push_back(dst); }
        }
    }
    for (int32_t s : out) in_set[s] = 0; // reset scratch
    std::sort(out.begin(), out.end());
}

bool contains(const std::vector<int32_t>& sorted_vec, int32_t x) {
    return std::binary_search(sorted_vec.begin(), sorted_vec.end(), x);
}

// Moore partition-refinement minimization + byte-class recompression.
void minimize(DfaResult& d) {
    int32_t n = d.n_states, c = d.n_classes;
    std::vector<int32_t> part(n);
    for (int32_t s = 0; s < n; ++s) part[s] = d.accept[s] ? 1 : 0;
    int32_t n_parts = 2;
    std::vector<int32_t> key(c + 1);
    for (;;) {
        std::unordered_map<std::vector<int32_t>, int32_t, VecHash> sig;
        std::vector<int32_t> next(n);
        for (int32_t s = 0; s < n; ++s) {
            key[0] = part[s];
            for (int32_t k = 0; k < c; ++k) key[k + 1] = part[d.trans[s * c + k]];
            auto it = sig.find(key);
            if (it == sig.end()) {
                int32_t id = static_cast<int32_t>(sig.size());
                sig.emplace(key, id);
                next[s] = id;
            } else {
                next[s] = it->second;
            }
        }
        int32_t m = static_cast<int32_t>(sig.size());
        part.swap(next);
        if (m == n_parts) break;
        n_parts = m;
    }
    // build minimized table (representative per partition)
    std::vector<int32_t> rep(n_parts, -1);
    for (int32_t s = 0; s < n; ++s) if (rep[part[s]] < 0) rep[part[s]] = s;
    std::vector<int32_t> mtrans(static_cast<size_t>(n_parts) * c);
    std::vector<uint8_t> macc(n_parts);
    for (int32_t p = 0; p < n_parts; ++p) {
        int32_t s = rep[p];
        macc[p] = d.accept[s];
        for (int32_t k = 0; k < c; ++k) mtrans[p * c + k] = part[d.trans[s * c + k]];
    }
    int32_t mstart = part[d.start];
    // byte-class recompression: merge now-identical transition columns
    std::unordered_map<std::vector<int32_t>, int32_t, VecHash> colsig;
    std::vector<int32_t> colmap(c);
    std::vector<int32_t> col(n_parts);
    for (int32_t k = 0; k < c; ++k) {
        for (int32_t p = 0; p < n_parts; ++p) col[p] = mtrans[p * c + k];
        auto it = colsig.find(col);
        if (it == colsig.end()) {
            int32_t id = static_cast<int32_t>(colsig.size());
            colsig.emplace(col, id);
            colmap[k] = id;
        } else {
            colmap[k] = it->second;
        }
    }
    int32_t nc = static_cast<int32_t>(colsig.size());
    std::vector<int32_t> ftrans(static_cast<size_t>(n_parts) * nc);
    for (int32_t k = 0; k < c; ++k)
        for (int32_t p = 0; p < n_parts; ++p)
            ftrans[p * nc + colmap[k]] = mtrans[p * c + k];
    for (int b = 0; b < 256; ++b) d.byte_class[b] = colmap[d.byte_class[b]];
    d.trans.swap(ftrans);
    d.accept.swap(macc);
    d.n_states = n_parts;
    d.n_classes = nc;
    d.start = mstart;
}

// Core of the single-pattern subset construction, shared by the ctypes
// entry below and the batched regex pipeline (section 4): builds the
// byte-class-refined, assertion-resolved DFA from a flat NFA view.
// Returns a heap DfaResult, or nullptr with *err = 1 on state blowup.
DfaResult* dfa_build_impl(const Nfa& nfa, int32_t max_states,
                          int32_t do_minimize, int32_t* err) {
    *err = 0;
    if (max_states < 1) { *err = 1; return nullptr; } // can't even intern start
    int32_t start = nfa.start;
    int32_t fin = nfa.fin;
    int32_t n_nfa_states = nfa.n_states;
    const int64_t* t_off = nfa.t_off;
    const int32_t* t_bs = nfa.t_bs;
    const int32_t* t_dst = nfa.t_dst;
    const uint8_t* bytesets = nfa.bytesets;
    const uint8_t* word_mask = nfa.word_mask;
    int32_t n_bytesets = nfa.n_bytesets;

    // --- byte classes: refine every byteset + word membership -------------
    std::vector<int32_t> byte_class(256);
    std::vector<int> reps;
    {
        std::unordered_map<std::vector<int32_t>, int32_t, VecHash> sigs;
        std::vector<int32_t> sig(n_bytesets + 1);
        for (int b = 0; b < 256; ++b) {
            for (int32_t i = 0; i < n_bytesets; ++i)
                sig[i] = bs_has(bytesets + static_cast<size_t>(i) * 32, b);
            sig[n_bytesets] = bs_has(word_mask, b);
            auto it = sigs.find(sig);
            if (it == sigs.end()) {
                int32_t cls = static_cast<int32_t>(sigs.size());
                sigs.emplace(sig, cls);
                reps.push_back(b);
                byte_class[b] = cls;
            } else {
                byte_class[b] = it->second;
            }
        }
    }
    int32_t n_classes = static_cast<int32_t>(reps.size());

    // --- subset construction ---------------------------------------------
    auto* d = new DfaResult();
    d->byte_class = byte_class;
    d->n_classes = n_classes;
    // state 0 = MATCHED sink (absorbing, accepting)
    d->trans.assign(n_classes, 0);
    d->accept.assign(1, 1);

    // key: sorted core states + left tag appended
    std::unordered_map<std::vector<int32_t>, int32_t, VecHash> intern;
    std::vector<std::vector<int32_t>> cores; // per dfa state (id >= 1): key
    std::vector<uint8_t> in_set(n_nfa_states, 0);
    std::vector<int32_t> cl, stack, moved;

    auto intern_state = [&](std::vector<int32_t>&& key) -> int32_t {
        auto it = intern.find(key);
        if (it != intern.end()) return it->second;
        int32_t sid = static_cast<int32_t>(cores.size()) + 1;
        if (sid > max_states) return -1;
        intern.emplace(key, sid);
        cores.push_back(std::move(key));
        d->trans.resize(static_cast<size_t>(sid + 1) * n_classes, -1);
        d->accept.push_back(0);
        return sid;
    };

    std::vector<int32_t> start_key{start, L_BEGIN};
    d->start = intern_state(std::move(start_key));

    for (int32_t sid = d->start; sid <= static_cast<int32_t>(cores.size()); ++sid) {
        // copy: `cores` reallocates as intern_state appends mid-loop
        std::vector<int32_t> key = cores[sid - 1];
        std::vector<int32_t> core(key.begin(), key.end() - 1);
        int32_t left = key.back();
        // end-of-input acceptance
        closure(nfa, core, left, -1, cl, in_set, stack);
        d->accept[sid] = contains(cl, fin) ? 1 : 0;
        for (int32_t k = 0; k < n_classes; ++k) {
            int rep = reps[k];
            bool rw = bs_has(word_mask, rep);
            closure(nfa, core, left, rw ? 1 : 0, cl, in_set, stack);
            if (contains(cl, fin)) {
                d->trans[static_cast<size_t>(sid) * n_classes + k] = 0; // MATCHED
                continue;
            }
            moved.clear();
            for (int32_t s : cl) {
                for (int64_t e = t_off[s]; e < t_off[s + 1]; ++e) {
                    if (bs_has(bytesets + static_cast<size_t>(t_bs[e]) * 32, rep))
                        moved.push_back(t_dst[e]);
                }
            }
            std::sort(moved.begin(), moved.end());
            moved.erase(std::unique(moved.begin(), moved.end()), moved.end());
            std::vector<int32_t> mkey(moved);
            mkey.push_back(rw ? L_WORD : L_NONWORD);
            int32_t dst = intern_state(std::move(mkey));
            if (dst < 0) { *err = 1; delete d; return nullptr; }
            d->trans[static_cast<size_t>(sid) * n_classes + k] = dst;
        }
    }
    d->n_states = static_cast<int32_t>(cores.size()) + 1;

    if (do_minimize) minimize(*d);
    return d;
}

} // namespace

// Build a DFA from a flat NFA. Returns an opaque handle (read with
// lpn_dfa_read, free with lpn_dfa_free) or nullptr with *err set:
//   1 = state cap exceeded.
void* lpn_dfa_build(int32_t n_nfa_states, int32_t start, int32_t fin,
                    const int64_t* eps_off, const int8_t* eps_cond,
                    const int32_t* eps_dst, const int64_t* t_off,
                    const int32_t* t_bs, const int32_t* t_dst,
                    const uint8_t* bytesets, int32_t n_bytesets,
                    const uint8_t* word_mask, int32_t max_states,
                    int32_t do_minimize, int32_t* out_n_states,
                    int32_t* out_n_classes, int32_t* out_start,
                    int32_t* err) {
    Nfa nfa{n_nfa_states, start, fin, eps_off, eps_cond, eps_dst,
            t_off, t_bs, t_dst, bytesets, word_mask, n_bytesets};
    DfaResult* d = dfa_build_impl(nfa, max_states, do_minimize, err);
    if (!d) return nullptr;
    *out_n_states = d->n_states;
    *out_n_classes = d->n_classes;
    *out_start = d->start;
    return d;
}

void lpn_dfa_read(void* handle, int32_t* trans, int32_t* byte_class,
                  uint8_t* accept) {
    auto* d = static_cast<DfaResult*>(handle);
    std::memcpy(trans, d->trans.data(), d->trans.size() * sizeof(int32_t));
    std::memcpy(byte_class, d->byte_class.data(), 256 * sizeof(int32_t));
    std::memcpy(accept, d->accept.data(), d->accept.size());
}

void lpn_dfa_free(void* handle) { delete static_cast<DfaResult*>(handle); }

// ---------------------------------------------------------------------------
// 3. Union multi-pattern DFA builder
// ---------------------------------------------------------------------------
//
// Determinizes the UNION of R pattern NFAs (merged by the Python side into
// one arena with a shared unanchored start) into one DFA whose states carry
// sticky per-pattern output bitmask words — the device then runs R patterns
// with ONE [B] state gather per byte instead of a [B, R] gather
// (patterns/regex/multidfa.py documents the design and the TPU measurement
// that motivates it). Same assertion-aware closure as the single builder;
// no MATCHED sink (each pattern latches independently via output bits read
// from the pre-transition state under the incoming byte's word-ness).

namespace {

struct MultiDfaResult {
    std::vector<int32_t> trans;        // [n_states * n_classes]
    std::vector<int32_t> byte_class;   // [256]
    std::vector<int32_t> cls_word;     // [n_classes] 0/1
    std::vector<uint32_t> out2;        // [n_states * 2 * n_words]
    std::vector<uint32_t> accept_w;    // [n_states * n_words]
    int32_t n_states = 0;
    int32_t n_classes = 0;
    int32_t n_words = 0;
    int32_t start = 0;
};

// Moore minimization for the multi-DFA: initial partition by the full
// output signature (out2 nonword/word rows + end-accept words), refinement
// on transitions, then byte-class recompression with word-ness kept in the
// column signature so cls_word stays well-defined.
void minimize_multi(MultiDfaResult& d) {
    int32_t n = d.n_states, c = d.n_classes, w = d.n_words;
    std::vector<int32_t> part(n);
    {
        std::unordered_map<std::vector<int32_t>, int32_t, VecHash> sigs;
        std::vector<int32_t> sig(3 * w);
        for (int32_t s = 0; s < n; ++s) {
            for (int32_t k = 0; k < w; ++k) {
                sig[k] = static_cast<int32_t>(d.out2[(s * 2) * w + k]);
                sig[w + k] = static_cast<int32_t>(d.out2[(s * 2 + 1) * w + k]);
                sig[2 * w + k] = static_cast<int32_t>(d.accept_w[s * w + k]);
            }
            auto it = sigs.find(sig);
            if (it == sigs.end()) {
                int32_t id = static_cast<int32_t>(sigs.size());
                sigs.emplace(sig, id);
                part[s] = id;
            } else {
                part[s] = it->second;
            }
        }
    }
    int32_t n_parts = -1;
    std::vector<int32_t> key(c + 1);
    for (;;) {
        std::unordered_map<std::vector<int32_t>, int32_t, VecHash> sig;
        std::vector<int32_t> next(n);
        for (int32_t s = 0; s < n; ++s) {
            key[0] = part[s];
            for (int32_t k = 0; k < c; ++k) key[k + 1] = part[d.trans[s * c + k]];
            auto it = sig.find(key);
            if (it == sig.end()) {
                int32_t id = static_cast<int32_t>(sig.size());
                sig.emplace(key, id);
                next[s] = id;
            } else {
                next[s] = it->second;
            }
        }
        int32_t m = static_cast<int32_t>(sig.size());
        part.swap(next);
        if (m == n_parts) break;
        n_parts = m;
    }
    std::vector<int32_t> rep(n_parts, -1);
    for (int32_t s = 0; s < n; ++s) if (rep[part[s]] < 0) rep[part[s]] = s;
    std::vector<int32_t> mtrans(static_cast<size_t>(n_parts) * c);
    std::vector<uint32_t> mout(static_cast<size_t>(n_parts) * 2 * w);
    std::vector<uint32_t> macc(static_cast<size_t>(n_parts) * w);
    for (int32_t p = 0; p < n_parts; ++p) {
        int32_t s = rep[p];
        for (int32_t k = 0; k < c; ++k) mtrans[p * c + k] = part[d.trans[s * c + k]];
        for (int32_t k = 0; k < w; ++k) {
            mout[(p * 2) * w + k] = d.out2[(s * 2) * w + k];
            mout[(p * 2 + 1) * w + k] = d.out2[(s * 2 + 1) * w + k];
            macc[p * w + k] = d.accept_w[s * w + k];
        }
    }
    int32_t mstart = part[d.start];
    // byte-class recompression; word-ness is part of the column signature
    std::unordered_map<std::vector<int32_t>, int32_t, VecHash> colsig;
    std::vector<int32_t> colmap(c);
    std::vector<int32_t> new_word;
    std::vector<int32_t> col(n_parts + 1);
    for (int32_t k = 0; k < c; ++k) {
        col[0] = d.cls_word[k];
        for (int32_t p = 0; p < n_parts; ++p) col[p + 1] = mtrans[p * c + k];
        auto it = colsig.find(col);
        if (it == colsig.end()) {
            int32_t id = static_cast<int32_t>(colsig.size());
            colsig.emplace(col, id);
            colmap[k] = id;
            new_word.push_back(d.cls_word[k]);
        } else {
            colmap[k] = it->second;
        }
    }
    int32_t nc = static_cast<int32_t>(colsig.size());
    std::vector<int32_t> ftrans(static_cast<size_t>(n_parts) * nc);
    for (int32_t k = 0; k < c; ++k)
        for (int32_t p = 0; p < n_parts; ++p)
            ftrans[p * nc + colmap[k]] = mtrans[p * c + k];
    for (int b = 0; b < 256; ++b) d.byte_class[b] = colmap[d.byte_class[b]];
    d.trans.swap(ftrans);
    d.out2.swap(mout);
    d.accept_w.swap(macc);
    d.cls_word.swap(new_word);
    d.n_states = n_parts;
    d.n_classes = nc;
    d.start = mstart;
}

} // namespace

// Build the union multi-DFA. `finals[i]` is pattern i's final NFA state in
// the merged arena. Returns an opaque handle (read with lpn_multi_dfa_read,
// free with lpn_multi_dfa_free) or nullptr with *err = 1 on state blowup.
void* lpn_multi_dfa_build(
    int32_t n_nfa_states, int32_t start, const int64_t* eps_off,
    const int8_t* eps_cond, const int32_t* eps_dst, const int64_t* t_off,
    const int32_t* t_bs, const int32_t* t_dst, const uint8_t* bytesets,
    int32_t n_bytesets, const uint8_t* word_mask, const int32_t* finals,
    int32_t n_patterns, int32_t max_states, int32_t do_minimize,
    int32_t* out_n_states, int32_t* out_n_classes, int32_t* out_n_words,
    int32_t* out_start, int32_t* err) {
    *err = 0;
    if (max_states < 1) { *err = 1; return nullptr; }
    Nfa nfa{n_nfa_states, start, -1, eps_off, eps_cond, eps_dst,
            t_off, t_bs, t_dst, bytesets, word_mask};
    int32_t n_words = (n_patterns + 31) / 32;
    if (n_words < 1) n_words = 1;

    std::vector<int32_t> byte_class(256);
    std::vector<int> reps;
    {
        std::unordered_map<std::vector<int32_t>, int32_t, VecHash> sigs;
        std::vector<int32_t> sig(n_bytesets + 1);
        for (int b = 0; b < 256; ++b) {
            for (int32_t i = 0; i < n_bytesets; ++i)
                sig[i] = bs_has(bytesets + static_cast<size_t>(i) * 32, b);
            sig[n_bytesets] = bs_has(word_mask, b);
            auto it = sigs.find(sig);
            if (it == sigs.end()) {
                int32_t cls = static_cast<int32_t>(sigs.size());
                sigs.emplace(sig, cls);
                reps.push_back(b);
                byte_class[b] = cls;
            } else {
                byte_class[b] = it->second;
            }
        }
    }
    int32_t n_classes = static_cast<int32_t>(reps.size());

    // final NFA state -> pattern bit (finals are distinct by construction)
    std::unordered_map<int32_t, int32_t> final_bit;
    for (int32_t i = 0; i < n_patterns; ++i) final_bit.emplace(finals[i], i);

    auto* d = new MultiDfaResult();
    d->byte_class = byte_class;
    d->n_classes = n_classes;
    d->n_words = n_words;
    d->cls_word.resize(n_classes);
    for (int32_t k = 0; k < n_classes; ++k)
        d->cls_word[k] = bs_has(word_mask, reps[k]) ? 1 : 0;

    std::unordered_map<std::vector<int32_t>, int32_t, VecHash> intern;
    std::vector<std::vector<int32_t>> cores;
    std::vector<uint8_t> in_set(n_nfa_states, 0);
    std::vector<int32_t> cl_nw, cl_w, cl_end, stack, moved;

    auto intern_state = [&](std::vector<int32_t>&& key) -> int32_t {
        auto it = intern.find(key);
        if (it != intern.end()) return it->second;
        int32_t sid = static_cast<int32_t>(cores.size());
        if (sid >= max_states) return -1;
        intern.emplace(key, sid);
        cores.push_back(std::move(key));
        d->trans.resize(static_cast<size_t>(sid + 1) * n_classes, -1);
        d->out2.resize(static_cast<size_t>(sid + 1) * 2 * n_words, 0);
        d->accept_w.resize(static_cast<size_t>(sid + 1) * n_words, 0);
        return sid;
    };
    auto set_bits = [&](const std::vector<int32_t>& closed, uint32_t* words) {
        for (int32_t s : closed) {
            auto it = final_bit.find(s);
            if (it != final_bit.end())
                words[it->second / 32] |=
                    (uint32_t{1} << (it->second % 32));
        }
    };

    std::vector<int32_t> start_key{start, L_BEGIN};
    d->start = intern_state(std::move(start_key));

    for (int32_t sid = d->start; sid < static_cast<int32_t>(cores.size()); ++sid) {
        // copy: `cores` reallocates as intern_state appends mid-loop
        std::vector<int32_t> key = cores[sid];
        std::vector<int32_t> core(key.begin(), key.end() - 1);
        int32_t left = key.back();
        closure(nfa, core, left, 0, cl_nw, in_set, stack);
        closure(nfa, core, left, 1, cl_w, in_set, stack);
        closure(nfa, core, left, -1, cl_end, in_set, stack);
        set_bits(cl_nw, d->out2.data() + static_cast<size_t>(sid) * 2 * n_words);
        set_bits(cl_w,
                 d->out2.data() + (static_cast<size_t>(sid) * 2 + 1) * n_words);
        set_bits(cl_end, d->accept_w.data() + static_cast<size_t>(sid) * n_words);
        for (int32_t k = 0; k < n_classes; ++k) {
            int rep = reps[k];
            bool rw = bs_has(word_mask, rep);
            const std::vector<int32_t>& cl = rw ? cl_w : cl_nw;
            moved.clear();
            for (int32_t s : cl) {
                for (int64_t e = t_off[s]; e < t_off[s + 1]; ++e) {
                    if (bs_has(bytesets + static_cast<size_t>(t_bs[e]) * 32, rep))
                        moved.push_back(t_dst[e]);
                }
            }
            std::sort(moved.begin(), moved.end());
            moved.erase(std::unique(moved.begin(), moved.end()), moved.end());
            std::vector<int32_t> mkey(moved);
            mkey.push_back(rw ? L_WORD : L_NONWORD);
            int32_t dst = intern_state(std::move(mkey));
            if (dst < 0) { *err = 1; delete d; return nullptr; }
            d->trans[static_cast<size_t>(sid) * n_classes + k] = dst;
        }
    }
    d->n_states = static_cast<int32_t>(cores.size());

    if (do_minimize) minimize_multi(*d);

    *out_n_states = d->n_states;
    *out_n_classes = d->n_classes;
    *out_n_words = d->n_words;
    *out_start = d->start;
    return d;
}

void lpn_multi_dfa_read(void* handle, int32_t* trans, int32_t* byte_class,
                        int32_t* cls_word, uint32_t* out2,
                        uint32_t* accept_words) {
    auto* d = static_cast<MultiDfaResult*>(handle);
    std::memcpy(trans, d->trans.data(), d->trans.size() * sizeof(int32_t));
    std::memcpy(byte_class, d->byte_class.data(), 256 * sizeof(int32_t));
    std::memcpy(cls_word, d->cls_word.data(),
                d->cls_word.size() * sizeof(int32_t));
    std::memcpy(out2, d->out2.data(), d->out2.size() * sizeof(uint32_t));
    std::memcpy(accept_words, d->accept_w.data(),
                d->accept_w.size() * sizeof(uint32_t));
}

void lpn_multi_dfa_free(void* handle) {
    delete static_cast<MultiDfaResult*>(handle);
}

// ---------------------------------------------------------------------------
// 4. Batched regex -> DFA pipeline
// ---------------------------------------------------------------------------
//
// Ports the STRICT mode of patterns/regex/parser.py (Java-dialect subset ->
// byte-level AST) and nfa.py (Thompson construction with assertion epsilon
// edges) so a whole library compiles in ONE native call: at 10k regexes the
// Python parse + NFA build + CSR serialization + per-call ctypes marshalling
// cost ~4 s of a cold boot that this pipeline does in well under a second.
// Constructs outside the ported subset return status "unsupported" and the
// Python side falls back to its own pipeline for those regexes — the port
// can only ever DECLINE work, never produce different automata semantics
// (tests/test_native_pipeline.py holds the two pipelines byte-behavior
// equal over the builtin library, the synthetic benches, and the fuzz
// generator's shapes).  Lenient mode stays Python-only: it exists for
// literal extraction, which is not on the boot hot path.

namespace {

struct RxUnsupported {};  // parse/port error -> status 1 (host fallback)

using ByteSet = std::array<uint8_t, 32>;

inline void bs_add(ByteSet& m, int b) { m[b >> 3] |= uint8_t(1u << (b & 7)); }
inline bool bs_test(const ByteSet& m, int b) {
    return (m[b >> 3] >> (b & 7)) & 1;
}
inline ByteSet bs_negate(const ByteSet& m) {
    ByteSet r;
    for (int i = 0; i < 32; ++i) r[i] = uint8_t(~m[i]);
    return r;
}

inline bool ascii_digit(int c) { return c >= '0' && c <= '9'; }
inline bool ascii_alpha(int c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z');
}
inline bool ascii_alnum(int c) { return ascii_digit(c) || ascii_alpha(c); }

struct RxTables {
    ByteSet all{}, dot{}, digit{}, word{}, space{};
    RxTables() {
        for (int b = 0; b < 256; ++b) bs_add(all, b);
        dot = all;
        dot[('\n') >> 3] &= uint8_t(~(1u << ('\n' & 7)));
        dot[('\r') >> 3] &= uint8_t(~(1u << ('\r' & 7)));
        for (int b = '0'; b <= '9'; ++b) bs_add(digit, b);
        for (int b = 0; b < 128; ++b)
            if (ascii_alnum(b) || b == '_') bs_add(word, b);
        for (unsigned char b : {' ', '\t', '\n', '\x0b', '\f', '\r'})
            bs_add(space, b);
    }
};
const RxTables RX;  // matches parser.py's WORD/DIGIT/SPACE/DOT/ALL_BYTES

// POSIX \p{...} contents (parser.py _POSIX_CONTENTS, ASCII semantics).
bool posix_contents(const std::string& name, ByteSet& out) {
    out = ByteSet{};
    if (name == "Alpha") {
        for (int b = 0; b < 128; ++b) if (ascii_alpha(b)) bs_add(out, b);
    } else if (name == "Digit") {
        out = RX.digit;
    } else if (name == "Alnum") {
        for (int b = 0; b < 128; ++b) if (ascii_alnum(b)) bs_add(out, b);
    } else if (name == "Upper") {
        for (int b = 'A'; b <= 'Z'; ++b) bs_add(out, b);
    } else if (name == "Lower") {
        for (int b = 'a'; b <= 'z'; ++b) bs_add(out, b);
    } else if (name == "Space") {
        out = RX.space;
    } else if (name == "Punct") {
        for (int b = 33; b < 127; ++b) if (!ascii_alnum(b)) bs_add(out, b);
    } else if (name == "XDigit") {
        out = RX.digit;
        for (unsigned char b : {'a', 'b', 'c', 'd', 'e', 'f',
                                'A', 'B', 'C', 'D', 'E', 'F'})
            bs_add(out, b);
    } else {
        return false;
    }
    return true;
}

// AST node arena (indices). Mirrors parser.py's node classes.
struct PNode {
    enum Kind : uint8_t { LIT, CAT, ALT, REP, ASSERT, EMPTY } kind;
    int32_t bs = -1;          // LIT: byteset arena index
    std::vector<int32_t> kids; // CAT/ALT children; REP: kids[0]
    int32_t lo = 0, hi = 0;    // REP bounds; hi = -1 means unbounded
    char akind = 0;            // ASSERT: '^' '$' 'b' 'B'
};

struct RxParser {
    const uint8_t* p;
    int64_t n;
    int64_t i = 0;
    bool ci;
    bool quoted_run = false;  // last atom was a multi-char \Q..\E run
    std::vector<PNode> arena;
    std::vector<ByteSet> bsets;

    RxParser(const uint8_t* pat, int64_t len, bool case_insensitive)
        : p(pat), n(len), ci(case_insensitive) {}

    int32_t node(PNode&& nd) {
        arena.push_back(std::move(nd));
        return static_cast<int32_t>(arena.size()) - 1;
    }
    int32_t lit(const ByteSet& bs) {
        bsets.push_back(bs);
        PNode nd; nd.kind = PNode::LIT;
        nd.bs = static_cast<int32_t>(bsets.size()) - 1;
        return node(std::move(nd));
    }
    int32_t empty() { PNode nd; nd.kind = PNode::EMPTY; return node(std::move(nd)); }
    int32_t assertion(char k) {
        PNode nd; nd.kind = PNode::ASSERT; nd.akind = k; return node(std::move(nd));
    }

    int peek() const { return i < n ? p[i] : -1; }
    int take() { return p[i++]; }
    [[noreturn]] void fail() const { throw RxUnsupported{}; }

    ByteSet fold_byte(int b) const {
        ByteSet s{};
        if (ascii_alpha(b)) { bs_add(s, b | 0x20); bs_add(s, b & ~0x20); }
        else bs_add(s, b);
        return s;
    }
    ByteSet single(int b) const { ByteSet s{}; bs_add(s, b); return s; }

    // one CODEPOINT as a literal node (UTF-8 expansion for cp >= 128,
    // case folding for ASCII alpha under ci) — parser.py _literal
    int32_t literal_cp(uint32_t cp) {
        if (cp < 128) return lit(ci ? fold_byte(int(cp)) : single(int(cp)));
        uint8_t buf[4]; int len;
        if (cp < 0x800) {
            buf[0] = uint8_t(0xC0 | (cp >> 6)); buf[1] = uint8_t(0x80 | (cp & 0x3F)); len = 2;
        } else if (cp < 0x10000) {
            buf[0] = uint8_t(0xE0 | (cp >> 12)); buf[1] = uint8_t(0x80 | ((cp >> 6) & 0x3F));
            buf[2] = uint8_t(0x80 | (cp & 0x3F)); len = 3;
        } else {
            buf[0] = uint8_t(0xF0 | (cp >> 18)); buf[1] = uint8_t(0x80 | ((cp >> 12) & 0x3F));
            buf[2] = uint8_t(0x80 | ((cp >> 6) & 0x3F)); buf[3] = uint8_t(0x80 | (cp & 0x3F)); len = 4;
        }
        if (len == 1) return lit(single(buf[0]));
        PNode cat; cat.kind = PNode::CAT;
        for (int k = 0; k < len; ++k) cat.kids.push_back(lit(single(buf[k])));
        return node(std::move(cat));
    }

    // a raw non-ASCII byte in the pattern: it IS the char's UTF-8 bytes,
    // consume the whole sequence as single-byte literals (no folding)
    int32_t literal_utf8_run(int first) {
        int extra = first >= 0xF0 ? 3 : first >= 0xE0 ? 2 : first >= 0xC0 ? 1 : 0;
        if (extra == 0) return lit(single(first));  // stray continuation byte
        PNode cat; cat.kind = PNode::CAT;
        cat.kids.push_back(lit(single(first)));
        for (int k = 0; k < extra && i < n; ++k)
            cat.kids.push_back(lit(single(take())));
        if (cat.kids.size() == 1) return cat.kids[0];
        return node(std::move(cat));
    }

    int32_t parse() {
        int32_t nd = parse_alt();
        if (i < n) fail();
        return nd;
    }

    int32_t parse_alt() {
        std::vector<int32_t> options{parse_cat()};
        while (peek() == '|') { take(); options.push_back(parse_cat()); }
        if (options.size() == 1) return options[0];
        PNode alt; alt.kind = PNode::ALT; alt.kids = std::move(options);
        return node(std::move(alt));
    }

    int32_t parse_cat() {
        std::vector<int32_t> parts;
        while (i < n && peek() != '|' && peek() != ')') parts.push_back(parse_rep());
        if (parts.empty()) return empty();
        if (parts.size() == 1) return parts[0];
        PNode cat; cat.kind = PNode::CAT; cat.kids = std::move(parts);
        return node(std::move(cat));
    }

    int32_t parse_rep() {
        quoted_run = false;
        int32_t atom = parse_atom();  // parse_quoted sets the flag
        bool was_quoted = quoted_run;
        for (;;) {
            int32_t lo, hi;
            if (!parse_quantifier(lo, hi)) return atom;
            // Java binds a quantifier after \Q..\E to the LAST quoted
            // char; this parser returns the run as one atom — decline
            // to the host path (parser.py parse_rep does the same)
            if (was_quoted) fail();
            if (arena[atom].kind == PNode::ASSERT) {
                // quantified assertions: keep if lo > 0, else epsilon
                if (lo == 0) atom = empty();
                continue;
            }
            PNode rep; rep.kind = PNode::REP; rep.kids.push_back(atom);
            rep.lo = lo; rep.hi = hi;
            atom = node(std::move(rep));
        }
    }

    bool parse_quantifier(int32_t& lo, int32_t& hi) {
        int ch = peek();
        if (ch == '*') { take(); lo = 0; hi = -1; }
        else if (ch == '+') { take(); lo = 1; hi = -1; }
        else if (ch == '?') { take(); lo = 0; hi = 1; }
        else if (ch == '{') {
            int64_t mark = i;
            take();
            int64_t v = -1;
            bool overflow = false;
            while (ascii_digit(peek())) {
                if (v < 0) v = 0;
                v = v * 10 + (take() - '0');
                if (v > 1000000) overflow = true;
            }
            if (v < 0) { i = mark; return false; }  // literal '{'
            lo = overflow ? 1000001 : int32_t(v);
            hi = lo;
            if (peek() == ',') {
                take();
                int64_t v2 = -1;
                bool of2 = false;
                while (ascii_digit(peek())) {
                    if (v2 < 0) v2 = 0;
                    v2 = v2 * 10 + (take() - '0');
                    if (v2 > 1000000) of2 = true;
                }
                hi = v2 < 0 ? -1 : of2 ? 1000001 : int32_t(v2);
            }
            if (peek() != '}') { i = mark; return false; }
            take();
            if (hi >= 0 && hi < lo) fail();  // quantifier max < min
        } else {
            return false;
        }
        int nxt = peek();
        if (nxt == '+') fail();       // possessive
        else if (nxt == '?') take();  // lazy: same language
        return true;
    }

    int32_t parse_atom() {
        int ch = take();
        if (ch == '(') return parse_group();
        if (ch == '[') return lit(parse_class());
        if (ch == '.') return lit(RX.dot);
        if (ch == '^') return assertion('^');
        if (ch == '$') return java_dollar();
        if (ch == '\\') return parse_escape();
        if (ch == '*' || ch == '+' || ch == '?') fail();  // dangling
        if (ch >= 0x80) return literal_utf8_run(ch);
        return lit(ci ? fold_byte(ch) : single(ch));
    }

    // Java $ / \Z (non-MULTILINE): end of input, or before a final \r
    // (lines are pre-split on \r?\n) — parser.py _java_dollar
    int32_t java_dollar() {
        int32_t cr_then_end;
        {
            PNode cat; cat.kind = PNode::CAT;
            cat.kids.push_back(lit(single(0x0D)));
            cat.kids.push_back(assertion('$'));
            cr_then_end = node(std::move(cat));
        }
        PNode alt; alt.kind = PNode::ALT;
        alt.kids.push_back(assertion('$'));
        alt.kids.push_back(cr_then_end);
        return node(std::move(alt));
    }

    int32_t parse_group() {
        if (peek() == '?') {
            take();
            int nxt = peek();
            if (nxt == ':') {
                take();
            } else if (nxt == '<') {
                take();
                if (peek() == '=' || peek() == '!') fail();  // lookbehind
                while (peek() != '>' && peek() != -1) take();  // (?<name>...)
                if (peek() != '>') fail();
                take();
            } else if (nxt == '=' || nxt == '!') {
                fail();  // lookahead
            } else if (nxt == '>') {
                fail();  // atomic group
            } else if (nxt != -1 &&
                       (nxt == 'i' || nxt == 'd' || nxt == 'm' || nxt == 's' ||
                        nxt == 'u' || nxt == 'x' || nxt == 'U' || nxt == '-')) {
                std::string flags;
                while (true) {
                    int f = peek();
                    if (f == 'i' || f == 'd' || f == 'm' || f == 's' ||
                        f == 'u' || f == 'x' || f == 'U' || f == '-')
                        flags.push_back(char(take()));
                    else break;
                }
                // strict mode rejects every flag but 'i'/'-'
                for (char f : flags)
                    if (f != 'i' && f != '-') fail();
                if (peek() == ')') {
                    take();          // (?i): rest-of-pattern ci
                    ci = true;
                    return empty();
                }
                if (peek() != ':') fail();
                take();
                bool saved = ci;
                ci = flags.find('i') != std::string::npos &&
                     flags.find('-') == std::string::npos;
                int32_t nd = parse_alt();
                if (peek() != ')') fail();
                take();
                ci = saved;
                return nd;
            } else {
                fail();  // (?P..., (?#..., conditionals, ...
            }
        }
        // plain / named / (?:) body: inline flags scope to this group
        bool saved_ci = ci;
        int32_t nd = parse_alt();
        ci = saved_ci;
        if (peek() != ')') fail();
        take();
        return nd;
    }

    int32_t parse_escape() {
        if (i >= n) fail();  // trailing backslash
        int ch = take();
        switch (ch) {
            case 'b': return assertion('b');
            case 'B': return assertion('B');
            case 'A': return assertion('^');
            case 'z': return assertion('$');
            case 'Z': return java_dollar();
            case 'G': fail();
            case 'k': fail();  // named backreference
            case 'd': return lit(RX.digit);
            case 'D': return lit(bs_negate(RX.digit));
            case 'w': return lit(RX.word);
            case 'W': return lit(bs_negate(RX.word));
            case 's': return lit(RX.space);
            case 'S': return lit(bs_negate(RX.space));
            case 'p': case 'P': {
                ByteSet content;
                if (!parse_posix(content)) fail();
                return lit(ch == 'P' ? bs_negate(content) : content);
            }
            case 'x': return literal_cp(parse_hex(2));
            case 'u': return literal_cp(parse_hex(4));
            case '0': fail();  // octal escape
            case 'Q': return parse_quoted();
            case 'c': fail();  // control escape
            case 'n': return lit(single('\n'));
            case 't': return lit(single('\t'));
            case 'r': return lit(single('\r'));
            case 'f': return lit(single('\f'));
            case 'a': return lit(single(0x07));
            case 'e': return lit(single(0x1B));
            default:
                if (ascii_digit(ch)) fail();  // backreference
                if (ch >= 0x80) return literal_utf8_run(ch);
                return lit(ci ? fold_byte(ch) : single(ch));
        }
    }

    bool parse_posix(ByteSet& out) {
        if (peek() != '{') return false;
        take();
        std::string name;
        while (peek() != '}' && peek() != -1) name.push_back(char(take()));
        if (peek() != '}') return false;
        take();
        return posix_contents(name, out);
    }

    uint32_t parse_hex(int digits) {
        if (i + digits > n) fail();
        uint32_t v = 0;
        for (int k = 0; k < digits; ++k) {
            int c = take();
            int d = ascii_digit(c) ? c - '0'
                    : (c >= 'a' && c <= 'f') ? c - 'a' + 10
                    : (c >= 'A' && c <= 'F') ? c - 'A' + 10
                    : -1;
            if (d < 0) fail();
            v = (v << 4) | uint32_t(d);
        }
        return v;
    }

    int32_t parse_quoted() {  // \Q ... \E literal run
        std::vector<int32_t> parts;
        int n_chars = 0;  // CHARS, not bytes (parity with parser.py's count)
        while (i < n) {
            if (p[i] == '\\' && i + 1 < n && p[i + 1] == 'E') { i += 2; break; }
            int ch = take();
            if (ch < 0x80 || ch >= 0xC0) ++n_chars;  // not a continuation byte
            parts.push_back(ch >= 0x80 ? lit(single(ch))
                                       : lit(ci ? fold_byte(ch) : single(ch)));
        }
        if (parts.empty()) return empty();
        if (n_chars > 1) quoted_run = true;
        if (parts.size() == 1) return parts[0];
        PNode cat; cat.kind = PNode::CAT; cat.kids = std::move(parts);
        return node(std::move(cat));
    }

    // ----------------------------------------------------- character class
    // one class member: returns true with *byte set for a single char
    // usable as a range endpoint, false with *set filled for a shorthand
    bool class_member(int& byte, ByteSet& set) {
        int ch = take();
        if (ch != '\\') {
            if (ch >= 0x80) fail();  // non-ASCII in character class
            byte = ch;
            return true;
        }
        if (i >= n) fail();  // trailing backslash in class
        int esc = take();
        switch (esc) {
            case 'd': set = RX.digit; return false;
            case 'D': set = bs_negate(RX.digit); return false;
            case 'w': set = RX.word; return false;
            case 'W': set = bs_negate(RX.word); return false;
            case 's': set = RX.space; return false;
            case 'S': set = bs_negate(RX.space); return false;
            case 'p': case 'P': {
                ByteSet content;
                if (!parse_posix(content)) fail();
                set = esc == 'P' ? bs_negate(content) : content;
                return false;
            }
            case 'x': {
                uint32_t v = parse_hex(2);
                byte = int(v);
                return true;
            }
            case 'u': {
                uint32_t v = parse_hex(4);
                if (v >= 128) fail();  // non-ASCII in character class
                byte = int(v);
                return true;
            }
            case 'n': byte = '\n'; return true;
            case 't': byte = '\t'; return true;
            case 'r': byte = '\r'; return true;
            case 'f': byte = '\f'; return true;
            case 'a': byte = 0x07; return true;
            case 'e': byte = 0x1B; return true;
            case 'b': fail();  // \b inside character class
            default:
                if (esc >= 0x80) fail();  // non-ASCII in character class
                byte = esc;
                return true;
        }
    }

    ByteSet parse_class() {
        bool negated = false;
        if (peek() == '^') { take(); negated = true; }
        ByteSet members{};
        bool first = true;
        for (;;) {
            int ch = peek();
            if (ch == -1) fail();  // unterminated
            if (ch == ']' && !first) { take(); break; }
            first = false;
            if (ch == '[') fail();  // nested class
            if (ch == '&' && i + 1 < n && p[i + 1] == '&') fail();  // &&
            int b = 0;
            ByteSet shorthand{};
            bool is_byte = class_member(b, shorthand);
            if (!is_byte) {  // shorthand cannot anchor a range
                for (int k = 0; k < 32; ++k) members[k] |= shorthand[k];
                continue;
            }
            int lo = b;
            if (peek() == '-' && i + 1 < n && p[i + 1] != ']') {
                take();
                int hi2 = 0;
                ByteSet dummy{};
                if (!class_member(hi2, dummy)) fail();  // bad range endpoint
                if (hi2 < lo) fail();                   // reversed range
                for (int bb = lo; bb <= hi2; ++bb) {
                    if (ci) { ByteSet f = fold_byte(bb);
                              for (int k = 0; k < 32; ++k) members[k] |= f[k]; }
                    else bs_add(members, bb);
                }
            } else {
                if (ci) { ByteSet f = fold_byte(lo);
                          for (int k = 0; k < 32; ++k) members[k] |= f[k]; }
                else bs_add(members, lo);
            }
        }
        return negated ? bs_negate(members) : members;
    }
};

// Thompson construction mirroring nfa.py (_Builder), with owned storage
// and byteset interning for the CSR view dfa_build_impl consumes.
struct RxNfaBuilder {
    static constexpr int32_t MAX_COUNTED = 64;  // nfa.py _Builder.MAX_COUNTED

    std::vector<std::vector<std::pair<int8_t, int32_t>>> eps;
    std::vector<std::vector<std::pair<int32_t, int32_t>>> trans;  // (bs id, dst)
    std::vector<ByteSet> bsets;
    std::unordered_map<std::string, int32_t> bs_intern;

    int32_t new_state() {
        eps.emplace_back();
        trans.emplace_back();
        return static_cast<int32_t>(eps.size()) - 1;
    }
    int32_t intern_bs(const ByteSet& bs) {
        std::string key(reinterpret_cast<const char*>(bs.data()), 32);
        auto it = bs_intern.find(key);
        if (it != bs_intern.end()) return it->second;
        int32_t id = static_cast<int32_t>(bsets.size());
        bsets.push_back(bs);
        bs_intern.emplace(std::move(key), id);
        return id;
    }
    void add_eps(int32_t src, int32_t dst, int8_t cond = COND_NONE) {
        eps[src].push_back({cond, dst});
    }
    static int8_t cond_code(char k) {
        switch (k) {
            case '^': return COND_BOL;
            case '$': return COND_EOL;
            case 'b': return COND_B;
            default: return COND_NB;  // 'B'
        }
    }

    std::pair<int32_t, int32_t> build(const RxParser& rx, int32_t nd) {
        const PNode& p = rx.arena[nd];
        switch (p.kind) {
            case PNode::EMPTY: {
                int32_t s = new_state(), e = new_state();
                add_eps(s, e);
                return {s, e};
            }
            case PNode::LIT: {
                int32_t s = new_state(), e = new_state();
                trans[s].push_back({intern_bs(rx.bsets[p.bs]), e});
                return {s, e};
            }
            case PNode::ASSERT: {
                int32_t s = new_state(), e = new_state();
                add_eps(s, e, cond_code(p.akind));
                return {s, e};
            }
            case PNode::CAT: {
                auto [first_s, prev_e] = build(rx, p.kids[0]);
                for (size_t k = 1; k < p.kids.size(); ++k) {
                    auto [s, e] = build(rx, p.kids[k]);
                    add_eps(prev_e, s);
                    prev_e = e;
                }
                return {first_s, prev_e};
            }
            case PNode::ALT: {
                int32_t s = new_state(), e = new_state();
                for (int32_t opt : p.kids) {
                    auto [os, oe] = build(rx, opt);
                    add_eps(s, os);
                    add_eps(oe, e);
                }
                return {s, e};
            }
            case PNode::REP: {
                int32_t lo = p.lo, hi = p.hi;
                if (hi >= 0 && hi > MAX_COUNTED) throw RxUnsupported{};
                if (lo > MAX_COUNTED) throw RxUnsupported{};
                int32_t s = new_state();
                int32_t prev = s;
                for (int32_t k = 0; k < lo; ++k) {
                    auto [cs, ce] = build(rx, p.kids[0]);
                    add_eps(prev, cs);
                    prev = ce;
                }
                int32_t e = new_state();
                if (hi < 0) {
                    auto [cs, ce] = build(rx, p.kids[0]);
                    add_eps(prev, cs);
                    add_eps(ce, cs);
                    add_eps(ce, e);
                    add_eps(prev, e);
                } else {
                    add_eps(prev, e);
                    for (int32_t k = 0; k < hi - lo; ++k) {
                        auto [cs, ce] = build(rx, p.kids[0]);
                        add_eps(prev, cs);
                        add_eps(ce, e);
                        prev = ce;
                    }
                }
                return {s, e};
            }
        }
        throw RxUnsupported{};
    }
};

// --------------------------------------------------------- extraction port
// Required-literal sets and exact fixed-length sequences, mirroring
// patterns/regex/literals.py over the C++ AST — including its tie-breaks
// (max() is first-wins) and order (sequence order feeds Shift-Or packing).

struct RxExtract {
    int8_t lit_status = 2;  // 0 set present, 1 None, 2 not computed
    std::vector<std::pair<std::string, uint8_t>> lits;  // (text, ci)
    int8_t seq_status = 2;
    std::vector<std::vector<ByteSet>> seqs;
};

constexpr int MAX_LITERALS = 64;     // literals.py
constexpr int MAX_LITERAL_LEN = 24;
constexpr int MAX_EXACT_SEQS = 16;
constexpr int MAX_EXACT_LEN = 64;

inline int bs_popcount2(const ByteSet& m, int out[2]) {
    int cnt = 0;
    for (int b = 0; b < 256 && cnt <= 2; ++b)
        if (bs_test(m, b)) { if (cnt < 2) out[cnt] = b; ++cnt; }
    return cnt;
}

inline int lit_single(const ByteSet& m) {
    int pair[2];
    return bs_popcount2(m, pair) == 1 ? pair[0] : -1;
}

// {upper, lower} of one ASCII letter -> lowercase byte, else -1
inline int lit_case_pair(const ByteSet& m) {
    int pair[2];
    if (bs_popcount2(m, pair) != 2) return -1;
    int a = pair[0], b = pair[1];
    if (b >= 'a' && b <= 'z' && a == (b & ~0x20)) return b;
    return -1;
}

using LitSet = std::set<std::pair<std::string, uint8_t>>;

// (shortest literal length, -set size): bigger is better
inline std::pair<int, int> lit_score(const LitSet& s) {
    int shortest = INT32_MAX;
    for (auto& [t, ci] : s)
        shortest = std::min(shortest, int(t.size()));
    return {shortest, -int(s.size())};
}

bool extract_lits(const RxParser& rx, int32_t nd, LitSet& out);

bool extract_lits_cat(const RxParser& rx, const PNode& cat, LitSet& out) {
    std::vector<LitSet> candidates;
    std::vector<std::pair<int, uint8_t>> run;  // (byte, ci)
    auto flush_run = [&]() {
        if (run.empty()) return;
        std::string text;
        bool ci = false;
        for (auto& [b, c] : run) { text.push_back(char(b)); ci |= (c != 0); }
        if (ci)
            for (char& c : text)
                if (c >= 'A' && c <= 'Z') c = char(c | 0x20);
        LitSet one;
        one.insert({std::move(text), uint8_t(ci)});
        candidates.push_back(std::move(one));
        run.clear();
    };
    for (int32_t kid : cat.kids) {
        const PNode& part = rx.arena[kid];
        if (part.kind == PNode::ASSERT || part.kind == PNode::EMPTY)
            continue;  // zero-width: adjacency preserved
        const PNode* piece = &part;
        bool appended_rep = false;
        if (part.kind == PNode::REP && part.lo >= 1 &&
            rx.arena[part.kids[0]].kind == PNode::LIT) {
            piece = &rx.arena[part.kids[0]];
            appended_rep = true;
        }
        if (piece->kind == PNode::LIT) {
            const ByteSet& bs = rx.bsets[piece->bs];
            int b = lit_single(bs);
            if (b >= 0) {
                run.push_back({b, 0});
                if (appended_rep) flush_run();
                continue;
            }
            int folded = lit_case_pair(bs);
            if (folded >= 0) {
                run.push_back({folded, 1});
                if (appended_rep) flush_run();
                continue;
            }
        }
        flush_run();
        LitSet sub;
        if (extract_lits(rx, kid, sub)) candidates.push_back(std::move(sub));
    }
    flush_run();
    if (candidates.empty()) return false;
    size_t best = 0;
    auto best_score = lit_score(candidates[0]);
    for (size_t k = 1; k < candidates.size(); ++k) {
        auto s = lit_score(candidates[k]);
        if (s > best_score) { best = k; best_score = s; }  // first-wins ties
    }
    out = std::move(candidates[best]);
    return true;
}

bool extract_lits(const RxParser& rx, int32_t nd, LitSet& out) {
    const PNode& p = rx.arena[nd];
    switch (p.kind) {
        case PNode::EMPTY:
        case PNode::ASSERT:
            return false;
        case PNode::LIT: {
            const ByteSet& bs = rx.bsets[p.bs];
            int b = lit_single(bs);
            if (b >= 0) {
                out.clear();
                out.insert({std::string(1, char(b)), 0});
                return true;
            }
            int folded = lit_case_pair(bs);
            if (folded >= 0) {
                out.clear();
                out.insert({std::string(1, char(folded)), 1});
                return true;
            }
            return false;  // wide class
        }
        case PNode::REP:
            if (p.lo >= 1) return extract_lits(rx, p.kids[0], out);
            return false;
        case PNode::ALT: {
            LitSet uni;
            for (int32_t opt : p.kids) {
                LitSet sub;
                if (!extract_lits(rx, opt, sub)) return false;
                uni.insert(sub.begin(), sub.end());
                if (int(uni.size()) > MAX_LITERALS) return false;
            }
            out = std::move(uni);
            return true;
        }
        case PNode::CAT:
            return extract_lits_cat(rx, p, out);
    }
    return false;
}

bool exact_seqs_node(const RxParser& rx, int32_t nd,
                     std::vector<std::vector<ByteSet>>& out) {
    const PNode& p = rx.arena[nd];
    switch (p.kind) {
        case PNode::LIT:
            out.clear();
            out.push_back({rx.bsets[p.bs]});
            return true;
        case PNode::ALT: {
            std::vector<std::vector<ByteSet>> acc;
            for (int32_t opt : p.kids) {
                std::vector<std::vector<ByteSet>> sub;
                if (!exact_seqs_node(rx, opt, sub)) return false;
                for (auto& s : sub) acc.push_back(std::move(s));
                if (int(acc.size()) > MAX_EXACT_SEQS) return false;
            }
            out = std::move(acc);
            return true;
        }
        case PNode::CAT: {
            std::vector<std::vector<ByteSet>> acc{{}};
            for (int32_t kid : p.kids) {
                std::vector<std::vector<ByteSet>> sub;
                if (!exact_seqs_node(rx, kid, sub)) return false;
                std::vector<std::vector<ByteSet>> next;
                for (auto& a : acc)
                    for (auto& s : sub) {
                        auto joined = a;
                        joined.insert(joined.end(), s.begin(), s.end());
                        next.push_back(std::move(joined));
                    }
                acc = std::move(next);
                if (int(acc.size()) > MAX_EXACT_SEQS) return false;
                for (auto& a : acc)
                    if (int(a.size()) > MAX_EXACT_LEN) return false;
            }
            out = std::move(acc);
            return true;
        }
        case PNode::REP: {
            if (p.hi < 0 || p.lo != p.hi || p.lo < 1) return false;
            std::vector<std::vector<ByteSet>> sub;
            if (!exact_seqs_node(rx, p.kids[0], sub)) return false;
            std::vector<std::vector<ByteSet>> acc{{}};
            for (int32_t k = 0; k < p.lo; ++k) {
                std::vector<std::vector<ByteSet>> next;
                for (auto& a : acc)
                    for (auto& s : sub) {
                        auto joined = a;
                        joined.insert(joined.end(), s.begin(), s.end());
                        next.push_back(std::move(joined));
                    }
                acc = std::move(next);
                if (int(acc.size()) > MAX_EXACT_SEQS) return false;
                for (auto& a : acc)
                    if (int(a.size()) > MAX_EXACT_LEN) return false;
            }
            out = std::move(acc);
            return true;
        }
        default:
            return false;  // Assertion, Empty
    }
}

struct BatchResult {
    std::vector<DfaResult*> dfas;   // nullptr where status != 0
    std::vector<int32_t> status;    // 0 ok, 1 unsupported, 2 state limit
    std::vector<RxExtract> extracts;
    ~BatchResult() { for (auto* d : dfas) delete d; }
};

RxExtract run_extraction(const RxParser& rx, int32_t root) {
    RxExtract ex;
    LitSet lits;
    if (extract_lits(rx, root, lits)) {
        // truncate to MAX_LITERAL_LEN, re-dedup (truncation can merge)
        LitSet cut;
        for (auto& [t, ci] : lits)
            cut.insert({t.size() > MAX_LITERAL_LEN
                            ? t.substr(0, MAX_LITERAL_LEN) : t,
                        ci});
        ex.lit_status = 0;
        ex.lits.assign(cut.begin(), cut.end());
    } else {
        ex.lit_status = 1;
    }
    std::vector<std::vector<ByteSet>> seqs;
    if (exact_seqs_node(rx, root, seqs) && !seqs.empty() &&
        int(seqs.size()) <= MAX_EXACT_SEQS) {
        bool ok = true;
        for (auto& s : seqs)
            if (s.empty() || int(s.size()) > MAX_EXACT_LEN) ok = false;
        if (ok) {
            ex.seq_status = 0;
            ex.seqs = std::move(seqs);
        } else {
            ex.seq_status = 1;
        }
    } else {
        ex.seq_status = 1;
    }
    return ex;
}

} // namespace

// Compile n regexes (concatenated UTF-8 bytes, offs[n+1]) through the full
// parse -> Thompson -> subset-construction pipeline in one call. Per-regex
// status via lpn_regex_batch_get; arrays via lpn_regex_batch_read.
void* lpn_regex_batch_build(const uint8_t* blob, const int64_t* offs,
                            const uint8_t* ci_flags, int32_t n,
                            const uint8_t* word_mask, int32_t max_states,
                            int32_t do_minimize) {
    auto* out = new BatchResult();
    out->dfas.assign(n, nullptr);
    out->status.assign(n, 1);
    out->extracts.resize(n);
    for (int32_t r = 0; r < n; ++r) {
        try {
            RxParser rx(blob + offs[r], offs[r + 1] - offs[r], ci_flags[r] != 0);
            int32_t root = rx.parse();
            out->extracts[r] = run_extraction(rx, root);
            RxNfaBuilder b;
            int32_t start = b.new_state();
            auto [ps, pe] = b.build(rx, root);
            // unanchored find() prefix: any-byte self-loop on start
            ByteSet all{};
            for (int k = 0; k < 32; ++k) all[k] = 0xFF;
            b.trans[start].push_back({b.intern_bs(all), start});
            b.add_eps(start, ps);

            // CSR view over the owned storage
            int32_t ns = static_cast<int32_t>(b.eps.size());
            std::vector<int64_t> eps_off(ns + 1, 0), t_off(ns + 1, 0);
            std::vector<int8_t> eps_cond;
            std::vector<int32_t> eps_dst, t_bs, t_dst;
            for (int32_t s = 0; s < ns; ++s) {
                for (auto& [c, d] : b.eps[s]) { eps_cond.push_back(c); eps_dst.push_back(d); }
                eps_off[s + 1] = static_cast<int64_t>(eps_dst.size());
                for (auto& [bs, d] : b.trans[s]) { t_bs.push_back(bs); t_dst.push_back(d); }
                t_off[s + 1] = static_cast<int64_t>(t_dst.size());
            }
            if (eps_dst.empty()) { eps_cond.push_back(0); eps_dst.push_back(0); }
            if (t_dst.empty()) { t_bs.push_back(0); t_dst.push_back(0); }
            std::vector<uint8_t> flat_bs;
            flat_bs.reserve(b.bsets.size() * 32);
            for (auto& bs : b.bsets)
                flat_bs.insert(flat_bs.end(), bs.begin(), bs.end());
            if (flat_bs.empty()) flat_bs.assign(32, 0);

            Nfa nfa{ns, start, pe,
                    eps_off.data(), eps_cond.data(), eps_dst.data(),
                    t_off.data(), t_bs.data(), t_dst.data(),
                    flat_bs.data(), word_mask,
                    static_cast<int32_t>(b.bsets.size())};
            int32_t err = 0;
            DfaResult* d = dfa_build_impl(nfa, max_states, do_minimize, &err);
            if (!d) {
                out->status[r] = err == 1 ? 2 : 1;
                continue;
            }
            out->dfas[r] = d;
            out->status[r] = 0;
        } catch (const RxUnsupported&) {
            out->status[r] = 1;
        }
    }
    return out;
}

// Returns the regex's status (0 ok / 1 unsupported / 2 state limit); on 0
// fills the DFA dims so the caller can allocate before _read.
int32_t lpn_regex_batch_get(void* handle, int32_t i, int32_t* n_states,
                            int32_t* n_classes, int32_t* start) {
    auto* b = static_cast<BatchResult*>(handle);
    if (b->status[i] != 0) return b->status[i];
    DfaResult* d = b->dfas[i];
    *n_states = d->n_states;
    *n_classes = d->n_classes;
    *start = d->start;
    return 0;
}

void lpn_regex_batch_read(void* handle, int32_t i, int32_t* trans,
                          int32_t* byte_class, uint8_t* accept) {
    auto* b = static_cast<BatchResult*>(handle);
    DfaResult* d = b->dfas[i];
    std::memcpy(trans, d->trans.data(), d->trans.size() * sizeof(int32_t));
    std::memcpy(byte_class, d->byte_class.data(), 256 * sizeof(int32_t));
    std::memcpy(accept, d->accept.data(), d->accept.size());
}

// Totals across ALL regexes, so the extraction payload transfers in ONE
// read call (10k regexes x 2 ctypes crossings measured ~0.6 s of boot).
void lpn_regex_batch_extract_totals(void* handle, int64_t* lit_count,
                                    int64_t* lit_bytes, int64_t* seq_count,
                                    int64_t* seq_pos, int64_t* seq_bytes) {
    auto* b = static_cast<BatchResult*>(handle);
    int64_t lc = 0, lb = 0, sc = 0, sp = 0, sb = 0;
    for (auto& ex : b->extracts) {
        lc += static_cast<int64_t>(ex.lits.size());
        for (auto& [t, ci] : ex.lits) lb += static_cast<int64_t>(t.size());
        sc += static_cast<int64_t>(ex.seqs.size());
        for (auto& s : ex.seqs) {
            sp += static_cast<int64_t>(s.size());
            for (const ByteSet& m : s)
                for (int byte = 0; byte < 256; ++byte)
                    if (bs_test(m, byte)) ++sb;
        }
    }
    *lit_count = lc;
    *lit_bytes = lb;
    *seq_count = sc;
    *seq_pos = sp;
    *seq_bytes = sb;
}

// One-call payload: per-regex statuses/counts, then flattened literals
// (cumulative byte offsets + ci flags + blob) and sequences (positions
// per sequence, bytes per position, position-byte blob).  Sequence and
// position ORDER is load-bearing (it feeds Shift-Or packing); bytes
// within one position are ascending.  statuses: 0 = present, 1 = None,
// 2 = unavailable (parse failed).
void lpn_regex_batch_extract_all(void* handle, int8_t* lit_status,
                                 int32_t* lit_counts, int64_t* lit_offs,
                                 uint8_t* lit_ci, uint8_t* lit_blob,
                                 int8_t* seq_status, int32_t* seq_counts,
                                 int32_t* seq_lens, int32_t* pos_counts,
                                 uint8_t* seq_blob) {
    auto* b = static_cast<BatchResult*>(handle);
    int64_t lk = 0, loff = 0, sk = 0, pk = 0, sboff = 0;
    lit_offs[0] = 0;
    for (size_t r = 0; r < b->extracts.size(); ++r) {
        const RxExtract& ex = b->extracts[r];
        lit_status[r] = ex.lit_status;
        lit_counts[r] = static_cast<int32_t>(ex.lits.size());
        for (auto& [t, ci] : ex.lits) {
            std::memcpy(lit_blob + loff, t.data(), t.size());
            loff += static_cast<int64_t>(t.size());
            lit_ci[lk] = ci;
            lit_offs[++lk] = loff;
        }
        seq_status[r] = ex.seq_status;
        seq_counts[r] = static_cast<int32_t>(ex.seqs.size());
        for (auto& s : ex.seqs) {
            seq_lens[sk++] = static_cast<int32_t>(s.size());
            for (const ByteSet& m : s) {
                int32_t cnt = 0;
                for (int byte = 0; byte < 256; ++byte)
                    if (bs_test(m, byte)) {
                        seq_blob[sboff++] = static_cast<uint8_t>(byte);
                        ++cnt;
                    }
                pos_counts[pk++] = cnt;
            }
        }
    }
}

void lpn_regex_batch_free(void* handle) {
    delete static_cast<BatchResult*>(handle);
}

// ---------------------------------------------------------------------------
// 5. Aho-Corasick builder
// ---------------------------------------------------------------------------
//
// Same algorithm as patterns/regex/ac.py (goto-complete automaton, fail
// links folded in, outputs pre-OR'd along fail chains, byte-class
// compression): the Python BFS costs ~1.6 s of a 10k-library cold boot.

namespace {

struct AcResult {
    std::vector<int32_t> goto_tab;   // [n_nodes * n_classes]
    std::vector<int32_t> byte_class; // [256]
    std::vector<uint32_t> out_words; // [n_nodes * n_words]
    std::vector<uint8_t> has_out;    // [n_nodes]
    int32_t n_nodes = 0;
    int32_t n_classes = 0;
    int32_t n_words = 0;
};

} // namespace

void* lpn_ac_build(const uint8_t* blob, const int64_t* offs,
                   const int32_t* groups, int32_t n_literals,
                   int32_t n_groups, int32_t* out_nodes,
                   int32_t* out_classes, int32_t* out_nwords) {
    int32_t n_words = n_groups > 0 ? (n_groups + 31) / 32 : 1;

    // trie: per-node sparse children (byte -> node)
    std::vector<std::vector<std::pair<uint8_t, int32_t>>> children(1);
    std::vector<std::vector<int32_t>> lids(1);
    for (int32_t lid = 0; lid < n_literals; ++lid) {
        int32_t node = 0;
        for (int64_t j = offs[lid]; j < offs[lid + 1]; ++j) {
            uint8_t b = blob[j];
            int32_t nxt = -1;
            for (auto& [cb, cn] : children[node])
                if (cb == b) { nxt = cn; break; }
            if (nxt < 0) {
                nxt = static_cast<int32_t>(children.size());
                children[node].push_back({b, nxt});
                children.emplace_back();
                lids.emplace_back();
            }
            node = nxt;
        }
        lids[node].push_back(lid);
    }
    int32_t n_nodes = static_cast<int32_t>(children.size());

    // byte classes: bytes used by any edge, ascending; 0 = "other"
    std::array<uint8_t, 256> used{};
    for (auto& ch : children)
        for (auto& [b, _] : ch) used[b] = 1;
    std::vector<int32_t> byte_class(256, 0);
    std::vector<int32_t> class_byte{0};
    for (int b = 0; b < 256; ++b)
        if (used[b]) {
            byte_class[b] = static_cast<int32_t>(class_byte.size());
            class_byte.push_back(b);
        }
    int32_t n_classes = static_cast<int32_t>(class_byte.size());

    auto* r = new AcResult();
    r->n_nodes = n_nodes;
    r->n_classes = n_classes;
    r->n_words = n_words;
    r->byte_class = byte_class;
    r->goto_tab.assign(static_cast<size_t>(n_nodes) * n_classes, 0);
    r->out_words.assign(static_cast<size_t>(n_nodes) * n_words, 0);

    // dense per-node child-by-class lookup scratch, rebuilt per node
    std::vector<int32_t> fail(n_nodes, 0);
    std::vector<int32_t> child_of(n_classes, -1);
    std::vector<int32_t> queue;
    queue.reserve(n_nodes);

    // seed outputs
    for (int32_t nd = 0; nd < n_nodes; ++nd)
        for (int32_t lid : lids[nd]) {
            int32_t gid = groups[lid];
            r->out_words[static_cast<size_t>(nd) * n_words + gid / 32] |=
                uint32_t(1) << (gid % 32);
        }

    for (auto& [b, cn] : children[0]) {
        r->goto_tab[byte_class[b]] = cn;
        queue.push_back(cn);
    }
    for (size_t qi = 0; qi < queue.size(); ++qi) {
        int32_t node = queue[qi];
        // out[node] |= out[fail[node]] (fail is shallower: already final)
        for (int32_t w = 0; w < n_words; ++w)
            r->out_words[static_cast<size_t>(node) * n_words + w] |=
                r->out_words[static_cast<size_t>(fail[node]) * n_words + w];
        for (auto& [b, cn] : children[node]) child_of[byte_class[b]] = cn;
        const int32_t* fgoto =
            r->goto_tab.data() + static_cast<size_t>(fail[node]) * n_classes;
        int32_t* ngoto =
            r->goto_tab.data() + static_cast<size_t>(node) * n_classes;
        for (int32_t cls = 1; cls < n_classes; ++cls) {
            int32_t child = child_of[cls];
            if (child >= 0) {
                fail[child] = fgoto[cls];
                ngoto[cls] = child;
                queue.push_back(child);
            } else {
                ngoto[cls] = fgoto[cls];
            }
        }
        for (auto& [b, cn] : children[node]) child_of[byte_class[b]] = -1;
    }

    r->has_out.assign(n_nodes, 0);
    for (int32_t nd = 0; nd < n_nodes; ++nd)
        for (int32_t w = 0; w < n_words; ++w)
            if (r->out_words[static_cast<size_t>(nd) * n_words + w]) {
                r->has_out[nd] = 1;
                break;
            }

    *out_nodes = n_nodes;
    *out_classes = n_classes;
    *out_nwords = n_words;
    return r;
}

void lpn_ac_read(void* handle, int32_t* goto_tab, int32_t* byte_class,
                 uint32_t* out_words, uint8_t* has_out) {
    auto* r = static_cast<AcResult*>(handle);
    std::memcpy(goto_tab, r->goto_tab.data(),
                r->goto_tab.size() * sizeof(int32_t));
    std::memcpy(byte_class, r->byte_class.data(), 256 * sizeof(int32_t));
    std::memcpy(out_words, r->out_words.data(),
                r->out_words.size() * sizeof(uint32_t));
    std::memcpy(has_out, r->has_out.data(), r->has_out.size());
}

void lpn_ac_free(void* handle) { delete static_cast<AcResult*>(handle); }

} // extern "C"
