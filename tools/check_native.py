"""Triage the native scanner library without booting the server.

The classic failure is a prebuilt ``log_parser_native.so`` carried from
a newer build host: dlopen refuses it with a one-line ``GLIBCXX_x.y.z
not found`` and the process silently runs the scalar fallback at a
fraction of the ingest rate. This tool prints the whole diagnosis in
one shot:

    python tools/check_native.py            # table + load attempt
    python tools/check_native.py --json     # machine-readable
    python tools/check_native.py --rebuild  # force a from-source rebuild

- which GLIBCXX symbol versions the .so REQUIRES (read straight from
  its .dynstr, same list ``strings … | grep GLIBCXX`` shows);
- which versions the host's libstdc++ PROVIDES (the copy already mapped
  into this process wins — that is the one dlopen will use);
- the gap, the toolchain available for a rebuild, and the actual load
  attempt's outcome (the same reason string ``logparser_native_loaded``
  exposes on /metrics and GET /trace/last reports under ``native``).

Exit code: 0 when the library loads, 1 when it doesn't, 2 when a
requested ``--rebuild`` fails. In a container, the Dockerfile's
``native-rebuild`` stage runs the same from-source path so the shipped
.so always matches the image's own libstdc++.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from log_parser_tpu import native  # noqa: E402


def triage(rebuild: bool = False) -> dict:
    doc: dict = {
        "source": str(native._SRC),
        "source_exists": native._SRC.exists(),
        "so": str(native._SO),
        "so_exists": native._SO.exists(),
        "toolchain": shutil.which("g++"),
    }
    if rebuild:
        try:
            native._SO.unlink()
        except OSError:
            pass
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
               str(native._SRC), "-o", str(native._SO)]
        doc["rebuild_cmd"] = " ".join(cmd)
        try:
            native._SO.parent.mkdir(parents=True, exist_ok=True)
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=300
            )
            doc["rebuild_rc"] = proc.returncode
            if proc.returncode != 0:
                doc["rebuild_stderr"] = proc.stderr.strip()[:2000]
        except (OSError, subprocess.TimeoutExpired) as exc:
            doc["rebuild_rc"] = -1
            doc["rebuild_stderr"] = str(exc)
    doc["glibcxx"] = native.glibcxx_triage()
    # the real load attempt, exactly as the server would do it at boot
    doc["loaded"] = native.available()
    doc["load_error"] = native.stats().get("loadError")
    return doc


def render(doc: dict) -> None:
    g = doc["glibcxx"]

    def row(k, v):
        print(f"{k:<22} {v}")

    row("source", f"{doc['source']}"
        f"{'' if doc['source_exists'] else '  (MISSING)'}")
    row("shared object", f"{doc['so']}"
        f"{'' if doc['so_exists'] else '  (MISSING)'}")
    row("toolchain (g++)", doc["toolchain"] or "not found")
    row("host libstdc++", g["libstdcxx"] or "not found")
    row("required GLIBCXX", ", ".join(g["required"]) or "(none read)")
    provided = g["provided"]
    row("provided GLIBCXX",
        f"… up to {provided[-1]} ({len(provided)} versions)"
        if provided else "(none read)")
    if g["missing"]:
        row("MISSING", ", ".join(g["missing"]))
    if "rebuild_rc" in doc:
        row("rebuild", "ok" if doc["rebuild_rc"] == 0
            else f"FAILED (rc={doc['rebuild_rc']})")
        if doc.get("rebuild_stderr"):
            print(doc["rebuild_stderr"])
    row("load attempt", "ok — native scanner active" if doc["loaded"]
        else f"FAILED: {doc['load_error']}")
    if not doc["loaded"] and g["missing"]:
        print(
            "\nthe .so was built against a newer libstdc++ than this "
            "host ships.\nFix: rerun with --rebuild (needs g++), or "
            "build inside the image via the Dockerfile native-rebuild "
            "stage."
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diagnose the native scanner's GLIBCXX linkage")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the triage as JSON")
    ap.add_argument("--rebuild", action="store_true",
                    help="force a from-source rebuild before the load "
                         "attempt")
    args = ap.parse_args(argv)
    doc = triage(rebuild=args.rebuild)
    if args.as_json:
        print(json.dumps(doc, indent=2))
    else:
        render(doc)
    if args.rebuild and doc.get("rebuild_rc") != 0:
        return 2
    return 0 if doc["loaded"] else 1


if __name__ == "__main__":
    sys.exit(main())
