"""Chaos sweep: the fault-injection DSL against a LIVE server.

The chaos tests (tests/test_admission.py, tests/test_faults.py) exercise
the ladder in-process; this tool runs the same scenarios the way an
operator meets them — a real ``python -m log_parser_tpu.serve`` child
process, concurrent HTTP clients, signals — and prints a pass/fail table.
Every scenario pins ``LOG_PARSER_TPU_FAULT_SEED``, so a failing row
reproduces bit-identically when re-run.

Scenarios:

- ``baseline``        no faults — every request 200.
- ``device-raise``    probabilistic device faults — every request still
                      200 (golden fallback absorbs them), fallbackCount
                      moved, NOTHING shed.
- ``device-wedge``    a permanent device hang under ``--device-timeout``
                      — breaker opens, service stays 200 from the host
                      path, health shows DEGRADED.
- ``queue-shed``      slow ingest + max-inflight=1/max-queue=1 + a burst
                      — some 200s, some 429s carrying Retry-After.
- ``drain``           SIGTERM with a slow request in flight — in-flight
                      answered 200, /health/ready 503 during drain,
                      child exits 0.

Batcher group (``--group batcher``; micro-batching on — docs/OPS.md
"Micro-batching"):

- ``batch-coalesce``     a burst under a generous --batch-wait-ms —
                         every request 200, /trace/last shows real
                         coalescing (maxBatchSeen ≥ 2).
- ``batch-demux-drop``   a seeded ``batcher_demux`` fault drops ONE
                         demux slot — exactly that request 500s, its
                         batchmates answer 200 untouched.
- ``batch-device-fault`` an injected device fault fails a WHOLE batch —
                         every member still answers 200 from the golden
                         per-request fallback.

State group (``--group state``; durable frequency state + hot reload —
docs/OPS.md "State durability & recovery"):

- ``state-kill9-replay``     N requests, SIGKILL mid-stream, restart on
                             the same ``--state-dir``, remainder — final
                             frequency stats and scores identical to an
                             uninterrupted run.
- ``state-torn-tail``        a ``journal_torn`` fault leaves half a
                             frame as the WAL's final bytes — the
                             restart quarantines it to ``.torn``,
                             replays every whole record, and serves.
- ``state-canary-rollback``  an injected ``reload_canary`` fault turns
                             ``POST /patterns/reload`` into a structured
                             409 — the old banks keep serving, scores
                             unchanged; the next reload (budget spent)
                             succeeds.
- ``state-reload-under-load``  a concurrent burst of batched requests
                             races a hot reload — zero failed requests,
                             the reload completes, epoch bumps.

Poison group (``--group poison``; quarantine + bisection + shadow
verification — docs/OPS.md "Poison-request triage" / "Shadow
divergence"):

- ``poison-batch-isolate``     ONE poison request inside a 16-request
                               batched stream: bisection isolates it, the
                               other 15 are served ON-DEVICE (zero
                               fallbacks for them), the poison serves
                               from golden, its fingerprint quarantines,
                               and a repeat never reaches the device step
                               (the keyed fault's fire counter is pinned).
- ``poison-ttl-readmit``       a quarantined fingerprint is served
                               golden without touching the device until
                               ``--quarantine-ttl-s`` expires, then
                               re-admitted to the device step with a
                               clean slate.
- ``shadow-divergence-breaker``  an injected ``shadow`` divergence flips
                               /q/health to DEGRADED and opens the
                               pattern's breaker; after the cool-down the
                               half-open probe (forced shadow sample)
                               closes it and health recovers.

Linecache group (``--group linecache``; routing-tier template cache —
docs/OPS.md "Line cache (routing tier)"):

- ``linecache-hit-under-reload-swap``  a burst of cache-hit requests
                               races a hot pattern reload — zero failed
                               requests, the swap flushes the cache
                               exactly once (epochFlushes bumps), and
                               the new epoch repopulates it.
- ``linecache-eviction-under-load``  a cache budgeted far below the
                               working set keeps serving exact results
                               while evicting LRU lines and never
                               exceeds its resident-byte ceiling.
- ``linecache-breaker-partial-invalidation``  a shadow-divergence
                               breaker trip while the stream is served
                               from cache: the tripped pattern's
                               columns re-evaluate from the exact host
                               regex over CACHED rows (per-pattern
                               invalidation by construction) and the
                               other patterns keep hitting the cache.
- ``linecache-shadow-parity``  rate-1.0 online shadow verification over
                               a cache-served stream — every response,
                               including all-hit requests that never
                               touch the device, re-runs on the golden
                               host path; zero divergences is the
                               in-service cache-on ≡ cache-off proof.

Distributed group (``--group distributed``; needs a jax build whose CPU
backend supports multi-process collectives — reported SKIP otherwise):

- ``follower-degrade``  coordinator + follower sharing a jax.distributed
                        runtime; a seeded follower hang exhausts the
                        bounded-broadcast budget, requests keep answering
                        200 with the ``degraded: distributed-fallback``
                        marker, the heartbeat re-admits the mesh
                        (/trace/last ``distributed.mode`` back to
                        ``distributed``), and SIGTERM still shuts both
                        processes down cleanly.

kernel group (--group kernel): the Pallas union-DFA kernel tier behind
                        --pallas-dfa. One scenario pins the /trace/last
                        ``kernel`` verdict block (admission reason +
                        dispatch counters); the other arms a
                        ``kernel_raise`` fault and proves the whole
                        batch falls back to the XLA scan tier with
                        parity preserved — clients never see the fault
                        and the golden fallbackCount stays zero.

Streaming group (``--group streaming``; follow-mode sessions —
docs/OPS.md "Streaming follow-mode"):

- ``stream-device-fault-golden``  an injected device fault mid-session
                        flips the session to a golden continuation: it
                        keeps emitting, closes with a ``final`` frame,
                        and ``stream.goldenContinuations`` moves — the
                        client never sees the fault.
- ``stream-poison-kill``  a keyed poison chunk kills exactly its own
                        SESSION (structured ``error`` frame, reason
                        ``poison``, fingerprint struck) — the server and
                        a parallel fresh session keep serving.
- ``stream-reload-rebase``  a hot pattern reload lands while a session
                        is open between chunks; the next chunk re-bases
                        the session onto the new banks
                        (``sessionsRebased`` bumps) and it still closes
                        with a ``final`` frame.
- ``stream-ttl-reap``   idle sessions under ``--stream-ttl-s 1`` are
                        reaped while a concurrent parse burst runs —
                        their admission slots release
                        (``openSessions`` 0, gate ``inflight`` 0) and
                        the server stays healthy.

Tenant group (``--group tenant``; multi-tenant serving — docs/OPS.md
"Multi-tenant serving"):

- ``tenant-quota-shed``     one tenant's lines/s bucket empties under a
                        run of requests — that tenant gets structured
                        429s (``reason: tenant rate``, Retry-After ≥ 1)
                        while the default tenant keeps answering 200;
                        /trace/last pins ``admission.shedTenant`` and
                        the tenant's ``quota.shedRate``.
- ``tenant-evict-rebuild``  a bank budget sized for ~1.5 tenants forces
                        LRU eviction when a second tenant arrives and a
                        rebuild when the first returns — every request
                        (including a concurrent default-tenant burst)
                        still answers 200 and the ``tenants`` trace
                        block shows ``evicted``/``rebuilds`` moving.
- ``tenant-reload-isolated``  a hot pattern reload scoped to tenant A
                        (``X-Tenant`` on ``POST /patterns/reload``)
                        races a burst of tenant-B traffic — zero failed
                        B requests, A's ``reloadEpoch`` bumps, B's and
                        the default tenant's stay put.

Miner group (``--group miner``; template miner — docs/OPS.md "Template
miner"):

- ``miner-tap-overflow``    a wedged miner worker (``miner_hang:inf``)
                        under a 4-slot tap — the bounded queue fills,
                        ``miner.dropped`` climbs on /trace/last, and the
                        hot path never notices (every request 200).
- ``miner-reject-identity``  a candidate rejected at the vet gates
                        (byte-identical to a curated regex) leaves the
                        serving bank OBJECT-identical and the reload
                        epoch untouched.
- ``miner-reload-race``     mined admission racing a concurrent curated
                        reload under the quiesce gate — a clean
                        retryable ``mined-swap``, curated reload lands
                        first, the candidate re-admits on a later pump
                        against the post-reload library.

Spans group (``--group spans``; causal span tracing — docs/OPS.md "Span
tracing & utilization accounting"):

- ``spans-fault-site``   a device fault under micro-batching — the
                        faulted dispatch records its span carrying the
                        failure attr, the same flush trace still closes
                        with its demux span, and flush/request traces
                        keep linking each other both ways.
- ``spans-sample-drop``  ``--trace-sample 0`` with the slow bar lifted
                        out of reach — request traces are dropped,
                        force-kept flush traces still commit, and the
                        staging dict drains to zero (no orphans).

Migrate group (``--group migrate``; crash-safe tenant live migration +
health-driven drain — docs/OPS.md "Tenant migration & drain"):

- ``migrate-live-cutover``     acme moves between two processes over
                        HTTP; the source 307-forwards with Location +
                        Retry-After, the target serves the migrated
                        state.
- ``migrate-crash-mid-export`` the ``migrate_export`` fault under the
                        quiesce gate: structured 409 abort, the source
                        keeps the tenant, no forward.
- ``migrate-crash-pre-cutover`` the ``migrate_cutover`` fault after the
                        target staged: the source aborts and keeps
                        serving; the target's staged copy never
                        activates (single-owner invariant).
- ``migrate-drain-under-burst`` /admin/drain races a burst: every
                        tenant closes under --drain-deadline-s,
                        /q/health flips to a DRAINING 503, SIGTERM
                        exits clean.
- ``migrate-stream-handoff``   a live follow-mode session on the moving
                        tenant is closed with an explicit error frame
                        naming the new owner — cutover never hangs on
                        a pinned stream.

Replica group (``--group replica``; warm-standby replication + fenced
failover — docs/OPS.md "Warm-standby replication & failover"):

- ``replica-failover-kill9``   a live primary/standby pair shipping WAL
                        (``logparser_replication_lag_*`` visible on
                        /metrics) loses its primary to SIGKILL; the
                        armed supervisor promotes the standby, which
                        then serves the tenant's replicated history.
- ``replica-stale-primary-demotes`` the standby is promoted while the
                        primary is still alive (the operator error the
                        fence exists for): the stale primary's next
                        shipped batch is refused with the higher
                        epoch, it demotes itself, and client traffic
                        307-forwards to the new owner.
- ``replica-lagging-promotion`` the primary is killed with an unshipped
                        WAL tail; a manual /admin/promote serves the
                        acked prefix — the documented state-loss bound
                        — and the promotion is journaled.

Fleet group (``--group fleet``; router front-door + signal-driven
placement — docs/OPS.md "Fleet routing & placement"): a real
``--role router`` process over real backend serving processes.

- ``fleet-backend-kill-reroute`` a backend dies by SIGKILL mid-fleet:
                        the ring evicts it after ``--fleet-down-after``
                        failures, every subsequent request is served by
                        the survivors (zero client errors), and the
                        router's health + ``logparser_fleet_*`` metrics
                        reflect the loss.
- ``fleet-hot-tenant-automove`` one tenant burns its quota (429 sheds):
                        the placer scrapes the shed rate off the
                        backend's /metrics and live-migrates the tenant
                        to the least-loaded backend; clients see only
                        200s and structured 429s, never a 5xx, and the
                        tenant serves from its new owner afterwards.
- ``fleet-budget-rebalance`` fleet-arbitrated budgets replace the
                        per-process flags: the router pushes
                        traffic-derived line-cache + tenant-residency
                        shares via POST /admin/budget and both sides
                        agree — the backend's /trace/last shows the
                        applied share, the router's /fleet/status the
                        assignment.

Pressure group (``--group pressure``; resource-exhaustion robustness —
docs/OPS.md "Resource exhaustion"):

- ``pressure-soft-compaction`` a forced ``watermark:soft`` raise: the
                        ladder reclaims (a seeded terminal migration
                        journal compacts to its decision records),
                        /q/health carries a DEGRADED pressure check,
                        and responses stay 200 WITHOUT a durability
                        stamp — soft never downgrades durability.
- ``pressure-hard-degrade-rearm`` a @times-bounded ``watermark:hard``
                        raise: 200s stamped ``durability: degraded``
                        with the WAL diverted to the in-memory ring,
                        then automatic hysteretic recovery — the stamp
                        disappears and fsync'd journaling re-arms from
                        a clean snapshot barrier.
- ``pressure-retry-storm-shed`` a dead backend under an armed
                        ``retry_storm`` fault: router re-route retries
                        shed structured 503s (``retry budget
                        exhausted``) and the service recovers once the
                        corpse is evicted; the identical kill with
                        ``--retry-budget 0`` retries unbounded to a
                        200 — the storm the budget prevents.

Usage: python tools/chaos_sweep.py [--only NAME]
                                   [--group base|batcher|state|poison|linecache|kernel|streaming|distributed|tenant|miner|obs|spans|migrate|replica|fleet|pressure|all]
                                   [--keep-logs]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# in-process drills import log_parser_tpu directly (script mode puts
# tools/ on sys.path, not the repo root)
sys.path.insert(0, REPO)
PATTERN_DIR = os.path.join(REPO, "log_parser_tpu", "patterns", "builtin")
LOGS = "INFO boot\njava.lang.OutOfMemoryError: heap\nINFO after"
PAYLOAD = json.dumps(
    {"pod": {"metadata": {"name": "chaos"}}, "logs": LOGS}
).encode()


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def post(url: str, headers: dict | None = None, timeout: float = 30.0):
    req = urllib.request.Request(
        url + "/parse",
        data=PAYLOAD,
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def get(url: str, path: str):
    try:
        with urllib.request.urlopen(url + path, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class Server:
    """One serve child; scenario args via CLI flags, chaos via env."""

    def __init__(self, name: str, args: list[str], env: dict[str, str],
                 port: int | None = None):
        # replica pairs need each other's URL at boot, so their ports are
        # allocated up front and passed in
        self.port = port or free_port()
        self.url = f"http://127.0.0.1:{self.port}"
        self.log = tempfile.NamedTemporaryFile(
            "wb", prefix=f"chaos_{name}_", suffix=".log", delete=False
        )
        child_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PYTHONUNBUFFERED": "1",
            **env,
        }
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "log_parser_tpu.serve",
                "--pattern-dir", PATTERN_DIR,
                "--host", "127.0.0.1", "--port", str(self.port),
                *args,
            ],
            cwd=REPO,
            env=child_env,
            stdout=self.log,
            stderr=subprocess.STDOUT,
        )

    def wait_ready(self, timeout: float = 90.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"server exited rc={self.proc.returncode} before ready "
                    f"(log: {self.log.name})"
                )
            try:
                status, _ = get(self.url, "/health/ready")
                if status == 200:
                    return
            except OSError:
                pass
            time.sleep(0.25)
        raise RuntimeError(f"server never became ready (log: {self.log.name})")

    def stop(self, expect_zero: bool = False) -> int:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(10)
        rc = self.proc.returncode
        if expect_zero and rc != 0:
            raise AssertionError(f"expected clean exit, got rc={rc}")
        return rc


class Burst:
    """N concurrent posts; collect (status, headers) pairs."""

    def __init__(self, url: str, n: int, headers: dict | None = None):
        self.results: list[tuple[int, dict]] = []
        self._lock = threading.Lock()

        def one():
            status, _, hdrs = post(url, headers)
            with self._lock:
                self.results.append((status, hdrs))

        self.threads = [threading.Thread(target=one) for _ in range(n)]
        for t in self.threads:
            t.start()

    def join(self, timeout: float = 60.0):
        for t in self.threads:
            t.join(timeout)
        assert all(not t.is_alive() for t in self.threads), "burst stuck"
        return self.results


# ------------------------------------------------------------- scenarios


def scenario_baseline(srv: Server):
    for _ in range(4):
        status, body, _ = post(srv.url)
        assert status == 200, f"expected 200, got {status}"
        assert body["summary"]["significantEvents"] >= 1
    _, trace = get(srv.url, "/trace/last")
    assert trace["fallbackCount"] == 0, trace["fallbackCount"]


def scenario_device_raise(srv: Server):
    statuses = [post(srv.url)[0] for _ in range(12)]
    assert statuses == [200] * 12, statuses
    _, trace = get(srv.url, "/trace/last")
    fired = trace["faults"]["fired"]["device_raise"]
    assert 0 < fired < 12, f"seeded p=0.5 fired {fired}/12"
    assert trace["fallbackCount"] == fired, trace
    assert trace["admission"]["shedQueueFull"] == 0


def scenario_device_wedge(srv: Server):
    # warm up off the wedge (after=1), then hit it: still 200, via golden
    assert post(srv.url)[0] == 200
    statuses = [post(srv.url)[0] for _ in range(3)]
    assert statuses == [200] * 3, statuses
    status, health = get(srv.url, "/health")
    assert status == 200 and health.get("checks"), health
    assert health["checks"][0]["status"] == "DEGRADED", health
    _, trace = get(srv.url, "/trace/last")
    assert trace["deviceCircuitOpen"] is True
    assert trace["fallbackCount"] >= 1


def scenario_queue_shed(srv: Server):
    post(srv.url)  # warm: XLA compile outside the contended burst
    results = Burst(srv.url, 6).join()
    codes = sorted(s for s, _ in results)
    assert codes.count(200) >= 2, codes
    assert codes.count(429) >= 1, codes
    for status, hdrs in results:
        if status == 429:
            assert int(hdrs["Retry-After"]) >= 1, hdrs
    _, trace = get(srv.url, "/trace/last")
    assert trace["admission"]["shedQueueFull"] >= 1, trace["admission"]


def scenario_drain(srv: Server):
    post(srv.url)  # warm
    slow = Burst(srv.url, 1)  # ingest_slow holds this one in flight
    time.sleep(0.4)
    srv.proc.send_signal(signal.SIGTERM)
    deadline = time.monotonic() + 10
    saw_unready = False
    while time.monotonic() < deadline and not saw_unready:
        try:
            status, _ = get(srv.url, "/health/ready")
            saw_unready = status == 503
        except OSError:  # listener already gone: drain finished
            break
        time.sleep(0.05)
    results = slow.join()
    assert results[0][0] == 200, f"in-flight request got {results[0][0]}"
    srv.proc.wait(30)
    assert srv.proc.returncode == 0, f"rc={srv.proc.returncode}"
    assert saw_unready, "never observed /health/ready 503 during drain"


# ----------------------------------------------------- batcher scenarios


def scenario_batch_coalesce(srv: Server):
    post(srv.url)  # warm: compile the R=1 batch program off the clock
    results = Burst(srv.url, 6).join(timeout=120)
    codes = sorted(s for s, _ in results)
    assert codes == [200] * 6, codes
    _, trace = get(srv.url, "/trace/last")
    b = trace["batcher"]
    assert b["requestsBatched"] >= 7, b  # warm + burst all went through it
    assert b["maxBatchSeen"] >= 2, f"burst never coalesced: {b}"
    assert b["flushFull"] + b["flushWait"] >= 1, b
    assert trace["fallbackCount"] == 0, trace["fallbackCount"]


def scenario_batch_demux_drop(srv: Server):
    # two warm posts burn the fault's after=2 budget outside the burst
    assert post(srv.url)[0] == 200
    assert post(srv.url)[0] == 200
    results = Burst(srv.url, 4).join(timeout=120)
    codes = sorted(s for s, _ in results)
    # the dropped demux slot fails exactly ONE request; batchmates are
    # untouched — the containment contract of runtime/batcher.py
    assert codes == [200, 200, 200, 500], codes
    _, trace = get(srv.url, "/trace/last")
    assert trace["batcher"]["demuxErrors"] == 1, trace["batcher"]
    assert trace["faults"]["fired"]["batcher_demux_raise"] == 1, trace["faults"]
    assert trace["fallbackCount"] == 0, trace["fallbackCount"]


def scenario_batch_device_fault(srv: Server):
    post(srv.url)  # warm: one device call burns after=1
    results = Burst(srv.url, 4).join(timeout=120)
    codes = sorted(s for s, _ in results)
    # a transient device failure of the shared step never 500s anybody:
    # bisection retries the halves on-device (a coalesced batch), or —
    # if the faulted flush held a single request — that one serves from
    # the golden host path
    assert codes == [200] * 4, codes
    _, trace = get(srv.url, "/trace/last")
    b = trace["batcher"]
    assert b["bisects"] + trace["fallbackCount"] >= 1, trace
    assert trace["fallbackCount"] <= 1, trace["fallbackCount"]
    assert b["demuxErrors"] == 0, b


BATCHER_FLAGS = ["--batching", "on", "--batch-wait-ms", "200", "--batch-max", "8"]

BATCHER_SCENARIOS = [
    ("batch-coalesce", BATCHER_FLAGS, {}, scenario_batch_coalesce),
    (
        "batch-demux-drop",
        BATCHER_FLAGS,
        {
            "LOG_PARSER_TPU_FAULTS": "batcher_demux_raise@times=1@after=2",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_batch_demux_drop,
    ),
    (
        "batch-device-fault",
        BATCHER_FLAGS,
        {
            "LOG_PARSER_TPU_FAULTS": "device_raise@times=1@after=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_batch_device_fault,
    ),
]


# ------------------------------------------------------ poison scenarios


POISON_LOGS = "INFO boot\nPOISON-PILL marker line\njava.lang.OutOfMemoryError: heap"


def post_logs(url: str, logs: str, timeout: float = 240.0):
    body = json.dumps(
        {"pod": {"metadata": {"name": "chaos"}}, "logs": logs}
    ).encode()
    req = urllib.request.Request(
        url + "/parse", data=body, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _poll_trace(url: str, pred, timeout: float = 30.0) -> dict:
    """Poll /trace/last until ``pred(trace)`` — shadow verification is
    asynchronous, its counters land after the response."""
    deadline = time.monotonic() + timeout
    trace: dict = {}
    while time.monotonic() < deadline:
        _, trace = get(url, "/trace/last")
        if pred(trace):
            return trace
        time.sleep(0.2)
    raise AssertionError(f"trace never satisfied predicate: {trace}")


def scenario_poison_batch_isolate(srv: Server):
    """The acceptance scenario: ONE poison request inside a 16-request
    batched stream causes zero failures for the other 15 (served
    on-device after bisection), the poison fingerprint quarantines, and a
    repeat never reaches the device step again."""
    post(srv.url)  # warm: compile the R=1 batch program off the clock
    results: list[int] = []
    lock = threading.Lock()

    def one(logs: str) -> None:
        status, _, _ = post_logs(srv.url, logs)
        with lock:
            results.append(status)

    threads = [
        threading.Thread(target=one, args=(LOGS,)) for _ in range(15)
    ] + [threading.Thread(target=one, args=(POISON_LOGS,))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(240)
    assert all(not t.is_alive() for t in threads), "burst stuck"
    assert results == [200] * 16, sorted(results)
    _, trace = get(srv.url, "/trace/last")
    b, q = trace["batcher"], trace["quarantine"]
    # exactly the poison row fell back to golden; the healthy 15 were
    # served on-device (a fallback for any of them would show here)
    assert trace["fallbackCount"] == 1, trace["fallbackCount"]
    assert b["bisects"] >= 1, b
    assert b["bisectIsolated"] == 1, b
    assert b["demuxErrors"] == 0, b
    assert q["quarantined"] == 1 and q["active"] == 1, q
    fired_before = trace["faults"]["fired"]["quarantine_raise"]
    # the repeat is routed straight to golden: the keyed fault sits at
    # the device-step boundary, so its fire counter CANNOT move
    status, _, _ = post_logs(srv.url, POISON_LOGS)
    assert status == 200, status
    _, trace = get(srv.url, "/trace/last")
    assert trace["faults"]["fired"]["quarantine_raise"] == fired_before, (
        trace["faults"]
    )
    assert trace["quarantine"]["servedGolden"] >= 1, trace["quarantine"]


def scenario_poison_ttl_readmit(srv: Server):
    """Quarantine TTL expiry: the fingerprint serves golden until the TTL
    lapses, then re-admits to the device step with a clean slate."""
    post(srv.url)  # warm
    # strike 1 (--quarantine-strikes 1): fault fires, golden serves, the
    # fingerprint quarantines
    status, _, _ = post_logs(srv.url, POISON_LOGS)
    assert status == 200, status
    _, trace = get(srv.url, "/trace/last")
    assert trace["quarantine"]["quarantined"] == 1, trace["quarantine"]
    assert trace["faults"]["fired"]["quarantine_raise"] == 1, trace["faults"]
    # inside the TTL: served golden, the device step is never evaluated
    calls_before = trace["faults"]["calls"]["quarantine_raise"]
    status, _, _ = post_logs(srv.url, POISON_LOGS)
    assert status == 200, status
    _, trace = get(srv.url, "/trace/last")
    assert trace["faults"]["calls"]["quarantine_raise"] == calls_before, (
        trace["faults"]
    )
    assert trace["quarantine"]["servedGolden"] >= 1, trace["quarantine"]
    # past the TTL: re-admitted to the device step (the keyed fault is
    # evaluated again — its budget is spent, so the request succeeds
    # on-device)
    time.sleep(2.4)
    status, _, _ = post_logs(srv.url, POISON_LOGS)
    assert status == 200, status
    _, trace = get(srv.url, "/trace/last")
    assert trace["quarantine"]["readmitted"] == 1, trace["quarantine"]
    assert trace["quarantine"]["active"] == 0, trace["quarantine"]
    assert trace["faults"]["calls"]["quarantine_raise"] > calls_before, (
        trace["faults"]
    )


def scenario_shadow_divergence_breaker(srv: Server):
    """An injected shadow divergence (rate 1.0) must flip /q/health to
    DEGRADED and open the pattern's breaker; the half-open probe after
    the 1s cool-down closes it and health recovers."""
    assert post(srv.url)[0] == 200  # warm comparison (fault after=1: clean)
    _poll_trace(srv.url, lambda t: t.get("shadow", {}).get("compared", 0) >= 1)
    assert post(srv.url)[0] == 200  # this one's comparison diverges
    trace = _poll_trace(
        srv.url, lambda t: t.get("shadow", {}).get("divergences", 0) >= 1
    )
    sh = trace["shadow"]
    assert sh["divergences"] == 1, sh
    assert sh["breakers"]["open"], sh["breakers"]
    assert sh["breakers"]["trips"] == 1, sh["breakers"]
    _, health = get(srv.url, "/q/health")
    assert {"name": "shadow", "status": "DEGRADED"} in health.get("checks", []), (
        health
    )
    # requests keep answering 200 while the divergent pattern serves from
    # the exact host regex
    assert post(srv.url)[0] == 200
    # cool-down expiry → half-open → the forced shadow sample on the next
    # request closes the breaker (fault budget spent: comparison is clean)
    time.sleep(1.4)
    assert post(srv.url)[0] == 200
    trace = _poll_trace(
        srv.url,
        lambda t: t.get("shadow", {}).get("breakers", {}).get("closes", 0) >= 1,
    )
    br = trace["shadow"]["breakers"]
    assert not br["open"] and not br["halfOpen"], br
    _, health = get(srv.url, "/q/health")
    assert {"name": "shadow", "status": "DEGRADED"} not in health.get(
        "checks", []
    ), health


POISON_SCENARIOS = [
    (
        "poison-batch-isolate",
        [
            "--batching", "on", "--batch-wait-ms", "500", "--batch-max", "16",
            "--quarantine-strikes", "1", "--quarantine-ttl-s", "600",
        ],
        {
            "LOG_PARSER_TPU_FAULTS": "quarantine_raise@match=POISON-PILL",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_poison_batch_isolate,
    ),
    (
        "poison-ttl-readmit",
        ["--quarantine-strikes", "1", "--quarantine-ttl-s", "2"],
        {
            "LOG_PARSER_TPU_FAULTS": "quarantine_raise@match=POISON-PILL@times=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_poison_ttl_readmit,
    ),
    (
        "shadow-divergence-breaker",
        ["--shadow-rate", "1.0"],
        {
            "LOG_PARSER_TPU_FAULTS": "shadow_raise@times=1@after=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
            "LOG_PARSER_TPU_PATTERN_BREAKER_COOLDOWN_S": "1",
        },
        scenario_shadow_divergence_breaker,
    ),
]


# --------------------------------------------------- linecache scenarios


def scenario_linecache_reload_swap(srv: Server):
    """A burst of cache-hit requests racing a hot pattern reload: zero
    failed requests, the swap flushes the routing tier exactly once
    (epochFlushes bumps), and the new epoch repopulates the cache — a
    stale hit across the pattern swap is impossible."""
    for _ in range(2):  # warm: miss+populate, then all-hit
        assert post(srv.url)[0] == 200
    _, trace = get(srv.url, "/trace/last")
    lc = trace["lineCache"]
    assert lc["entries"] > 0 and lc["hits"] > 0, lc
    burst = Burst(srv.url, 8)
    time.sleep(0.05)  # let the burst enqueue before the swap quiesces
    status, body = post_raw(srv.url, "/patterns/reload", b"")
    results = burst.join(timeout=120)
    codes = sorted(s for s, _ in results)
    assert codes == [200] * 8, codes
    assert status == 200 and body["epoch"] == 1, (status, body)
    # the swapped banks serve the next request and repopulate the cache
    status, body, _ = post(srv.url)
    assert status == 200, status
    assert body["summary"]["significantEvents"] >= 1, body["summary"]
    _, trace = get(srv.url, "/trace/last")
    lc = trace["lineCache"]
    assert lc["epochFlushes"] == 1, lc
    assert lc["entries"] > 0, lc
    assert trace["reload"]["epoch"] == 1, trace["reload"]
    assert trace["fallbackCount"] == 0, trace["fallbackCount"]


def scenario_linecache_eviction(srv: Server):
    """A cache budgeted far below the working set must keep serving
    exact results while evicting LRU lines, and its resident bytes must
    never exceed the configured ceiling."""
    for r in range(6):
        logs = "\n".join(
            f"INFO unique filler {r}.{i} status=ok" for i in range(40)
        ) + "\njava.lang.OutOfMemoryError: heap"
        status, body, _ = post_logs(srv.url, logs)
        assert status == 200, status
        assert body["summary"]["significantEvents"] >= 1, body["summary"]
    _, trace = get(srv.url, "/trace/last")
    lc = trace["lineCache"]
    assert lc["evictions"] > 0, lc
    assert lc["residentBytes"] <= EVICTION_BUDGET_MB * 1024 * 1024, lc
    assert lc["entries"] > 0, lc
    assert trace["fallbackCount"] == 0, trace["fallbackCount"]


def scenario_linecache_breaker_partial(srv: Server):
    """A shadow-divergence breaker trip while the stream is served from
    cache: the tripped pattern's columns re-evaluate from the exact host
    regex over CACHED rows (per-pattern invalidation by construction —
    the host override cube is spliced over cached and fresh bits alike),
    requests stay 200 with the correct event, and the other patterns
    keep hitting the cache."""
    assert post(srv.url)[0] == 200  # miss+populate; comparison clean (after=1)
    _poll_trace(srv.url, lambda t: t.get("shadow", {}).get("compared", 0) >= 1)
    assert post(srv.url)[0] == 200  # all-hit; this comparison diverges
    trace = _poll_trace(
        srv.url, lambda t: t.get("shadow", {}).get("divergences", 0) >= 1
    )
    assert trace["shadow"]["breakers"]["open"], trace["shadow"]["breakers"]
    hits_before = trace["lineCache"]["hits"]
    # breaker open: the request still serves from cache (hits grow) and
    # the divergent pattern's verdict comes from the exact host regex
    status, body, _ = post(srv.url)
    assert status == 200, status
    assert body["summary"]["significantEvents"] >= 1, body["summary"]
    _, trace = get(srv.url, "/trace/last")
    assert trace["lineCache"]["hits"] > hits_before, trace["lineCache"]
    assert trace["fallbackCount"] == 0, trace["fallbackCount"]


def scenario_linecache_shadow_parity(srv: Server):
    """Rate-1.0 online shadow verification over a cache-served stream —
    every response, including the all-hit requests that never touch the
    device, re-runs on the golden host path and compares events and
    scores. Zero divergences IS the in-service cache-on ≡ cache-off
    proof."""
    for _ in range(6):
        assert post(srv.url)[0] == 200
    trace = _poll_trace(
        srv.url, lambda t: t.get("shadow", {}).get("compared", 0) >= 6
    )
    assert trace["shadow"]["divergences"] == 0, trace["shadow"]
    lc = trace["lineCache"]
    # requests 2..6 are served wholly from cache (3 lines each)
    assert lc["hits"] >= 15, lc
    assert trace["fallbackCount"] == 0, trace["fallbackCount"]


EVICTION_BUDGET_MB = 0.002  # ≈ 16 entries at the builtin bank's row width

LINECACHE_SCENARIOS = [
    (
        "linecache-hit-under-reload-swap",
        [
            "--line-cache-mb", "64",
            "--batching", "on", "--batch-wait-ms", "20", "--batch-max", "8",
        ],
        {},
        scenario_linecache_reload_swap,
    ),
    (
        "linecache-eviction-under-load",
        ["--line-cache-mb", str(EVICTION_BUDGET_MB)],
        {},
        scenario_linecache_eviction,
    ),
    (
        "linecache-breaker-partial-invalidation",
        ["--line-cache-mb", "64", "--shadow-rate", "1.0"],
        {
            "LOG_PARSER_TPU_FAULTS": "shadow_raise@times=1@after=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
            "LOG_PARSER_TPU_PATTERN_BREAKER_COOLDOWN_S": "600",
        },
        scenario_linecache_breaker_partial,
    ),
    (
        "linecache-shadow-parity",
        ["--line-cache-mb", "64", "--shadow-rate", "1.0"],
        {},
        scenario_linecache_shadow_parity,
    ),
]


# ------------------------------------------------------ kernel scenarios


def scenario_kernel_tier_engaged(srv: Server):
    """--pallas-dfa on: the trace surfaces the tier verdict. On hosts
    where the union tier packs groups the kernel dispatches (or reports
    a concrete admission reason); everywhere the responses stay
    correct."""
    for _ in range(3):
        status, body, _ = post(srv.url)
        assert status == 200, status
        assert body["summary"]["significantEvents"] >= 1, body["summary"]
    _, trace = get(srv.url, "/trace/last")
    k = trace["kernel"]
    assert k["reason"] in (
        "ok", "no_union_groups", "table_too_large", "no_tile",
    ), k
    if k["enabled"] and k["reason"] == "ok":
        assert k["kernelBatches"] >= 1, k
    assert trace["fallbackCount"] == 0, trace["fallbackCount"]


def scenario_kernel_fault_xla_fallback(srv: Server):
    """An armed kernel fault must never surface to clients or trip the
    golden fallback: cube() catches it at trace time and the WHOLE batch
    rides the XLA scan tier — parity preserved, zero fallbackCount."""
    for _ in range(3):
        status, body, _ = post(srv.url)
        assert status == 200, status
        assert body["summary"]["significantEvents"] >= 1, body["summary"]
    _, trace = get(srv.url, "/trace/last")
    k = trace["kernel"]
    if k["enabled"]:
        # the fault fired during the first trace: the tier reports it
        # and every dispatch lands on the XLA side of the counters
        assert k["reason"] == "fault", k
        assert k["kernelBatches"] == 0, k
        assert k["xlaBatches"] >= 1, k
        fired = trace.get("faults", {}).get("fired", {})
        assert fired.get("kernel_raise", 0) >= 1, fired
    else:  # no union groups on this host: the fire site is never reached
        assert k["reason"] == "no_union_groups", k
    assert trace["fallbackCount"] == 0, trace["fallbackCount"]


KERNEL_SCENARIOS = [
    (
        "kernel-tier-engaged",
        ["--pallas-dfa", "on"],
        {},
        scenario_kernel_tier_engaged,
    ),
    (
        "kernel-fault-xla-fallback",
        ["--pallas-dfa", "on"],
        {
            "LOG_PARSER_TPU_FAULTS": "kernel_raise:1.0@times=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_kernel_fault_xla_fallback,
    ),
]


# --------------------------------------------------- streaming scenarios


class StreamClient:
    """Raw-socket chunked-TE client for ``POST /parse/stream``. The
    stdlib ``urllib`` can neither send chunked request bodies nor read a
    response while the request is still being written, so follow-mode
    needs a hand-rolled socket: send the headers, read the immediate
    NDJSON response headers, then interleave chunk writes with frame
    reads on one connection."""

    def __init__(self, url: str, tenant: str | None = None):
        host, _, port = url.removeprefix("http://").partition(":")
        self.sock = socket.create_connection((host, int(port)), timeout=120)
        tenant_hdr = (
            f"X-Tenant: {tenant}\r\n".encode() if tenant else b""
        )
        self.sock.sendall(
            b"POST /parse/stream HTTP/1.1\r\nHost: chaos\r\n"
            + tenant_hdr
            + b"Transfer-Encoding: chunked\r\n\r\n"
        )
        buf = b""
        while b"\r\n\r\n" not in buf:
            part = self.sock.recv(65536)
            if not part:
                raise AssertionError("stream closed before response headers")
            buf += part
        head, self._buf = buf.split(b"\r\n\r\n", 1)
        self.status = int(head.split(b" ", 2)[1])
        assert self.status == 200, f"stream open -> {self.status}"

    def send(self, data: bytes) -> None:
        self.sock.sendall(b"%x\r\n" % len(data) + data + b"\r\n")

    def read_frames(self) -> list[dict]:
        """Drain NDJSON frames to server EOF (the server closes the
        connection after the terminal frame) and return them parsed."""
        buf = self._buf
        while True:
            try:
                part = self.sock.recv(65536)
            except OSError:
                break
            if not part:
                break
            buf += part
        self.sock.close()
        return [json.loads(ln) for ln in buf.splitlines() if ln.strip()]

    def finish(self) -> list[dict]:
        self.sock.sendall(b"0\r\n\r\n")  # terminating chunk closes the session
        return self.read_frames()

    def abort(self) -> None:
        self.sock.close()


def _one_final(frames: list[dict]) -> dict:
    bad = [f for f in frames if f["type"] == "error"]
    assert not bad, bad
    finals = [f for f in frames if f["type"] == "final"]
    assert len(finals) == 1 and frames[-1] is finals[0], [
        f["type"] for f in frames
    ]
    return finals[0]


def scenario_stream_device_fault_golden(srv: Server):
    """A device fault on a mid-session chunk must flip THAT session to a
    golden continuation — later chunks keep scoring, the close still
    produces a ``final`` frame, and the client never sees the fault."""
    assert post(srv.url)[0] == 200  # burns the after=1 skip deterministically
    c = StreamClient(srv.url)
    c.send(b"INFO stream boot\n")  # device eval #2: the armed fault fires here
    c.send(b"java.lang.OutOfMemoryError: heap\n")
    final = _one_final(c.finish())
    assert final["result"]["summary"]["significantEvents"] >= 1, final
    _, trace = get(srv.url, "/trace/last")
    st = trace["stream"]
    assert st["goldenContinuations"] == 1, st
    assert st["sessionsClosed"] == 1 and st["openSessions"] == 0, st
    assert trace["faults"]["fired"]["device_raise"] == 1, trace["faults"]
    assert post(srv.url)[0] == 200  # and the device path itself is fine


def scenario_stream_poison_kill(srv: Server):
    """A keyed poison chunk kills exactly its own session: a structured
    ``error`` frame with reason ``poison``, while the server — and a
    parallel fresh session — keep serving."""
    assert post(srv.url)[0] == 200  # no marker in PAYLOAD: must not fire
    c = StreamClient(srv.url)
    c.send(b"INFO clean chunk\n")
    c.send(b"POISON-PILL marker line\n")  # match= key: fires on this chunk only
    frames = c.read_frames()  # the server ends the response after the kill
    assert frames and frames[-1]["type"] == "error", frames
    assert frames[-1]["reason"] == "poison", frames[-1]
    c2 = StreamClient(srv.url)  # blast radius: one session, not the server
    c2.send(b"java.lang.OutOfMemoryError: heap\n")
    final = _one_final(c2.finish())
    assert final["result"]["summary"]["significantEvents"] >= 1, final
    assert post(srv.url)[0] == 200
    _, trace = get(srv.url, "/trace/last")
    st = trace["stream"]
    assert st["poisonKills"] == 1 and st["sessionsKilled"] >= 1, st
    assert st["openSessions"] == 0, st


def scenario_stream_reload_rebase(srv: Server):
    """A hot pattern reload landing between chunks of an open session:
    the next chunk re-bases the session onto the swapped banks (the
    reload never waits on idle sessions — quiesce counts active calls,
    not open sessions) and the session still closes with a final."""
    assert post(srv.url)[0] == 200
    c = StreamClient(srv.url)
    c.send(b"INFO stream warm\n")
    status, body = post_raw(srv.url, "/patterns/reload", b"")
    assert status == 200 and body["epoch"] == 1, (status, body)
    c.send(b"java.lang.OutOfMemoryError: heap\n")  # first post-swap chunk
    final = _one_final(c.finish())
    assert final["result"]["summary"]["significantEvents"] >= 1, final
    _, trace = get(srv.url, "/trace/last")
    st = trace["stream"]
    assert st["sessionsRebased"] >= 1, st
    assert st["sessionsClosed"] == 1 and st["openSessions"] == 0, st
    assert trace["reload"]["epoch"] == 1, trace["reload"]


def scenario_stream_ttl_reap(srv: Server):
    """Sessions abandoned mid-stream under --stream-ttl-s 1 are reaped
    while concurrent blob traffic runs: their admission slots release
    (gate inflight back to 0) and the server stays healthy."""
    c1, c2 = StreamClient(srv.url), StreamClient(srv.url)
    c1.send(b"INFO abandoned tail")
    c2.send(b"INFO abandoned tail two")
    burst = Burst(srv.url, 4)  # reap must land under live parse load
    codes = sorted(s for s, _ in burst.join(timeout=120))
    assert codes == [200] * 4, codes
    trace = _poll_trace(
        srv.url, lambda t: t.get("stream", {}).get("sessionsReaped", 0) >= 2
    )
    st = trace["stream"]
    assert st["openSessions"] == 0, st
    assert trace["admission"]["inflight"] == 0, trace["admission"]
    c1.abort()
    c2.abort()
    assert post(srv.url)[0] == 200


STREAMING_SCENARIOS = [
    (
        "stream-device-fault-golden",
        [],
        {
            "LOG_PARSER_TPU_FAULTS": "device_raise:1.0@after=1@times=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_stream_device_fault_golden,
    ),
    (
        "stream-poison-kill",
        [],
        {
            "LOG_PARSER_TPU_FAULTS": "quarantine_raise:1.0@match=POISON-PILL",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_stream_poison_kill,
    ),
    (
        "stream-reload-rebase",
        [],
        {},
        scenario_stream_reload_rebase,
    ),
    (
        "stream-ttl-reap",
        ["--stream-ttl-s", "1"],
        {},
        scenario_stream_ttl_reap,
    ),
]


# ------------------------------------------------------- state scenarios


def post_raw(url: str, path: str, data: bytes, timeout: float = 60.0,
             headers: dict | None = None):
    req = urllib.request.Request(
        url + path,
        data=data,
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _final_scores(body: dict) -> list:
    return [
        (ev.get("lineNumber"), ev.get("matchedPattern", {}).get("id"),
         ev.get("score"))
        for ev in body.get("events", [])
    ]


def scenario_state_kill9_replay():
    """Crash-recovery parity, operator-grade: a server hard-killed after
    3 requests and restarted on the same --state-dir must end (after 2
    more) with the same frequency stats and the same last-response scores
    as one uninterrupted server that took all 5."""
    with tempfile.TemporaryDirectory(prefix="chaos_state_") as tmp:
        crash_dir = os.path.join(tmp, "crash")
        control_dir = os.path.join(tmp, "control")

        srv = Server("state-kill9-a", ["--state-dir", crash_dir], {})
        srv.wait_ready()
        for _ in range(3):
            assert post(srv.url)[0] == 200
        srv.proc.kill()  # SIGKILL: no drain, no final snapshot
        srv.proc.wait(30)
        log_a = srv.log.name

        srv2 = Server("state-kill9-b", ["--state-dir", crash_dir], {})
        try:
            srv2.wait_ready()
            _, trace = get(srv2.url, "/trace/last")
            j = trace["journal"]
            # the kill-9 tail was replayed (or already folded into the
            # boot snapshot of run A — either way nothing was lost)
            assert j["stateDir"] == crash_dir, j
            for _ in range(1):
                assert post(srv2.url)[0] == 200
            status, last_body, _ = post(srv2.url)
            assert status == 200
            _, crashed_stats = get(srv2.url, "/frequency/stats")
        finally:
            srv2.stop()

        control = Server("state-kill9-control", ["--state-dir", control_dir], {})
        try:
            control.wait_ready()
            for _ in range(4):
                assert post(control.url)[0] == 200
            status, control_body, _ = post(control.url)
            assert status == 200
            _, control_stats = get(control.url, "/frequency/stats")
        finally:
            control.stop()

        assert crashed_stats == control_stats, (crashed_stats, control_stats)
        assert _final_scores(last_body) == _final_scores(control_body), (
            last_body, control_body
        )
        for path in (log_a, srv2.log.name, control.log.name):
            try:
                os.unlink(path)
            except OSError:
                pass


def scenario_state_torn_tail():
    """A crash mid-append leaves half a frame as the WAL's final bytes.
    The fault writes exactly that (then wedges the journal so it stays
    final); the restart must quarantine the torn bytes, replay every
    whole record, and serve."""
    with tempfile.TemporaryDirectory(prefix="chaos_state_") as tmp:
        state_dir = os.path.join(tmp, "state")
        srv = Server(
            "state-torn-a",
            ["--state-dir", state_dir, "--snapshot-every", "100000"],
            {
                # 3rd append (request 3's match record) is written torn
                "LOG_PARSER_TPU_FAULTS": "journal_torn_raise@after=2",
                "LOG_PARSER_TPU_FAULT_SEED": "42",
            },
        )
        srv.wait_ready()
        for _ in range(4):
            assert post(srv.url)[0] == 200
        srv.proc.kill()
        srv.proc.wait(30)
        log_a = srv.log.name

        srv2 = Server("state-torn-b", ["--state-dir", state_dir], {})
        try:
            srv2.wait_ready()
            assert os.path.exists(os.path.join(state_dir, "journal.wal.torn"))
            _, trace = get(srv2.url, "/trace/last")
            assert trace["journal"]["tornTails"] == 1, trace["journal"]
            assert trace["journal"]["healthy"] is True, trace["journal"]
            assert post(srv2.url)[0] == 200
        finally:
            srv2.stop()
        for path in (log_a, srv2.log.name):
            try:
                os.unlink(path)
            except OSError:
                pass


def scenario_state_canary_rollback(srv: Server):
    """An injected canary divergence must turn the reload into a 409 and
    leave the served results unchanged; the retry (fault budget spent)
    must succeed and bump the epoch."""
    status, before, _ = post(srv.url)
    assert status == 200
    status, body = post_raw(srv.url, "/patterns/reload", b"")
    assert status == 409, (status, body)
    assert body["stage"] == "canary", body
    _, trace = get(srv.url, "/trace/last")
    assert trace["reload"]["epoch"] == 0, trace["reload"]
    assert trace["reload"]["failures"] == 1, trace["reload"]
    # old banks still serving, scores unchanged
    status, after, _ = post(srv.url)
    assert status == 200
    assert _final_scores(after) == _final_scores(before), (after, before)
    status, body = post_raw(srv.url, "/patterns/reload", b"")
    assert status == 200, (status, body)
    assert body["epoch"] == 1, body
    assert post(srv.url)[0] == 200


def scenario_state_reload_under_load(srv: Server):
    """Hot reload racing a concurrent batched burst: every request 200,
    the reload completes, nothing wedges."""
    post(srv.url)  # warm the batch program
    burst = Burst(srv.url, 8)
    time.sleep(0.05)  # let the burst enqueue before the swap quiesces
    status, body = post_raw(srv.url, "/patterns/reload", b"")
    results = burst.join(timeout=120)
    codes = sorted(s for s, _ in results)
    assert codes == [200] * 8, codes
    assert status == 200, (status, body)
    assert body["epoch"] == 1, body
    # the swapped banks serve the next request
    assert post(srv.url)[0] == 200
    _, trace = get(srv.url, "/trace/last")
    assert trace["reload"]["epoch"] == 1, trace["reload"]
    assert trace["reload"]["failures"] == 0, trace["reload"]


# state scenarios that manage their own server lifecycle (kill/restart)
STATE_STANDALONE = [
    ("state-kill9-replay", scenario_state_kill9_replay),
    ("state-torn-tail", scenario_state_torn_tail),
]

STATE_SCENARIOS = [
    (
        "state-canary-rollback",
        [],
        {
            "LOG_PARSER_TPU_FAULTS": "reload_canary_raise@times=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_state_canary_rollback,
    ),
    (
        "state-reload-under-load",
        ["--batching", "on", "--batch-wait-ms", "20", "--batch-max", "8"],
        {},
        scenario_state_reload_under_load,
    ),
]


# ------------------------------------------------- distributed scenarios


_NO_CPU_MULTIPROCESS = "Multiprocess computations aren't implemented"


class DistributedPair:
    """A coordinator serve child + one follower child sharing a
    jax.distributed runtime (4 virtual CPU devices each → one 8-device
    global mesh). The coordinator owns HTTP; the follower replays
    broadcasts in follower_loop."""

    def __init__(self, name: str, coord_args: list[str], coord_env: dict):
        dist_port = free_port()
        shared = [
            "--coordinator", f"127.0.0.1:{dist_port}", "--num-processes", "2",
        ]
        base_env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
        self.follower_log = tempfile.NamedTemporaryFile(
            "wb", prefix=f"chaos_{name}_follower_", suffix=".log", delete=False
        )
        self.follower = subprocess.Popen(
            [
                sys.executable, "-m", "log_parser_tpu.serve",
                "--pattern-dir", PATTERN_DIR,
                *shared, "--process-id", "1",
            ],
            cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONUNBUFFERED": "1", **base_env},
            stdout=self.follower_log,
            stderr=subprocess.STDOUT,
        )
        self.coord = Server(
            name,
            [*shared, "--process-id", "0", *coord_args],
            {**base_env, **coord_env},
        )
        self.url = self.coord.url
        self.log = self.coord.log

    def logs_tail(self) -> str:
        out = []
        for path in (self.coord.log.name, self.follower_log.name):
            try:
                with open(path, "rb") as f:
                    out.append(f.read()[-4000:].decode(errors="replace"))
            except OSError:
                pass
        return "\n".join(out)

    def stop(self) -> None:
        self.coord.stop()
        if self.follower.poll() is None:
            try:
                self.follower.wait(30)
            except subprocess.TimeoutExpired:
                self.follower.kill()
                self.follower.wait(10)


def scenario_follower_degrade(pair: DistributedPair):
    # r1 rides the full mesh before the fault arms (after=1)
    status, body, _ = post(pair.url, timeout=60)
    assert status == 200, f"expected 200, got {status}"
    assert "degraded" not in body.get("metadata", {}), body["metadata"]

    # r2: the follower hang burns the whole broadcast budget (2s x 2) —
    # the request must still answer 200, served degraded from local chips
    status, body, _ = post(pair.url, timeout=120)
    assert status == 200, f"degraded request got {status}"
    assert body["metadata"].get("degraded") == "distributed-fallback", (
        body.get("metadata")
    )
    _, health = get(pair.url, "/health")
    assert {"name": "mesh", "status": "DEGRADED"} in health.get("checks", []), health

    # the heartbeat probe must re-admit the mesh once the hang expires
    # (times=2 budget was spent inside r2)
    deadline = time.monotonic() + 30
    mode = None
    while time.monotonic() < deadline:
        _, trace = get(pair.url, "/trace/last")
        mode = trace.get("distributed", {}).get("mode")
        if mode == "distributed":
            break
        time.sleep(0.3)
    assert mode == "distributed", f"mesh never re-admitted (mode={mode})"
    assert trace["distributed"]["broadcastTimeouts"] >= 2, trace["distributed"]
    assert trace["distributed"]["degradedRequests"] >= 1, trace["distributed"]
    assert trace["distributed"]["readmissions"] >= 1, trace["distributed"]

    # r3 is distributed again, and SIGTERM shuts BOTH processes down
    status, body, _ = post(pair.url, timeout=60)
    assert status == 200 and "degraded" not in body.get("metadata", {})
    pair.coord.proc.send_signal(signal.SIGTERM)
    pair.coord.proc.wait(60)
    assert pair.coord.proc.returncode == 0, f"rc={pair.coord.proc.returncode}"
    pair.follower.wait(60)
    assert pair.follower.returncode == 0, f"follower rc={pair.follower.returncode}"


DISTRIBUTED_SCENARIOS = [
    (
        "follower-degrade",
        [
            "--broadcast-timeout", "2", "--broadcast-retries", "1",
            "--dead-after", "2", "--heartbeat-s", "0.5",
        ],
        {
            "LOG_PARSER_TPU_FAULTS": "follower_hang:30@after=1@times=2",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_follower_degrade,
    ),
]


def _make_tenant_root(tmp: str, tenants=("acme", "globex")) -> str:
    """A tenant library root: one sub-directory per tenant, each a copy
    of the builtin pattern library (content identical on purpose — these
    scenarios pin isolation mechanics, not per-tenant pattern authoring)."""
    root = os.path.join(tmp, "tenants")
    for tid in tenants:
        shutil.copytree(PATTERN_DIR, os.path.join(root, tid))
    return root


def scenario_tenant_quota_shed():
    """One tenant's lines/s bucket empties under a concurrent burst: the
    over-quota requests get structured 429s with Retry-After while the
    burst's head (and the default tenant) are served normally."""
    with tempfile.TemporaryDirectory(prefix="chaos_tenant_") as tmp:
        root = _make_tenant_root(tmp)
        # PAYLOAD is 3 lines; lines/s 2 with the 2s burst window is a
        # 4-token bucket — exactly one concurrent request fits
        srv = Server(
            "tenant-quota-shed",
            ["--tenant-root", root, "--tenant-lines-per-s", "2"],
            {},
        )
        try:
            srv.wait_ready()
            hdr = {"X-Tenant": "acme"}
            # the burst also races first-touch resolution: one thread
            # builds acme's bank, the rest coalesce on the build event
            results = Burst(srv.url, 8, headers=hdr).join(timeout=180)
            codes = [s for s, _ in results]
            assert set(codes) <= {200, 429}, codes
            assert codes.count(200) >= 1, codes
            assert codes.count(429) >= 5, codes
            for status, hdrs in results:
                if status == 429:
                    assert int(hdrs["Retry-After"]) >= 1, hdrs
            # bucket still empty: a follow-up shows the structured body
            status, body, _ = post(srv.url, hdr)
            assert status == 429 and body["reason"] == "tenant rate", (
                status, body,
            )
            # the default tenant's own bucket is untouched by acme's shed
            assert post(srv.url)[0] == 200
            _, trace = get(srv.url, "/trace/last")
            assert trace["admission"]["shedTenant"] >= 5, trace["admission"]
            quota = trace["tenants"]["perTenant"]["acme"]["quota"]
            assert quota["shedRate"] >= 5, quota
        finally:
            srv.stop()


def scenario_tenant_evict_rebuild():
    """A bank budget sized for ~1.5 tenants: the second tenant's arrival
    LRU-evicts the first, the first's return rebuilds it — all while a
    concurrent default-tenant burst keeps answering 200 (builds happen
    outside the registry lock, so nobody stalls behind a compile)."""
    with tempfile.TemporaryDirectory(prefix="chaos_tenant_") as tmp:
        root = _make_tenant_root(tmp)
        # measure one bank's resident bytes off a probe server — the
        # budget flag must land between 1x and 2x of a bank to force
        # eviction on the second tenant without thrashing the first
        probe = Server("tenant-evict-probe", ["--tenant-root", root], {})
        try:
            probe.wait_ready()
            assert post(probe.url, {"X-Tenant": "acme"})[0] == 200
            _, trace = get(probe.url, "/trace/last")
            bank_mb = (
                trace["tenants"]["perTenant"]["acme"]["bankBytes"] / 2**20
            )
        finally:
            probe.stop()
        srv = Server(
            "tenant-evict-rebuild",
            ["--tenant-root", root,
             "--tenant-budget-mb", f"{bank_mb * 1.5:.4f}"],
            {},
        )
        try:
            srv.wait_ready()
            assert post(srv.url, {"X-Tenant": "acme"})[0] == 200
            burst = Burst(srv.url, 4)  # default-tenant load rides along
            assert post(srv.url, {"X-Tenant": "globex"})[0] == 200  # evicts
            assert post(srv.url, {"X-Tenant": "acme"})[0] == 200  # rebuilds
            codes = sorted(s for s, _ in burst.join(timeout=180))
            assert codes == [200] * 4, codes
            _, trace = get(srv.url, "/trace/last")
            t = trace["tenants"]
            assert t["evicted"] >= 1, t
            assert t["rebuilds"] >= 1, t
            assert t["residentBankMb"] <= t["budgetMb"] + bank_mb + 1, t
        finally:
            srv.stop()


def scenario_tenant_reload_isolated():
    """A hot reload scoped to tenant A races a burst of tenant-B traffic:
    the quiesce runs on A's engine alone, so every B request answers 200;
    A's reloadEpoch bumps while B's and the default tenant's stay 0."""
    with tempfile.TemporaryDirectory(prefix="chaos_tenant_") as tmp:
        root = _make_tenant_root(tmp)
        srv = Server("tenant-reload-isolated", ["--tenant-root", root], {})
        try:
            srv.wait_ready()
            assert post(srv.url, {"X-Tenant": "acme"})[0] == 200
            assert post(srv.url, {"X-Tenant": "globex"})[0] == 200
            burst = Burst(srv.url, 6, headers={"X-Tenant": "globex"})
            status, body = post_raw(
                srv.url, "/patterns/reload", b"",
                headers={"X-Tenant": "acme"},
            )
            codes = sorted(s for s, _ in burst.join(timeout=180))
            assert codes == [200] * 6, codes
            assert status == 200 and body["epoch"] == 1, (status, body)
            _, trace = get(srv.url, "/trace/last")
            per = trace["tenants"]["perTenant"]
            assert per["acme"]["reloadEpoch"] == 1, per["acme"]
            assert per["globex"]["reloadEpoch"] == 0, per["globex"]
            assert per["default"]["reloadEpoch"] == 0, per["default"]
        finally:
            srv.stop()


# tenant scenarios manage their own server lifecycle (the library root
# must exist before the Server's flag list can reference it)
TENANT_STANDALONE = [
    ("tenant-quota-shed", scenario_tenant_quota_shed),
    ("tenant-evict-rebuild", scenario_tenant_evict_rebuild),
    ("tenant-reload-isolated", scenario_tenant_reload_isolated),
]


# ------------------------------------------------- migrate group scenarios


def _migrate_pair(tmp: str, src_name: str, dst_name: str,
                  src_env: dict | None = None,
                  src_flags: list | None = None):
    """Two serve processes sharing one tenant library root (the bank
    content-hash check requires identical pattern config on both sides),
    each with its own --state-dir for WALs and migration journals."""
    root = _make_tenant_root(tmp)
    src = Server(
        src_name,
        ["--tenant-root", root,
         "--state-dir", os.path.join(tmp, "src_state"),
         *(src_flags or [])],
        src_env or {},
    )
    dst = Server(
        dst_name,
        ["--tenant-root", root,
         "--state-dir", os.path.join(tmp, "dst_state")],
        {},
    )
    return src, dst


def scenario_migrate_live_cutover():
    """The happy path end to end: acme migrates from source to target
    over HTTP; afterwards the source answers acme with a 307 (Location +
    Retry-After) while the target serves it with the migrated frequency
    history applied."""
    with tempfile.TemporaryDirectory(prefix="chaos_migrate_") as tmp:
        src, dst = _migrate_pair(tmp, "migrate-src", "migrate-dst")
        try:
            src.wait_ready()
            dst.wait_ready()
            hdr = {"X-Tenant": "acme"}
            for _ in range(2):  # build frequency history worth moving
                assert post(src.url, hdr)[0] == 200
            status, body = post_raw(
                src.url, "/admin/migrate",
                json.dumps({"tenant": "acme", "target": dst.url}).encode(),
            )
            assert status == 200 and body["outcome"] == "completed", (
                status, body,
            )
            # the source now 307-forwards acme with the redirect envelope
            code, fbody, fhdrs = post(src.url, hdr)
            assert code == 307, (code, fbody)
            assert fhdrs["Location"].startswith(dst.url), fhdrs
            assert int(fhdrs["Retry-After"]) >= 1, fhdrs
            assert dst.url in fbody["location"], fbody
            # ...while the target owns it (and the default tenant on the
            # source is untouched)
            assert post(dst.url, hdr)[0] == 200
            assert post(src.url)[0] == 200
            _, strace = get(src.url, "/trace/last")
            m = strace["migration"]
            assert m["completed"] == 1 and m["forwards"] == 1, m
            assert m["aborted"] == 0, m
            _, dtrace = get(dst.url, "/trace/last")
            dm = dtrace["migration"]
            assert dm["staged"] == 1 and dm["activated"] == 1, dm
        finally:
            src.stop()
            dst.stop()


def scenario_migrate_crash_mid_export():
    """The ``migrate_export`` fault fires under the quiesce gate: the
    migration aborts with a structured 409, the source keeps the tenant
    (no forward, still 200), and the abort is durable — a journaled
    ABORT record, not a wedge."""
    with tempfile.TemporaryDirectory(prefix="chaos_migrate_") as tmp:
        root = _make_tenant_root(tmp)
        srv = Server(
            "migrate-crash-export",
            ["--tenant-root", root,
             "--state-dir", os.path.join(tmp, "state")],
            {"LOG_PARSER_TPU_FAULTS": "migrate_export_raise@times=1"},
        )
        try:
            srv.wait_ready()
            hdr = {"X-Tenant": "acme"}
            assert post(srv.url, hdr)[0] == 200
            status, body = post_raw(
                srv.url, "/admin/migrate",
                json.dumps({"tenant": "acme",
                            "target": "http://127.0.0.1:9"}).encode(),
            )
            assert status == 409, (status, body)
            # the source still owns acme: served locally, no forward
            assert post(srv.url, hdr)[0] == 200
            _, trace = get(srv.url, "/trace/last")
            m = trace["migration"]
            assert m["aborted"] == 1 and m["forwards"] == 0, m
            assert m["completed"] == 0, m
            assert trace["faults"]["fired"]["migrate_export_raise"] == 1, (
                trace["faults"]
            )
        finally:
            srv.stop()


def scenario_migrate_crash_pre_cutover():
    """The ``migrate_cutover`` fault fires AFTER the target staged the
    bundle but before the commit record: the source aborts and keeps
    serving; the target's staged-but-never-activated copy must never
    apply (single-owner invariant)."""
    with tempfile.TemporaryDirectory(prefix="chaos_migrate_") as tmp:
        src, dst = _migrate_pair(
            tmp, "migrate-precut-src", "migrate-precut-dst",
            src_env={
                "LOG_PARSER_TPU_FAULTS": "migrate_cutover_raise@times=1"
            },
        )
        try:
            src.wait_ready()
            dst.wait_ready()
            hdr = {"X-Tenant": "acme"}
            assert post(src.url, hdr)[0] == 200
            status, body = post_raw(
                src.url, "/admin/migrate",
                json.dumps({"tenant": "acme", "target": dst.url}).encode(),
            )
            assert status == 409, (status, body)
            # source still owns: 200, no forward installed
            assert post(src.url, hdr)[0] == 200
            _, strace = get(src.url, "/trace/last")
            m = strace["migration"]
            assert m["aborted"] == 1 and m["forwards"] == 0, m
            # the target staged the bundle but never activated it
            _, dtrace = get(dst.url, "/trace/last")
            dm = dtrace["migration"]
            assert dm["staged"] == 1 and dm["activated"] == 0, dm
            assert dm["stagedNow"] == 1, dm
        finally:
            src.stop()
            dst.stop()


def scenario_migrate_drain_under_burst():
    """POST /admin/drain while a default-tenant burst is in flight: the
    drain closes every resident tenant under the deadline, /q/health
    flips to a DRAINING 503 for the LBs, the burst sees only 200s (head)
    or structured 503s (tail), and SIGTERM afterwards exits clean."""
    with tempfile.TemporaryDirectory(prefix="chaos_migrate_") as tmp:
        root = _make_tenant_root(tmp)
        srv = Server(
            "migrate-drain-burst",
            ["--tenant-root", root,
             "--state-dir", os.path.join(tmp, "state"),
             "--drain-deadline-s", "15"],
            {},
        )
        try:
            srv.wait_ready()
            assert post(srv.url, {"X-Tenant": "acme"})[0] == 200
            assert post(srv.url, {"X-Tenant": "globex"})[0] == 200
            burst = Burst(srv.url, 6)
            status, body = post_raw(srv.url, "/admin/drain", b"{}")
            assert status == 200, (status, body)
            assert sorted(body["closed"]) == ["acme", "globex"], body
            assert body["elapsedS"] <= 15, body
            codes = [s for s, _ in burst.join(timeout=120)]
            assert set(codes) <= {200, 503}, codes
            hstatus, health = get(srv.url, "/q/health")
            assert hstatus == 503 and health["status"] == "DRAINING", (
                hstatus, health,
            )
            assert any(
                c["name"] == "drain" and c["status"] == "DRAINING"
                for c in health["checks"]
            ), health
            _, trace = get(srv.url, "/trace/last")
            d = trace["migration"]["drain"]
            assert d["draining"] == 1 and d["tenantsClosed"] == 2, d
        finally:
            srv.stop(expect_zero=True)


def scenario_migrate_stream_handoff():
    """A live follow-mode session is open on the migrating tenant: the
    cutover must not hang on it — across processes the session closes
    with an explicit ``error`` frame naming the new owner, and the
    tenant's blob traffic 307-forwards."""
    with tempfile.TemporaryDirectory(prefix="chaos_migrate_") as tmp:
        src, dst = _migrate_pair(tmp, "migrate-stream-src",
                                 "migrate-stream-dst")
        try:
            src.wait_ready()
            dst.wait_ready()
            hdr = {"X-Tenant": "acme"}
            assert post(src.url, hdr)[0] == 200
            c = StreamClient(src.url, tenant="acme")
            c.send(b"INFO pinned session\n")
            status, body = post_raw(
                src.url, "/admin/migrate",
                json.dumps({"tenant": "acme", "target": dst.url}).encode(),
            )
            assert status == 200 and body["outcome"] == "completed", (
                status, body,
            )
            assert body["sessionsClosed"] == 1, body
            # the handler thread is blocked reading chunks; the next
            # chunk lands on the killed session and flushes its terminal
            # error frame back down this connection
            c.send(b"INFO post-cutover chunk\n")
            frames = c.read_frames()
            assert frames and frames[-1]["type"] == "error", frames
            assert frames[-1]["reason"] == "migrated", frames[-1]
            assert dst.url in frames[-1]["message"], frames[-1]
            assert post(src.url, hdr)[0] == 307
            assert post(dst.url, hdr)[0] == 200
        finally:
            src.stop()
            dst.stop()


MIGRATE_STANDALONE = [
    ("migrate-live-cutover", scenario_migrate_live_cutover),
    ("migrate-crash-mid-export", scenario_migrate_crash_mid_export),
    ("migrate-crash-pre-cutover", scenario_migrate_crash_pre_cutover),
    ("migrate-drain-under-burst", scenario_migrate_drain_under_burst),
    ("migrate-stream-handoff", scenario_migrate_stream_handoff),
]


# Replica group (``--group replica``; warm-standby replication + fenced
# failover — docs/OPS.md "Warm-standby replication & failover"): real
# primary/standby pairs over HTTP; where a drill needs a dead primary it
# dies by SIGKILL, so promotion must work from the epoch journal and the
# standby's own re-journaled WAL alone.


def _replica_pair(tmp: str, prefix: str, failover_s: float | None = None):
    """A primary continuously shipping to a warm standby. The primary
    boots first and must be ready before the standby exists: an armed
    standby starts probing immediately, and primary boot latency must
    never be counted as primary death."""
    root = _make_tenant_root(tmp)
    a_port, b_port = free_port(), free_port()
    primary = Server(
        f"{prefix}-primary",
        ["--tenant-root", root,
         "--state-dir", os.path.join(tmp, "a_state"),
         "--replica-target", f"http://127.0.0.1:{b_port}"],
        {}, port=a_port,
    )
    primary.wait_ready()
    flags = ["--tenant-root", root,
             "--state-dir", os.path.join(tmp, "b_state"),
             "--replica-of", f"http://127.0.0.1:{a_port}"]
    if failover_s is not None:
        flags += ["--failover-after-s", str(failover_s)]
    standby = Server(f"{prefix}-standby", flags, {}, port=b_port)
    standby.wait_ready()
    return primary, standby


def _applied_records(url: str) -> int:
    _, trace = get(url, "/trace/last")
    rep = trace.get("replication") or {}
    return int(rep.get("appliedRecords", 0))


def scenario_replica_failover_kill9():
    """The acceptance drill end to end: a pair ships live WAL (the lag
    families are on /metrics), the primary dies by SIGKILL, the armed
    supervisor promotes the standby, and the standby serves the
    tenant's replicated history un-fenced."""
    with tempfile.TemporaryDirectory(prefix="chaos_replica_") as tmp:
        primary, standby = _replica_pair(tmp, "replica-kill9",
                                         failover_s=3.0)
        try:
            hdr = {"X-Tenant": "acme"}
            assert post(primary.url, hdr)[0] == 200
            assert post(primary.url)[0] == 200  # default tenant too
            # the standby is fenced while its primary lives
            code, _, fhdrs = post(standby.url, hdr)
            assert code == 307, code
            assert fhdrs["Location"].startswith(primary.url), fhdrs
            # shipping is continuous: both tenants' frames land and are
            # re-journaled on the standby
            _poll_trace(
                standby.url,
                lambda t: (t.get("replication") or {}).get(
                    "appliedRecords", 0) >= 2,
                timeout=45.0,
            )
            _, text = get_text(primary.url, "/metrics")
            assert "logparser_replication_lag_bytes" in text, (
                "lag families missing from /metrics"
            )
            assert "logparser_replication_lag_records" in text
            primary.proc.kill()  # SIGKILL: no drain, no goodbye
            primary.proc.wait(10)
            trace = _poll_trace(
                standby.url,
                lambda t: (t.get("replication") or {}).get("role")
                == "primary",
                timeout=30.0,
            )
            rep = trace["replication"]
            assert rep["promotions"] >= 1 and rep["epoch"] >= 1, rep
            # the supervisor fired and disarmed itself: it counted the
            # primary down for the full threshold before promoting
            fo = rep["failover"]
            assert fo["failures"] >= 1 and fo["downS"] >= 3.0, fo
            # the fence is lifted: the replicated history serves here now
            assert post(standby.url, hdr)[0] == 200
            assert post(standby.url)[0] == 200
            _, text = get_text(standby.url, "/metrics")
            assert "logparser_replication_promotions_total" in text
        finally:
            primary.stop()
            standby.stop()


def scenario_replica_stale_primary_demotes():
    """Promote the standby while the primary is still alive — the
    operator error split-brain fencing exists for. The stale primary's
    next shipped batch is refused with the higher epoch, it demotes
    itself durably, and its client traffic 307-forwards to the new
    owner instead of double-serving."""
    with tempfile.TemporaryDirectory(prefix="chaos_replica_") as tmp:
        primary, standby = _replica_pair(tmp, "replica-stale")
        try:
            hdr = {"X-Tenant": "acme"}
            assert post(primary.url, hdr)[0] == 200
            _poll_trace(
                standby.url,
                lambda t: (t.get("replication") or {}).get(
                    "appliedRecords", 0) >= 1,
                timeout=45.0,
            )
            status, body = post_raw(standby.url, "/admin/promote",
                                    b'{"reason":"drill"}')
            assert status == 200 and body["status"] == "promoted", (
                status, body,
            )
            assert body["epoch"] >= 1, body
            # new traffic on the stale primary journals fresh frames; its
            # pump ships them with the old epoch and gets refused
            assert post(primary.url, hdr)[0] in (200, 307)
            trace = _poll_trace(
                primary.url,
                lambda t: (t.get("replication") or {}).get("role")
                == "standby",
                timeout=30.0,
            )
            rep = trace["replication"]
            assert rep["demotions"] >= 1, rep
            assert rep["epoch"] >= body["epoch"], rep
            # fenced: the loser forwards to the winner
            code, _, fhdrs = post(primary.url, hdr)
            assert code == 307, code
            assert fhdrs["Location"].startswith(standby.url), fhdrs
            assert post(standby.url, hdr)[0] == 200
        finally:
            primary.stop()
            standby.stop()


def scenario_replica_lagging_promotion():
    """SIGKILL the primary with an unshipped WAL tail, then promote by
    hand: the standby serves the acked prefix — the documented
    state-loss bound — and the promotion is journaled (idempotent on a
    second POST)."""
    with tempfile.TemporaryDirectory(prefix="chaos_replica_") as tmp:
        primary, standby = _replica_pair(tmp, "replica-lag")
        try:
            hdr = {"X-Tenant": "acme"}
            assert post(primary.url, hdr)[0] == 200
            _poll_trace(
                standby.url,
                lambda t: (t.get("replication") or {}).get(
                    "appliedRecords", 0) >= 1,
                timeout=45.0,
            )
            acked = _applied_records(standby.url)
            # pile on a tail and kill before the 0.2s pump can ship all
            # of it — some of these frames (and some of these requests)
            # die with the primary, which is the point
            def fire():
                try:
                    post(primary.url, hdr, timeout=10)
                except OSError:
                    pass  # connection died under SIGKILL
            threads = [threading.Thread(target=fire) for _ in range(4)]
            for t in threads:
                t.start()
            primary.proc.kill()
            primary.proc.wait(10)
            for t in threads:
                t.join(30)
            assert all(not t.is_alive() for t in threads), "burst stuck"
            status, body = post_raw(standby.url, "/admin/promote",
                                    b'{"reason":"primary dead"}')
            assert status == 200 and body["status"] == "promoted", (
                status, body,
            )
            # idempotent re-promote: already primary, same epoch
            status2, body2 = post_raw(standby.url, "/admin/promote", b"{}")
            assert status2 == 200 and body2["status"] == "primary", (
                status2, body2,
            )
            assert body2["epoch"] == body["epoch"], (body, body2)
            # the acked prefix survived the failover and serves
            assert _applied_records(standby.url) >= acked
            assert post(standby.url, hdr)[0] == 200
            _, trace = get(standby.url, "/trace/last")
            rep = trace["replication"]
            assert rep["role"] == "primary" and rep["promotions"] >= 1, rep
        finally:
            primary.stop()
            standby.stop()


REPLICA_STANDALONE = [
    ("replica-failover-kill9", scenario_replica_failover_kill9),
    ("replica-stale-primary-demotes", scenario_replica_stale_primary_demotes),
    ("replica-lagging-promotion", scenario_replica_lagging_promotion),
]


def scenario_miner_tap_overflow(srv: Server):
    """A wedged miner worker (miner_hang:inf) under a tiny tap capacity:
    the bounded queue fills, further novel lines become DROPS — counted
    on /trace/last, invisible to the hot path (every request still 200,
    nothing blocks behind the dead consumer)."""
    for r in range(6):
        lines = "\n".join(
            f"chaosnovel{r}x{i} widget rebalance pass={r}.{i}" for i in range(12)
        )
        status, body, _ = post_logs(srv.url, lines)
        assert status == 200, (status, body)
    trace = _poll_trace(
        srv.url, lambda t: t.get("miner", {}).get("dropped", 0) >= 1
    )
    m = trace["miner"]
    assert m["queued"] <= 4, m  # capacity env below
    assert m["tapped"] <= 4, m  # nothing drained: worker is wedged
    assert m["clusters"] == 0, m  # the consumer really is dead
    # the hot path after saturation: still instant 200s
    assert post_logs(srv.url, "one more\nplain line")[0] == 200


MINER_SCENARIOS = [
    (
        "miner-tap-overflow",
        ["--miner", "on"],
        {
            "LOG_PARSER_TPU_FAULTS": "miner_hang:inf",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
            "LOG_PARSER_TPU_MINER_TAP_CAPACITY": "4",
        },
        scenario_miner_tap_overflow,
    ),
]


# ------------------------------------------------- observability scenarios
#
# Obs group (``--group obs``; the fleet observability plane — docs/OPS.md
# "Observability"): /metrics stays live and monotone while the device
# path is faulting; the slow-request ring captures the faulted request by
# its propagated id; sustained availability burn flips the /q/health
# ``slo`` check DEGRADED and it recovers once the error cells age out of
# every window.


def get_text(url: str, path: str):
    """Raw-text GET — /metrics is Prometheus exposition, not JSON."""
    with urllib.request.urlopen(url + path, timeout=10) as resp:
        return resp.status, resp.read().decode()


def _metric_total(text: str, name: str) -> float | None:
    """Sum every sample of one metric family across its label sets."""
    total, found = 0.0, False
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        head = line.split(" ", 1)[0]
        if head.split("{", 1)[0] == name:
            total += float(line.rsplit(" ", 1)[1])
            found = True
    return total if found else None


def scenario_obs_metrics_monotone(srv: Server):
    status, text = get_text(srv.url, "/metrics")
    assert status == 200, status
    assert "# TYPE logparser_requests_total counter" in text, "missing TYPE"
    before = _metric_total(text, "logparser_requests_total") or 0.0
    statuses = [post(srv.url)[0] for _ in range(8)]
    assert statuses == [200] * 8, statuses  # faults fall back to golden
    status, text = get_text(srv.url, "/metrics")
    assert status == 200, "metrics endpoint died under device faults"
    assert 'le="+Inf"' in text, "histogram without +Inf bucket"
    after = _metric_total(text, "logparser_requests_total")
    assert after is not None and after >= before + 8, (before, after)
    fallbacks = _metric_total(text, "logparser_fallback_total")
    assert fallbacks and fallbacks >= 1, f"seeded p=0.5 never fired: {fallbacks}"
    # registry and /trace/last read the same counters — no dual books
    _, trace = get(srv.url, "/trace/last")
    assert trace["fallbackCount"] == fallbacks, (trace["fallbackCount"], fallbacks)


def scenario_obs_slow_ring_capture(srv: Server):
    # request 1 eats the injected 0.5 s device stall (plus first-compile
    # time) — far over the 250 ms bar; its propagated id must land in the
    # slow ring and survive later fast traffic
    status, _, hdrs = post(srv.url, headers={"X-Request-Id": "slowpoke-1"})
    assert status == 200, status
    assert hdrs.get("X-Request-Id") == "slowpoke-1", hdrs
    for _ in range(3):
        assert post(srv.url)[0] == 200
    _, recent = get(srv.url, "/trace/recent?n=10")
    slow_ids = [e["requestId"] for e in recent["slow"]]
    assert "slowpoke-1" in slow_ids, slow_ids
    assert recent["ring"]["slowCaptured"] >= 1, recent["ring"]
    assert len(recent["requests"]) == 4, recent["requests"]


def scenario_obs_slo_burn_flip(srv: Server):
    # 6 injected transport 500s in one second: error frac 1.0 against a
    # 0.1 budget burns 10x on both (2 s / 4 s) windows -> DEGRADED
    statuses = [post(srv.url)[0] for _ in range(6)]
    assert statuses == [500] * 6, statuses
    _, health = get(srv.url, "/q/health")
    slo = next(c for c in health.get("checks", []) if c["name"] == "slo")
    assert slo["status"] == "DEGRADED", slo
    assert "availability" in slo["burning"], slo
    # fault spec is exhausted (@times=6): traffic is healthy again; the
    # error cells age out of the 4 s window and the check recovers
    deadline = time.monotonic() + 15
    recovered = False
    while time.monotonic() < deadline:
        assert post(srv.url)[0] == 200
        _, health = get(srv.url, "/q/health")
        checks = health.get("checks", [])
        slo = next((c for c in checks if c["name"] == "slo"), None)
        if slo is None or slo["status"] == "UP":
            recovered = True
            break
        time.sleep(0.5)
    assert recovered, f"slo check never recovered: {health}"


OBS_SCENARIOS = [
    (
        "obs-metrics-monotone",
        # cache off so every request reaches the faulted device site
        ["--line-cache-mb", "0"],
        {
            "LOG_PARSER_TPU_FAULTS": "device_raise:0.5",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_obs_metrics_monotone,
    ),
    (
        "obs-slow-ring-capture",
        ["--trace-slow-ms", "250"],
        {
            "LOG_PARSER_TPU_FAULTS": "device_slow:0.5@times=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_obs_slow_ring_capture,
    ),
    (
        "obs-slo-burn-flip",
        ["--slo-availability", "0.9"],
        {
            "LOG_PARSER_TPU_SLO_WINDOWS_S": "2,4",
            "LOG_PARSER_TPU_FAULTS": "http_raise:1.0@times=6",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_obs_slo_burn_flip,
    ),
]


# Spans group (``--group spans``; causal span tracing — docs/OPS.md
# "Span tracing & utilization accounting"): a faulted device dispatch
# records its span — carrying the fault site — on the flush trace
# before bisection retries, and the sampling drop path never orphans a
# staged child span while force-kept flush traces still commit.


def _poll_spans(url: str, pred, timeout: float = 30.0) -> dict:
    """Poll GET /trace/spans until ``pred(body)`` — the flush trace
    commits on the scheduler thread a beat after the member responses
    return, so assertions on it must wait it out."""
    deadline = time.monotonic() + timeout
    body = {}
    while time.monotonic() < deadline:
        status, body = get(url, "/trace/spans?n=64")
        assert status == 200, status
        if pred(body):
            return body
        time.sleep(0.25)
    raise AssertionError(f"span predicate never held: {body}")


def scenario_spans_fault_site(srv: Server):
    post(srv.url)  # warm: one device call burns the fault's after=1
    results = Burst(srv.url, 4).join(timeout=120)
    codes = sorted(s for s, _ in results)
    assert codes == [200] * 4, codes  # bisection/golden absorbed the fault

    def _faulted_flush_closed(body):
        flushes = [t for t in body["traces"] if t["name"] == "flush"]
        return any(
            "error" in (s.get("attrs") or {})
            for t in flushes for s in t["spans"] if s["name"] == "dispatch"
        ) and all(
            any(s["name"] == "demux" for s in t["spans"]) for t in flushes
        )

    body = _poll_spans(srv.url, _faulted_flush_closed)
    flushes = [t for t in body["traces"] if t["name"] == "flush"]
    # the faulted dispatch recorded its span with the failure attr, and
    # the SAME flush trace still closed with its demux span — a fault is
    # a recorded child of the tree, never a hole in it
    faulted = [
        t for t in flushes
        if any(
            "error" in (s.get("attrs") or {})
            for s in t["spans"] if s["name"] == "dispatch"
        )
    ]
    assert faulted, [t["name"] for t in body["traces"]]
    assert any(s["name"] == "demux" for s in faulted[0]["spans"]), faulted[0]
    # causality survives the fault: flush roots still link member request
    # traces, and a member request back-links a flush trace
    linked = {
        ln["traceId"]
        for t in flushes for ln in (t["spans"][0].get("links") or [])
    }
    assert linked, flushes
    requests = [t for t in body["traces"] if t["name"] == "request"]
    flush_ids = {t["traceId"] for t in flushes}
    assert any(
        ln["traceId"] in flush_ids
        for t in requests for ln in (t["spans"][0].get("links") or [])
    ), requests
    assert body["store"]["staged"] == 0, body["store"]


def scenario_spans_sample_drop(srv: Server):
    post(srv.url)  # warm compile off the clock
    results = Burst(srv.url, 4).join(timeout=120)
    codes = sorted(s for s, _ in results)
    assert codes == [200] * 4, codes
    # flush traces are rare and force-kept: they commit at sample 0
    body = _poll_spans(
        srv.url, lambda b: any(t["name"] == "flush" for t in b["traces"])
    )
    names = [t["name"] for t in body["traces"]]
    # ... while every request trace was head-sampled away (slow bar
    # lifted out of reach so the always-on slow path cannot rescue them)
    assert "request" not in names, names
    store = body["store"]
    assert store["droppedTraces"] >= 5, store
    # the drop popped each request's staged enqueue/admission children
    # with it — a dropped sample never orphans a staged span
    assert store["staged"] == 0, store


SPANS_SCENARIOS = [
    (
        "spans-fault-site",
        # cache off: identical chaos payloads would be full line-cache
        # hits after the warm post and the flush would never reach the
        # faulted device dispatch
        BATCHER_FLAGS + ["--line-cache-mb", "0"],
        {
            "LOG_PARSER_TPU_FAULTS": "device_raise@times=1@after=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_spans_fault_site,
    ),
    (
        "spans-sample-drop",
        BATCHER_FLAGS + ["--trace-sample", "0", "--trace-slow-ms", "60000"],
        {},
        scenario_spans_sample_drop,
    ),
]


def _miner_engine(curated_regex: str, mode: str = "auto"):
    """In-process engine + miner for the standalone drills: one curated
    pattern, line cache on, worker NOT started (pump() is driven
    explicitly so every step is deterministic)."""
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pattern import (
        Pattern, PatternSet, PatternSetMetadata, PrimaryPattern,
    )
    from log_parser_tpu.runtime import AnalysisEngine

    sets = [
        PatternSet(
            metadata=PatternSetMetadata(library_id="curated", name="curated"),
            patterns=[
                Pattern(
                    id="curated-1",
                    name="curated",
                    severity="HIGH",
                    primary_pattern=PrimaryPattern(
                        regex=curated_regex, confidence=0.8
                    ),
                )
            ],
        )
    ]
    engine = AnalysisEngine(sets, ScoringConfig())
    engine.enable_line_cache(4)
    engine.enable_miner(
        mode=mode, min_support=3, stability=0, autostart=False
    )
    return engine, sets


def _miner_pod(lines: list[str]):
    from log_parser_tpu.models.pod import PodFailureData

    return PodFailureData(pod={"metadata": {"name": "chaos"}}, logs="\n".join(lines))


def scenario_miner_reject_identity():
    """A vet-rejected candidate must leave the serving bank OBJECT-
    identical — not rebuilt-equal, the same object — and the reload epoch
    untouched. The curated pattern's regex is byte-identical to what the
    synthesizer will emit, so admission rejects at the duplicate gate."""
    engine, _ = _miner_engine(
        r"FooBarBazQux\s{1,8}happened\s{1,8}at\s{1,8}\S{1,64}"
    )
    bank_before = engine.bank
    epoch_before = engine.reload_epoch
    engine.analyze(_miner_pod(
        [f"FooBarBazQux happened at t{i}" for i in range(4)]
    ))
    engine.miner.pump()
    stats = engine.miner.stats()
    assert stats["rejected"].get("mined-duplicate") == 1, stats
    assert stats["admitted"] == 0 and stats["errors"] == 0, stats
    assert engine.bank is bank_before, "rejection rebuilt the bank"
    assert engine.reload_epoch == epoch_before, engine.reload_epoch
    engine.miner.stop()


def scenario_miner_reload_race():
    """Mined admission racing a concurrent curated reload: while the
    quiesce gate is held by the curated swap, admission's apply_library
    raises — a retryable mined-swap, never an error or a torn bank. The
    curated reload lands first; the mined candidate re-admits on a later
    pump against the POST-reload library."""
    from log_parser_tpu.runtime.reload import build_candidate

    engine, sets = _miner_engine("OutOfMemoryError")
    engine.analyze(_miner_pod(
        [f"zorblatt collector compacted tier t{i} fine" for i in range(4)]
    ))
    # hold the quiesce gate exactly the way an in-progress curated
    # reload does, then pump: admission must fail CLEANLY into retry
    with engine._quiesce_cv:
        engine._swap_pending = True
    try:
        engine.miner.pump()
    finally:
        with engine._quiesce_cv:
            engine._swap_pending = False
            engine._quiesce_cv.notify_all()
    stats = engine.miner.stats()
    assert stats["retrying"] == 1 and stats["admitted"] == 0, stats
    assert stats["errors"] == 0, stats
    # the curated reload wins the race...
    engine.apply_library(
        build_candidate(sets, engine.config, engine_clock=engine.frequency.clock)
    )
    assert engine.reload_epoch == 1
    # ...and the retry admits against the post-reload library
    engine.miner.pump()
    stats = engine.miner.stats()
    assert stats["admitted"] == 1 and stats["retrying"] == 0, stats
    assert stats["errors"] == 0 and not stats["rejected"], stats
    ids = {p.id for ps in engine.bank.pattern_sets for p in ps.patterns}
    assert "curated-1" in ids and any(i.startswith("mined-") for i in ids), ids
    # the merged library serves: both curated and mined fire
    r = engine.analyze(_miner_pod(
        ["zorblatt collector compacted tier t9 fine", "OutOfMemoryError"]
    ))
    got = {e.matched_pattern.id for e in r.events}
    assert "curated-1" in got and any(i.startswith("mined-") for i in got), got
    engine.miner.stop()


# in-process drills: object identity and deterministic gate-holding need
# the engine in OUR process, not behind HTTP
MINER_STANDALONE = [
    ("miner-reject-identity", scenario_miner_reject_identity),
    ("miner-reload-race", scenario_miner_reload_race),
]


# Fleet group (``--group fleet``; router front-door + signal-driven
# placement — docs/OPS.md "Fleet routing & placement"): a real
# ``--role router`` process proxying to real backend serving processes
# over a consistent-hash ring, with the placement control loop live.


def _fleet(tmp: str, prefix: str, router_flags: list | None = None,
           backend_flags: list | None = None,
           backend_env: dict | None = None,
           router_env: dict | None = None):
    """A router over two backend serving processes sharing one tenant
    library root (migrations need identical pattern config fleet-wide),
    each backend with its own --state-dir. Backends boot and become
    ready BEFORE the router exists, so backend boot latency is never
    counted against --fleet-down-after."""
    root = _make_tenant_root(tmp)
    backends = [
        Server(
            f"{prefix}-backend{i}",
            ["--tenant-root", root,
             "--state-dir", os.path.join(tmp, f"state{i}"),
             *(backend_flags or [])],
            backend_env or {},
        )
        for i in range(2)
    ]
    for b in backends:
        b.wait_ready()
    router = Server(
        f"{prefix}-router",
        ["--role", "router",
         "--backends", ",".join(f"127.0.0.1:{b.port}" for b in backends),
         *(router_flags or [])],
        router_env or {},
    )
    router.wait_ready()
    return router, backends


def _router_metric(url: str, name: str, label: str = "") -> float:
    """Sum of a metric family's samples on the router's /metrics,
    optionally filtered by a label substring."""
    _, text = get_text(url, "/metrics")
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name) and (not label or label in line):
            try:
                total += float(line.rsplit(None, 1)[1])
            except ValueError:
                pass
    return total


def _poll_until(pred, timeout: float = 30.0, every: float = 0.5):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        last = pred()
        if last:
            return last
        time.sleep(every)
    raise AssertionError(f"condition never held (last: {last!r})")


def scenario_fleet_backend_kill_reroute():
    """SIGKILL one backend of two: the ring evicts it after
    --fleet-down-after failed contacts, every subsequent request —
    including the ones racing the detection window — is served by the
    survivor, and the router's aggregate health stays UP."""
    with tempfile.TemporaryDirectory(prefix="chaos_fleet_") as tmp:
        router, backends = _fleet(
            tmp, "fleet-kill",
            router_flags=["--fleet-down-after", "1", "--fleet-poll-s", "0.5"],
        )
        try:
            # both tenants route through the front-door while the fleet
            # is whole
            for hdr in (None, {"X-Tenant": "acme"}):
                status, _, _ = post(router.url, hdr)
                assert status == 200, status
            assert _router_metric(
                router.url, "logparser_fleet_backends_up"
            ) == 2.0
            backends[0].proc.kill()
            backends[0].proc.wait(10)
            # zero client errors across the detection window: a request
            # that lands on the dead backend retries the next ring owner
            # in-flight
            for i in range(8):
                hdr = {"X-Tenant": "acme"} if i % 2 else None
                status, body, _ = post(router.url, hdr)
                assert status == 200, (i, status, body)
            _poll_until(lambda: _router_metric(
                router.url, "logparser_fleet_backends_up") == 1.0)
            assert _router_metric(
                router.url, "logparser_fleet_reroutes_total", "backend_down"
            ) >= 1.0
            hstatus, health = get(router.url, "/q/health")
            assert hstatus == 200 and health["status"] == "UP", (
                hstatus, health,
            )
            _, fleet = get(router.url, "/fleet/status")
            assert len(fleet["ring"]["backends"]) == 1, fleet["ring"]
            assert fleet["ring"]["remaps"] > 0, fleet["ring"]
            down = fleet["backends"][
                f"http://127.0.0.1:{backends[0].port}"]
            assert not down["up"] and down["lastError"], down
        finally:
            router.stop()
            for b in backends:
                b.stop()


def scenario_fleet_hot_tenant_automove():
    """A tenant burning its rate quota (429 sheds on the backend) is
    live-migrated by the placer: the shed rate is scraped off the
    backend's own /metrics, the move runs the real migrate protocol,
    and the tenant serves from its new owner — clients never see a
    5xx, only 200s and the structured 429s the quota already answers."""
    with tempfile.TemporaryDirectory(prefix="chaos_fleet_") as tmp:
        router, backends = _fleet(
            tmp, "fleet-hot",
            router_flags=["--fleet-poll-s", "0.5",
                          "--fleet-shed-rate", "0.5",
                          "--fleet-down-after", "10"],
            # PAYLOAD is 3 lines; lines/s 2 = a 4-token bucket, so a
            # concurrent burst sheds structured 429s per tenant
            backend_flags=["--tenant-lines-per-s", "2"],
        )
        try:
            hdr = {"X-Tenant": "acme"}
            assert post(router.url, hdr)[0] == 200
            statuses = []
            # sustained sheds across several placer polls
            for _ in range(3):
                burst = Burst(router.url, 6, hdr)
                statuses.extend(s for s, _ in burst.join())
                time.sleep(0.6)
            assert set(statuses) <= {200, 429}, statuses
            assert 429 in statuses, statuses
            _poll_until(lambda: _router_metric(
                router.url, "logparser_fleet_moves_total") >= 1.0)
            assert _router_metric(
                router.url, "logparser_fleet_moves_total", "quota_shed"
            ) >= 1.0
            # the moved tenant serves from its new owner once the token
            # bucket refills; the router already routes there (the
            # override was installed on the migrate ack)
            def served():
                status, _, _ = post(router.url, hdr)
                return status == 200
            _poll_until(served, timeout=15.0)
            _, fleet = get(router.url, "/fleet/status")
            assert fleet["placement"]["movesFailed"] == 0, fleet["placement"]
        finally:
            router.stop()
            for b in backends:
                b.stop()


def scenario_fleet_budget_rebalance():
    """Fleet-arbitrated budgets land on both sides: the router splits
    --fleet-cache-mb / --fleet-tenant-budget-mb from observed traffic
    and pushes POST /admin/budget; each backend's /trace/last shows the
    applied share replacing its boot-time flag value."""
    with tempfile.TemporaryDirectory(prefix="chaos_fleet_") as tmp:
        router, backends = _fleet(
            tmp, "fleet-budget",
            router_flags=["--fleet-poll-s", "0.5",
                          "--fleet-cache-mb", "32",
                          "--fleet-tenant-budget-mb", "48"],
            backend_flags=["--line-cache-mb", "64"],
        )
        try:
            for hdr in (None, {"X-Tenant": "acme"}):
                assert post(router.url, hdr)[0] == 200

            def applied():
                shares = []
                for b in backends:
                    _, trace = get(b.url, "/trace/last")
                    cache_mb = trace.get("lineCache", {}).get("budgetMb")
                    tenant_mb = trace.get("tenants", {}).get("budgetMb")
                    if cache_mb is None or cache_mb == 64.0:
                        return None  # boot-time flag value still in force
                    if not tenant_mb:
                        return None
                    shares.append((cache_mb, tenant_mb))
                return shares

            shares = _poll_until(applied)
            # the shares partition the fleet-wide budgets (floor 8 MiB
            # each plus the traffic-proportional pool)
            assert abs(sum(s[0] for s in shares) - 32.0) < 0.1, shares
            assert abs(sum(s[1] for s in shares) - 48.0) < 0.1, shares
            assert all(s[0] >= 8.0 and s[1] >= 8.0 for s in shares), shares
            _, fleet = get(router.url, "/fleet/status")
            budget = fleet["placement"]["budget"]
            assert len(budget) == 2, budget
            assert _router_metric(
                router.url, "logparser_fleet_budget_mb", "line_cache"
            ) > 0.0
        finally:
            router.stop()
            for b in backends:
                b.stop()


FLEET_STANDALONE = [
    ("fleet-backend-kill-reroute", scenario_fleet_backend_kill_reroute),
    ("fleet-hot-tenant-automove", scenario_fleet_hot_tenant_automove),
    ("fleet-budget-rebalance", scenario_fleet_budget_rebalance),
]


# Pressure group (``--group pressure``; resource-exhaustion ladder —
# docs/OPS.md "Resource exhaustion"): the disk watermark ladder, the
# durability-degrade/re-arm cycle, and retry-budget shedding, all forced
# through the ``disk_enospc`` / ``retry_storm`` fault sites so the
# drills run on any host without filling a real disk.


def scenario_pressure_soft_compaction():
    """Soft disk pressure (a ``watermark:soft`` probe raise): the ladder
    reclaims — a seeded terminal migration journal compacts past its
    decision records — while /q/health answers 200 with a DEGRADED
    pressure check and responses stay 200 WITHOUT the ``durability``
    stamp: soft reclaims space, it never downgrades durability."""
    from log_parser_tpu.runtime.migrate import MIGRATE_DIR, MigrationJournal

    with tempfile.TemporaryDirectory(prefix="chaos_pressure_") as tmp:
        state = os.path.join(tmp, "state")
        # a finished migration's source journal: begin + chatter +
        # cutover + complete. Only [begin, cutover, complete] matter
        # after the terminal record — compaction must reclaim the rest.
        seeded = os.path.join(state, MIGRATE_DIR, "m-old.src.wal")
        jr = MigrationJournal(seeded)
        jr.append("begin", mid="m-old", tenant="ghost",
                  src="local", dst="http://127.0.0.1:1")
        for i in range(16):
            jr.append("copy", chunk=i)
        jr.append("cutover", location="http://127.0.0.1:1", retryAfterS=1)
        jr.append("complete")
        jr.close()
        srv = Server(
            "pressure-soft",
            ["--state-dir", state],
            {"LOG_PARSER_TPU_FAULTS":
                 "disk_enospc_raise@match=watermark:soft"},
        )
        try:
            srv.wait_ready()
            status, body, _ = post(srv.url)
            assert status == 200, (status, body)
            assert "durability" not in body, body
            hstatus, health = get(srv.url, "/q/health")
            assert hstatus == 200, (hstatus, health)
            pres = [c for c in health.get("checks", [])
                    if c.get("name") == "pressure"]
            assert pres and pres[0]["status"] == "DEGRADED", health
            assert pres[0]["data"]["disk"] == "soft", health
            _, trace = get(srv.url, "/trace/last")
            p = trace["pressure"]
            assert p["disk"] == "soft", p
            assert p["compacted"].get("migration", 0) >= 1, p
            kinds = [r.get("k") for r in MigrationJournal.replay(seeded)]
            assert kinds == ["begin", "cutover", "complete"], kinds
            srv.stop(expect_zero=True)
        finally:
            srv.stop()


def scenario_pressure_hard_degrade_rearm():
    """Hard disk pressure forced for a few polls (``watermark:hard``
    raise, @times-bounded): responses stay 200 but carry ``durability:
    degraded`` and the WAL diverts to the in-memory ring; when the
    fault exhausts, the ladder re-arms from a clean snapshot barrier
    and the stamp disappears — its absence is the durability promise."""
    with tempfile.TemporaryDirectory(prefix="chaos_pressure_") as tmp:
        state = os.path.join(tmp, "state")
        srv = Server(
            "pressure-hard",
            ["--state-dir", state],
            # match-specs only consume on their own key, so @times=N is
            # exactly N ladder polls pinned hard (~N seconds at the 1s
            # poll) — sized to outlive the first request's jit warm-up
            {"LOG_PARSER_TPU_FAULTS":
                 "disk_enospc_raise@match=watermark:hard@times=45"},
        )
        try:
            srv.wait_ready()
            status, body, _ = post(srv.url)
            assert status == 200, (status, body)
            assert body.get("durability") == "degraded", body
            hstatus, health = get(srv.url, "/q/health")
            pres = [c for c in health.get("checks", [])
                    if c.get("name") == "pressure"]
            assert hstatus == 200 and pres, (hstatus, health)
            assert pres[0]["data"]["disk"] == "hard", health
            _, trace = get(srv.url, "/trace/last")
            assert trace["journal"]["degraded"] is True, trace["journal"]
            assert trace["journal"]["degradedRecords"] >= 1, trace["journal"]

            def recovered():
                _, t = get(srv.url, "/trace/last")
                return t["pressure"]["disk"] == "ok"
            _poll_until(recovered, timeout=90.0)
            status, body, _ = post(srv.url)
            assert status == 200, (status, body)
            assert "durability" not in body, body
            _, trace = get(srv.url, "/trace/last")
            assert trace["journal"]["degraded"] is False, trace["journal"]
            assert trace["pressure"]["transitions"].get("disk:ok", 0) >= 1, (
                trace["pressure"]
            )
            hstatus, health = get(srv.url, "/q/health")
            assert hstatus == 200 and not [
                c for c in health.get("checks", [])
                if c.get("name") == "pressure"
            ], health
            srv.stop(expect_zero=True)
        finally:
            srv.stop()


def scenario_pressure_retry_storm_shed():
    """A dead backend under an armed ``retry_storm`` fault: the
    router's re-route retries shed a structured 503 ``retry budget
    exhausted`` instead of hammering the fleet, and once the request
    path has evicted the corpse, requests serve 200 again. The control
    fleet — the SAME kill and fault with ``--retry-budget 0`` — retries
    unbounded straight to a 200, which is exactly the storm the budget
    converts into deterministic sheds."""
    from log_parser_tpu.fleet.ring import HashRing

    # the pump poll is parked at 30s so ONLY request-path failures
    # (--fleet-down-after 2) evict the corpse: the shed sequence is
    # then deterministic, not a race against the health loop
    flags = ["--fleet-poll-s", "30", "--fleet-down-after", "2"]
    storm = {"LOG_PARSER_TPU_FAULTS": "retry_storm_raise"}
    hdr = {"X-Tenant": "acme"}

    def kill_owner(router, backends):
        # ports are random per run, so compute acme's ring owner the
        # way the router does and kill exactly that backend
        urls = [f"http://127.0.0.1:{b.port}" for b in backends]
        victim = backends[urls.index(HashRing(urls).owner("acme"))]
        victim.proc.kill()
        victim.proc.wait(10)

    with tempfile.TemporaryDirectory(prefix="chaos_pressure_") as tmp:
        router, backends = _fleet(
            tmp, "pressure-storm", router_flags=flags, router_env=storm,
        )
        try:
            assert post(router.url, hdr)[0] == 200
            kill_owner(router, backends)
            # first post: the attempt on the corpse fails, the re-route
            # wants a retry token, the storm fault says the bucket is
            # dry -> structured shed
            status, body, _ = post(router.url, hdr)
            assert status == 503, (status, body)
            assert body.get("error") == "retry budget exhausted", body
            assert _router_metric(
                router.url, "logparser_pressure_retry_total", "shed"
            ) >= 1.0

            # each shed post still charged the corpse one failure; once
            # it leaves the ring the survivor answers first-attempt (no
            # retry, so the armed storm fault never fires)
            def served():
                status, body, _ = post(router.url, hdr)
                if status == 503:
                    assert body.get("error") == "retry budget exhausted", body
                    return False
                return status == 200
            _poll_until(served, timeout=20.0)
        finally:
            router.stop()
            for b in backends:
                b.stop()

    with tempfile.TemporaryDirectory(prefix="chaos_pressure_") as tmp:
        router, backends = _fleet(
            tmp, "pressure-storm-ctl",
            router_flags=[*flags, "--retry-budget", "0"], router_env=storm,
        )
        try:
            assert post(router.url, hdr)[0] == 200
            kill_owner(router, backends)
            # unbounded control: the same fault is armed but a disabled
            # budget never consults it — the very first post retries
            # through the corpse (evicting it) to the survivor's 200
            status, body, _ = post(router.url, hdr)
            assert status == 200, (status, body)
            assert _router_metric(
                router.url, "logparser_pressure_retry_total", "shed"
            ) == 0.0
        finally:
            router.stop()
            for b in backends:
                b.stop()


PRESSURE_STANDALONE = [
    ("pressure-soft-compaction", scenario_pressure_soft_compaction),
    ("pressure-hard-degrade-rearm", scenario_pressure_hard_degrade_rearm),
    ("pressure-retry-storm-shed", scenario_pressure_retry_storm_shed),
]


SCENARIOS = [
    ("baseline", [], {}, scenario_baseline),
    (
        "device-raise",
        # cache off: identical chaos payloads are full line-cache hits
        # after the first request, which would skip the device site
        ["--line-cache-mb", "0"],
        {
            "LOG_PARSER_TPU_FAULTS": "device_raise:0.5",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_device_raise,
    ),
    (
        "device-wedge",
        ["--device-timeout", "2.0"],
        {
            "LOG_PARSER_TPU_FAULTS": "device_hang:inf@after=1@times=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
            "LOG_PARSER_TPU_BREAKER_COOLDOWN_S": "600",
        },
        scenario_device_wedge,
    ),
    (
        "queue-shed",
        ["--max-inflight", "1", "--max-queue", "1"],
        {
            "LOG_PARSER_TPU_FAULTS": "ingest_slow:1.0@after=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_queue_shed,
    ),
    (
        "drain",
        ["--drain-s", "20"],
        {
            "LOG_PARSER_TPU_FAULTS": "ingest_slow:2.0@after=1@times=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_drain,
    ),
]


def _group_registry() -> dict[str, list[str]]:
    """Every scenario name by group — the source of truth ``--list``
    prints and ``--only`` can be checked against."""
    return {
        "base": [s[0] for s in SCENARIOS],
        "batcher": [s[0] for s in BATCHER_SCENARIOS],
        "state": [s[0] for s in STATE_SCENARIOS]
        + [s[0] for s in STATE_STANDALONE],
        "poison": [s[0] for s in POISON_SCENARIOS],
        "linecache": [s[0] for s in LINECACHE_SCENARIOS],
        "kernel": [s[0] for s in KERNEL_SCENARIOS],
        "streaming": [s[0] for s in STREAMING_SCENARIOS],
        "distributed": [s[0] for s in DISTRIBUTED_SCENARIOS],
        "tenant": [s[0] for s in TENANT_STANDALONE],
        "miner": [s[0] for s in MINER_SCENARIOS]
        + [s[0] for s in MINER_STANDALONE],
        "obs": [s[0] for s in OBS_SCENARIOS],
        "spans": [s[0] for s in SPANS_SCENARIOS],
        "migrate": [s[0] for s in MIGRATE_STANDALONE],
        "replica": [s[0] for s in REPLICA_STANDALONE],
        "fleet": [s[0] for s in FLEET_STANDALONE],
        "pressure": [s[0] for s in PRESSURE_STANDALONE],
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="chaos_sweep")
    parser.add_argument("--only", help="run a single scenario by name")
    parser.add_argument(
        "--list", action="store_true",
        help="print every scenario (group + name) and exit",
    )
    parser.add_argument(
        "--json", metavar="PATH",
        help="also write the result table to PATH as a JSON artifact",
    )
    parser.add_argument(
        "--group",
        choices=(
            "base", "batcher", "state", "poison", "linecache", "kernel",
            "streaming", "distributed", "tenant", "miner", "obs", "spans",
            "migrate", "replica", "fleet", "pressure", "all",
        ),
        default="base",
        help="which scenario group to sweep (default: base; the "
        "distributed group needs multi-process CPU collective support)",
    )
    parser.add_argument(
        "--keep-logs", action="store_true",
        help="keep child logs even for passing scenarios",
    )
    args = parser.parse_args(argv)

    if args.list:
        registry = _group_registry()
        width = max(len(g) for g in registry)
        for group, names in registry.items():
            for name in names:
                print(f"{group:<{width}}  {name}")
        return 0

    rows = []
    failed = 0
    single_server = []
    if args.group in ("base", "all"):
        single_server.extend(SCENARIOS)
    if args.group in ("batcher", "all"):
        single_server.extend(BATCHER_SCENARIOS)
    if args.group in ("state", "all"):
        single_server.extend(STATE_SCENARIOS)
    if args.group in ("poison", "all"):
        single_server.extend(POISON_SCENARIOS)
    if args.group in ("linecache", "all"):
        single_server.extend(LINECACHE_SCENARIOS)
    if args.group in ("kernel", "all"):
        single_server.extend(KERNEL_SCENARIOS)
    if args.group in ("streaming", "all"):
        single_server.extend(STREAMING_SCENARIOS)
    if args.group in ("miner", "all"):
        single_server.extend(MINER_SCENARIOS)
    if args.group in ("obs", "all"):
        single_server.extend(OBS_SCENARIOS)
    if args.group in ("spans", "all"):
        single_server.extend(SPANS_SCENARIOS)
    if single_server:
        for name, flags, env, check in single_server:
            if args.only and name != args.only:
                continue
            t0 = time.monotonic()
            srv = Server(name, flags, env)
            try:
                srv.wait_ready()
                check(srv)
                if name != "drain":  # drain stops (and asserts on) itself
                    srv.stop()
                rows.append((name, "PASS", time.monotonic() - t0, ""))
                if not args.keep_logs:
                    os.unlink(srv.log.name)
            except Exception as exc:  # one row per scenario, keep sweeping
                srv.stop()
                failed += 1
                rows.append((name, "FAIL", time.monotonic() - t0,
                             f"{exc} (log: {srv.log.name})"))
    standalone = []
    if args.group in ("state", "all"):
        standalone.extend(STATE_STANDALONE)
    if args.group in ("tenant", "all"):
        standalone.extend(TENANT_STANDALONE)
    if args.group in ("miner", "all"):
        standalone.extend(MINER_STANDALONE)
    if args.group in ("migrate", "all"):
        standalone.extend(MIGRATE_STANDALONE)
    if args.group in ("replica", "all"):
        standalone.extend(REPLICA_STANDALONE)
    if args.group in ("fleet", "all"):
        standalone.extend(FLEET_STANDALONE)
    if args.group in ("pressure", "all"):
        standalone.extend(PRESSURE_STANDALONE)
    for name, check in standalone:
        if args.only and name != args.only:
            continue
        t0 = time.monotonic()
        try:
            check()
            rows.append((name, "PASS", time.monotonic() - t0, ""))
        except Exception as exc:
            failed += 1
            rows.append((name, "FAIL", time.monotonic() - t0, str(exc)))
    if args.group in ("distributed", "all"):
        for name, flags, env, check in DISTRIBUTED_SCENARIOS:
            if args.only and name != args.only:
                continue
            t0 = time.monotonic()
            pair = DistributedPair(name, flags, env)
            try:
                pair.coord.wait_ready(timeout=180)
                check(pair)
                pair.stop()
                rows.append((name, "PASS", time.monotonic() - t0, ""))
                if not args.keep_logs:
                    os.unlink(pair.coord.log.name)
                    os.unlink(pair.follower_log.name)
            except Exception as exc:
                tail = pair.logs_tail()
                pair.stop()
                if _NO_CPU_MULTIPROCESS in tail:
                    rows.append((name, "SKIP", time.monotonic() - t0,
                                 "CPU backend lacks multi-process collectives"))
                else:
                    failed += 1
                    rows.append((name, "FAIL", time.monotonic() - t0,
                                 f"{exc} (logs: {pair.coord.log.name}, "
                                 f"{pair.follower_log.name})"))

    width = max(len(r[0]) for r in rows) if rows else 8
    print(f"\n{'scenario':<{width}}  result  seconds  detail")
    for name, result, secs, detail in rows:
        print(f"{name:<{width}}  {result:<6}  {secs:7.1f}  {detail}")
    passed = sum(1 for r in rows if r[1] == "PASS")
    print(f"\n{passed}/{len(rows)} scenarios passed (seed 42)")
    if args.json:
        artifact = {
            "tool": "chaos_sweep",
            "group": args.group,
            "seed": 42,
            "passed": passed,
            "failed": failed,
            "skipped": sum(1 for r in rows if r[1] == "SKIP"),
            "scenarios": [
                {"name": name, "result": result,
                 "seconds": round(secs, 2), "detail": detail}
                for name, result, secs, detail in rows
            ],
        }
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
