"""Chaos sweep: the fault-injection DSL against a LIVE server.

The chaos tests (tests/test_admission.py, tests/test_faults.py) exercise
the ladder in-process; this tool runs the same scenarios the way an
operator meets them — a real ``python -m log_parser_tpu.serve`` child
process, concurrent HTTP clients, signals — and prints a pass/fail table.
Every scenario pins ``LOG_PARSER_TPU_FAULT_SEED``, so a failing row
reproduces bit-identically when re-run.

Scenarios:

- ``baseline``        no faults — every request 200.
- ``device-raise``    probabilistic device faults — every request still
                      200 (golden fallback absorbs them), fallbackCount
                      moved, NOTHING shed.
- ``device-wedge``    a permanent device hang under ``--device-timeout``
                      — breaker opens, service stays 200 from the host
                      path, health shows DEGRADED.
- ``queue-shed``      slow ingest + max-inflight=1/max-queue=1 + a burst
                      — some 200s, some 429s carrying Retry-After.
- ``drain``           SIGTERM with a slow request in flight — in-flight
                      answered 200, /health/ready 503 during drain,
                      child exits 0.

Usage: python tools/chaos_sweep.py [--only NAME] [--keep-logs]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PATTERN_DIR = os.path.join(REPO, "log_parser_tpu", "patterns", "builtin")
LOGS = "INFO boot\njava.lang.OutOfMemoryError: heap\nINFO after"
PAYLOAD = json.dumps(
    {"pod": {"metadata": {"name": "chaos"}}, "logs": LOGS}
).encode()


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def post(url: str, headers: dict | None = None, timeout: float = 30.0):
    req = urllib.request.Request(
        url + "/parse",
        data=PAYLOAD,
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def get(url: str, path: str):
    try:
        with urllib.request.urlopen(url + path, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class Server:
    """One serve child; scenario args via CLI flags, chaos via env."""

    def __init__(self, name: str, args: list[str], env: dict[str, str]):
        self.port = free_port()
        self.url = f"http://127.0.0.1:{self.port}"
        self.log = tempfile.NamedTemporaryFile(
            "wb", prefix=f"chaos_{name}_", suffix=".log", delete=False
        )
        child_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PYTHONUNBUFFERED": "1",
            **env,
        }
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "log_parser_tpu.serve",
                "--pattern-dir", PATTERN_DIR,
                "--host", "127.0.0.1", "--port", str(self.port),
                *args,
            ],
            cwd=REPO,
            env=child_env,
            stdout=self.log,
            stderr=subprocess.STDOUT,
        )

    def wait_ready(self, timeout: float = 90.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"server exited rc={self.proc.returncode} before ready "
                    f"(log: {self.log.name})"
                )
            try:
                status, _ = get(self.url, "/health/ready")
                if status == 200:
                    return
            except OSError:
                pass
            time.sleep(0.25)
        raise RuntimeError(f"server never became ready (log: {self.log.name})")

    def stop(self, expect_zero: bool = False) -> int:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(10)
        rc = self.proc.returncode
        if expect_zero and rc != 0:
            raise AssertionError(f"expected clean exit, got rc={rc}")
        return rc


class Burst:
    """N concurrent posts; collect (status, headers) pairs."""

    def __init__(self, url: str, n: int, headers: dict | None = None):
        self.results: list[tuple[int, dict]] = []
        self._lock = threading.Lock()

        def one():
            status, _, hdrs = post(url, headers)
            with self._lock:
                self.results.append((status, hdrs))

        self.threads = [threading.Thread(target=one) for _ in range(n)]
        for t in self.threads:
            t.start()

    def join(self, timeout: float = 60.0):
        for t in self.threads:
            t.join(timeout)
        assert all(not t.is_alive() for t in self.threads), "burst stuck"
        return self.results


# ------------------------------------------------------------- scenarios


def scenario_baseline(srv: Server):
    for _ in range(4):
        status, body, _ = post(srv.url)
        assert status == 200, f"expected 200, got {status}"
        assert body["summary"]["significantEvents"] >= 1
    _, trace = get(srv.url, "/trace/last")
    assert trace["fallbackCount"] == 0, trace["fallbackCount"]


def scenario_device_raise(srv: Server):
    statuses = [post(srv.url)[0] for _ in range(12)]
    assert statuses == [200] * 12, statuses
    _, trace = get(srv.url, "/trace/last")
    fired = trace["faults"]["fired"]["device_raise"]
    assert 0 < fired < 12, f"seeded p=0.5 fired {fired}/12"
    assert trace["fallbackCount"] == fired, trace
    assert trace["admission"]["shedQueueFull"] == 0


def scenario_device_wedge(srv: Server):
    # warm up off the wedge (after=1), then hit it: still 200, via golden
    assert post(srv.url)[0] == 200
    statuses = [post(srv.url)[0] for _ in range(3)]
    assert statuses == [200] * 3, statuses
    status, health = get(srv.url, "/health")
    assert status == 200 and health.get("checks"), health
    assert health["checks"][0]["status"] == "DEGRADED", health
    _, trace = get(srv.url, "/trace/last")
    assert trace["deviceCircuitOpen"] is True
    assert trace["fallbackCount"] >= 1


def scenario_queue_shed(srv: Server):
    post(srv.url)  # warm: XLA compile outside the contended burst
    results = Burst(srv.url, 6).join()
    codes = sorted(s for s, _ in results)
    assert codes.count(200) >= 2, codes
    assert codes.count(429) >= 1, codes
    for status, hdrs in results:
        if status == 429:
            assert int(hdrs["Retry-After"]) >= 1, hdrs
    _, trace = get(srv.url, "/trace/last")
    assert trace["admission"]["shedQueueFull"] >= 1, trace["admission"]


def scenario_drain(srv: Server):
    post(srv.url)  # warm
    slow = Burst(srv.url, 1)  # ingest_slow holds this one in flight
    time.sleep(0.4)
    srv.proc.send_signal(signal.SIGTERM)
    deadline = time.monotonic() + 10
    saw_unready = False
    while time.monotonic() < deadline and not saw_unready:
        try:
            status, _ = get(srv.url, "/health/ready")
            saw_unready = status == 503
        except OSError:  # listener already gone: drain finished
            break
        time.sleep(0.05)
    results = slow.join()
    assert results[0][0] == 200, f"in-flight request got {results[0][0]}"
    srv.proc.wait(30)
    assert srv.proc.returncode == 0, f"rc={srv.proc.returncode}"
    assert saw_unready, "never observed /health/ready 503 during drain"


SCENARIOS = [
    ("baseline", [], {}, scenario_baseline),
    (
        "device-raise",
        [],
        {
            "LOG_PARSER_TPU_FAULTS": "device_raise:0.5",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_device_raise,
    ),
    (
        "device-wedge",
        ["--device-timeout", "2.0"],
        {
            "LOG_PARSER_TPU_FAULTS": "device_hang:inf@after=1@times=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
            "LOG_PARSER_TPU_BREAKER_COOLDOWN_S": "600",
        },
        scenario_device_wedge,
    ),
    (
        "queue-shed",
        ["--max-inflight", "1", "--max-queue", "1"],
        {
            "LOG_PARSER_TPU_FAULTS": "ingest_slow:1.0@after=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_queue_shed,
    ),
    (
        "drain",
        ["--drain-s", "20"],
        {
            "LOG_PARSER_TPU_FAULTS": "ingest_slow:2.0@after=1@times=1",
            "LOG_PARSER_TPU_FAULT_SEED": "42",
        },
        scenario_drain,
    ),
]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="chaos_sweep")
    parser.add_argument("--only", help="run a single scenario by name")
    parser.add_argument(
        "--keep-logs", action="store_true",
        help="keep child logs even for passing scenarios",
    )
    args = parser.parse_args(argv)

    rows = []
    failed = 0
    for name, flags, env, check in SCENARIOS:
        if args.only and name != args.only:
            continue
        t0 = time.monotonic()
        srv = Server(name, flags, env)
        try:
            srv.wait_ready()
            check(srv)
            if name != "drain":  # drain stops (and asserts on) itself
                srv.stop()
            rows.append((name, "PASS", time.monotonic() - t0, ""))
            if not args.keep_logs:
                os.unlink(srv.log.name)
        except Exception as exc:  # one row per scenario, keep sweeping
            srv.stop()
            failed += 1
            rows.append((name, "FAIL", time.monotonic() - t0,
                         f"{exc} (log: {srv.log.name})"))

    width = max(len(r[0]) for r in rows) if rows else 8
    print(f"\n{'scenario':<{width}}  result  seconds  detail")
    for name, result, secs, detail in rows:
        print(f"{name:<{width}}  {result:<6}  {secs:7.1f}  {detail}")
    print(f"\n{len(rows) - failed}/{len(rows)} scenarios passed (seed 42)")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
