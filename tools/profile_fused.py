"""Phase/subcomponent profiler for the fused device program (VERDICT r2 #2).

Times, on the current JAX platform:

- corpus ingest (host) and input upload (host->device transfer);
- the match cube alone vs the full fused step (cube + factor extraction +
  record compaction) — the difference is the extraction/compaction cost;
- output readback (device->host transfer of the record buffers) —
  through the axon tunnel each array is its own round-trip, so this
  isolates the per-request latency floor;
- pair-stride (2 bytes/step) vs single-stride (1 byte/step) DFA scans;
- engine.analyze() end-to-end with the PhaseTrace breakdown.

Usage:
    python tools/profile_fused.py [--lines 200000] [--synthetic-patterns 0]
                                  [--trace /tmp/jaxtrace]

With --synthetic-patterns N, a generated N-regex library (bench_bank's
shape) replaces the builtin one.  With --trace DIR, the steady-state
analyze() runs under jax.profiler.trace for TensorBoard/xprof reading.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

import numpy as np

# make the repo root importable without touching PYTHONPATH (overriding
# PYTHONPATH would drop /root/.axon_site and with it the TPU plugin)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, n=5, warmup=1):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts), statistics.median(ts)


def build_corpus(n: int) -> str:
    import bench

    return bench.build_corpus(n)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lines", type=int, default=200_000)
    ap.add_argument("--synthetic-patterns", type=int, default=0)
    ap.add_argument("--trace", default=None)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.native.ingest import Corpus
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.runtime import AnalysisEngine

    report: dict = {"platform": jax.devices()[0].platform, "lines": args.lines}

    if args.synthetic_patterns:
        import bench_bank

        sets = [bench_bank.synth_library(args.synthetic_patterns)]
        report["patterns"] = args.synthetic_patterns
    else:
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

        sets = load_builtin_pattern_sets()
        report["patterns"] = sum(len(s.patterns or []) for s in sets)

    logs = build_corpus(args.lines)
    engine = AnalysisEngine(sets, ScoringConfig())
    data = PodFailureData(pod={"metadata": {"name": "prof"}}, logs=logs)

    # ---- ingest ---------------------------------------------------------
    t_min, t_med = timeit(lambda: Corpus(logs), n=args.repeats)
    report["ingest_s"] = round(t_min, 4)
    corpus = Corpus(logs)
    enc = corpus.encoded
    B, T = enc.u8.shape
    report["batch_rows"] = B
    report["batch_cols"] = T

    # ---- input upload ---------------------------------------------------
    def upload():
        jax.block_until_ready(jax.device_put(enc.u8))

    t_min, _ = timeit(upload, n=args.repeats)
    report["upload_s"] = round(t_min, 4)
    report["upload_mb"] = round(enc.u8.nbytes / 1e6, 1)

    # ---- cube alone vs full step ---------------------------------------
    matchers = engine.matchers
    report["tiers"] = {
        "dfa_cols": len(matchers.dfa_cols),
        "shiftor_cols": len(matchers.shiftor_cols),
        "bitglush_cols": len(matchers.bitglush_cols),
        "bitglush_words": matchers.bitglush.n_words if matchers.bitglush else 0,
        "multi_groups": len(matchers.multi_groups),
        "multi_cols": len(matchers.multi_cols),
        "prefilter_cols": len(matchers.prefilter_cols),
        "host_cols": len(matchers.host_cols),
    }
    lines_tb = jnp.asarray(enc.u8.T)
    lens = jnp.asarray(enc.lengths)
    jax.block_until_ready((lines_tb, lens))

    cube_jit = jax.jit(lambda lt, ln: matchers.cube(lt, ln))

    def run_cube():
        jax.block_until_ready(cube_jit(lines_tb, lens))

    t_min, _ = timeit(run_cube, n=args.repeats)
    report["cube_s"] = round(t_min, 4)

    fused = engine.fused
    ladder, _cap = fused.k_ladder(enc.u8, engine._k_hint)
    K = ladder[0]
    report["k_bucket"] = K

    def run_step_nosync():
        return fused.dispatch(K, enc.u8, enc.lengths, corpus.n_lines)

    def run_step():
        jax.block_until_ready(run_step_nosync())

    t_min, _ = timeit(run_step, n=args.repeats)
    report["fused_step_s"] = round(t_min, 4)

    # ---- output readback (the per-request transfer floor) ---------------
    out = run_step_nosync()
    jax.block_until_ready(out)
    out_arrays = out if isinstance(out, (tuple, list)) else (out,)

    def readback():
        for o in out_arrays:
            np.asarray(o)

    t_min, _ = timeit(readback, n=args.repeats)
    report["readback_s"] = round(t_min, 4)
    report["readback_arrays"] = len(out_arrays)
    report["readback_kb"] = round(
        sum(np.asarray(o).nbytes for o in out_arrays) / 1e3, 1
    )

    # ---- stride A/B -----------------------------------------------------
    m1 = MatcherBanks(engine.bank, stride=1)
    cube1_jit = jax.jit(lambda lt, ln: m1.cube(lt, ln))

    def run_cube1():
        jax.block_until_ready(cube1_jit(lines_tb, lens))

    t_min, _ = timeit(run_cube1, n=args.repeats)
    report["cube_stride1_s"] = round(t_min, 4)

    # ---- end-to-end analyze with phase trace ----------------------------
    engine.analyze(data)  # warm

    def run_analyze():
        engine.analyze(data)

    if args.trace:
        with jax.profiler.trace(args.trace):
            run_analyze()
        report["trace_dir"] = args.trace
    t_min, _ = timeit(run_analyze, n=max(2, args.repeats - 2))
    report["analyze_s"] = round(t_min, 4)
    report["analyze_lines_per_s"] = round(args.lines / t_min, 1)
    report["phases_s"] = {
        k: round(v, 4) for k, v in (engine.last_trace.as_dict() or {}).items()
    }

    print(json.dumps(report))


if __name__ == "__main__":
    main()
