"""Deterministic fleet-simulation sweep: N seeded multi-fault schedules
through the in-process simulator (log_parser_tpu/sim/), invariants
SIM-I1..I5 checked after every op — docs/OPS.md "Deterministic fleet
simulation".

Each seed expands into a schedule of fleet ops (serve traffic, crash at
record boundaries, partition/drop/dup/defer the replication transport,
ENOSPC, clock pause/skew, kill/revive) against a whole fleet — router,
two backends, warm standby, migration + failover supervisors — in ONE
process under a virtual clock.  Determinism is exact: the same seed
always produces the same event log, so a failing row's digest reproduces
bit-identically with ``--replay`` and ``--minimize`` shrinks it to the
shortest schedule that still violates.

Usage:
  python tools/sim_sweep.py --seeds 200                 # campaign
  python tools/sim_sweep.py --seeds 200 --json out.json # + artifact
  python tools/sim_sweep.py --replay 137                # one seed, verbose
  python tools/sim_sweep.py --replay 137 --minimize     # shrink it
  python tools/sim_sweep.py --seeds 100 --bug-flag \\
      LOG_PARSER_TPU_SIM_BUG_FORWARD_RESURRECTION       # rediscovery drill

Exit status: 0 when every seed passed, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from log_parser_tpu.sim.harness import (  # noqa: E402
    minimize,
    run_schedule,
    run_seed,
)
from log_parser_tpu.sim.schedule import generate_schedule  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="sim_sweep")
    parser.add_argument(
        "--seeds", type=int, default=50, metavar="N",
        help="sweep seeds [--start, --start+N) (default: 50)",
    )
    parser.add_argument(
        "--start", type=int, default=0,
        help="first seed of the campaign (default: 0)",
    )
    parser.add_argument(
        "--ops", type=int, default=40,
        help="schedule length per seed (default: 40)",
    )
    parser.add_argument(
        "--replay", type=int, metavar="SEED",
        help="run ONE seed and print its schedule, events and digest",
    )
    parser.add_argument(
        "--minimize", action="store_true",
        help="with --replay: shrink a failing schedule to the shortest"
        " reproduction and print it",
    )
    parser.add_argument(
        "--bug-flag", action="append", default=[], metavar="ENV",
        help="set this env flag inside the simulated fleet (repeatable;"
        " the LOG_PARSER_TPU_SIM_BUG_* guards re-introduce fixed"
        " historical bugs for rediscovery drills)",
    )
    parser.add_argument(
        "--json", metavar="PATH",
        help="write the campaign result as a JSON artifact",
    )
    args = parser.parse_args(argv)
    bug_env = {flag: "1" for flag in args.bug_flag}

    if args.replay is not None:
        res = run_seed(args.replay, n_ops=args.ops, bug_env=bug_env or None)
        print(f"seed {args.replay}: {'PASS' if res.ok else 'FAIL'}"
              f"  digest {res.digest[:16]}…")
        for i, op in enumerate(res.schedule):
            marker = " <- first violation" if res.failed_at == i else ""
            print(f"  [{i:2d}] {tuple(op)}{marker}")
        for v in res.violations:
            print(f"  VIOLATION {v}")
        if not res.ok and args.minimize:
            small = minimize(
                generate_schedule(args.replay, args.ops),
                bug_env=bug_env or None,
            )
            rerun = run_schedule(small, bug_env=bug_env or None)
            print(f"minimized {len(res.schedule)} -> {len(small)} ops:")
            for op in small:
                print(f"  {tuple(op)}")
            for v in rerun.violations:
                print(f"  VIOLATION {v}")
        return 0 if res.ok else 1

    t0 = time.monotonic()
    rows = []
    failed = 0
    for seed in range(args.start, args.start + args.seeds):
        res = run_seed(seed, n_ops=args.ops, bug_env=bug_env or None)
        rows.append(res.to_dict())
        if not res.ok:
            failed += 1
            print(f"seed {seed}: FAIL at op {res.failed_at}"
                  f" — {res.violations[0]}")
    elapsed = time.monotonic() - t0
    print(f"{args.seeds - failed}/{args.seeds} seeds passed"
          f" ({args.ops} ops each) in {elapsed:.1f}s")
    if failed:
        first = next(r for r in rows if not r["ok"])
        print(f"reproduce: python tools/sim_sweep.py"
              f" --replay {first['seed']} --ops {args.ops} --minimize"
              + "".join(f" --bug-flag {f}" for f in args.bug_flag))
    if args.json:
        artifact = {
            "tool": "sim_sweep",
            "start": args.start,
            "seeds": args.seeds,
            "ops": args.ops,
            "bug_flags": sorted(bug_env),
            "passed": args.seeds - failed,
            "failed": failed,
            "elapsed_s": round(elapsed, 2),
            "results": rows,
        }
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
