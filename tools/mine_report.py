#!/usr/bin/env python3
"""Offline template-mining report CLI.

Runs the online miner's tokenizer + clusterer (log_parser_tpu/mining/)
over log files WITHOUT an engine or a serving process: the same
Logram-style token-position templates the live miner would grow from
the line-cache miss stream, reported as a table (or candidate YAML) so
an operator can preview what ``--miner`` would mine from a corpus
before turning it on — or mine a cold corpus that never hits a server.

Usage:
  python tools/mine_report.py FILE [FILE...]       # log files
  cat app.log | python tools/mine_report.py -      # stdin
  ... --min-support 20                             # promotion threshold
  ... --yaml                                       # candidate YAML for
                                                   # promotable clusters
  ... --json                                       # machine-readable

Exit codes: 0 = ran (even with zero clusters); 2 = a path could not be
read.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from log_parser_tpu.mining.synthesize import (  # noqa: E402
    candidate_yaml,
    synthesize,
    template_regex,
)
from log_parser_tpu.mining.templates import (  # noqa: E402
    TemplateClusterer,
    template_id,
)


def _feed(clusterer: TemplateClusterer, stream) -> int:
    n = 0
    for raw in stream:
        line = raw.rstrip(b"\r\n")
        if not line.strip():
            continue
        clusterer.observe(line)
        n += 1
    return n


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="mine_report")
    parser.add_argument(
        "paths", nargs="+", help="log files, or '-' for stdin"
    )
    parser.add_argument(
        "--min-support", type=int, default=8,
        help="miss lines a cluster must absorb to be promotable "
        "(the live miner's --miner-min-support; default 8)",
    )
    parser.add_argument(
        "--top", type=int, default=40,
        help="clusters to show, by support (default 40)",
    )
    parser.add_argument(
        "--yaml", action="store_true",
        help="emit candidate PatternSet YAML for every promotable "
        "cluster (what the live miner would park for review)",
    )
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    # stability=0: an offline corpus is one frozen batch — there is no
    # "later pump" for a template to hold still through, so promotability
    # is support alone
    clusterer = TemplateClusterer(
        min_support=args.min_support, stability=0
    )
    lines = 0
    for path in args.paths:
        try:
            if path == "-":
                lines += _feed(clusterer, sys.stdin.buffer)
            else:
                with open(path, "rb") as fh:
                    lines += _feed(clusterer, fh)
        except OSError as exc:
            print(f"error: {path}: {exc}", file=sys.stderr)
            return 2

    # promotable() applies the live miner's full promotion rule (support,
    # stability, a probe-worthy fixed token) and marks the clusters, so
    # the snapshot below carries the same promoted flag an operator would
    # see on /trace/last
    promotable = clusterer.promotable()
    clusters = sorted(clusterer.snapshot(), key=lambda c: -c["support"])

    if args.yaml:
        for c in promotable:
            print("---")
            print(candidate_yaml(synthesize(c)), end="")
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "lines": lines,
                    "stats": clusterer.stats(),
                    "clusters": clusters[: args.top],
                    "promotable": [
                        template_id(c.template) for c in promotable
                    ],
                },
                indent=2,
            )
        )
        return 0

    stats = clusterer.stats()
    print(
        f"{lines} lines -> {stats['clusters']} clusters "
        f"({stats['skipped']} skipped, {stats['discarded']} discarded at "
        f"cap); {len(promotable)} promotable at support "
        f">= {args.min_support}"
    )
    for c in clusters[: args.top]:
        mark = "*" if c["promoted"] else " "
        print(f"{mark} {c['support']:8d}  {c['id']}  {c['template']}")
    if promotable:
        print("\npromotable candidate regexes:")
        for c in promotable:
            print(f"  {template_id(c.template)}  {template_regex(c.template)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
