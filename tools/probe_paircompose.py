"""A/B the Shift-Or scan-step formulations in isolation on the live
backend: per-byte takes (round-3 shipping form), byte-pair table,
class-pair table, and ablations (no intermediate-hit half, no class
indirection). Each variant is its own jitted scan over the config-2
corpus; prints one JSON line. PERF.md §9 methodology.

Usage: python tools/probe_paircompose.py [--lines 200000] [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_common import timeit  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lines", type=int, default=200_000)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.native.ingest import Corpus
    from log_parser_tpu.ops.match import pack_byte_pairs
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    engine = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
    s = engine.matchers.shiftor
    mask_np = s._np["mask"]
    sc_np = s._np["start_clear"]
    e_np = s._np["end_mask"]
    W = s.n_words

    corpus = Corpus(bench.build_corpus(args.lines))
    enc = corpus.encoded
    lines_tb = jnp.asarray(enc.u8.T)
    lens = jnp.asarray(enc.lengths)
    jax.block_until_ready((lines_tb, lens))
    B = int(lens.shape[0])
    report = {
        "platform": jax.devices()[0].platform,
        "rows": B,
        "T": int(lines_tb.shape[0]),
        "W": W,
    }

    mask = jnp.asarray(mask_np)
    sc = jnp.asarray(sc_np)
    e = jnp.asarray(e_np)
    zero = jnp.uint32(0)

    def scan_of(step, init):
        @jax.jit
        def run(lines_tb, lens):
            pairs, ts = pack_byte_pairs(lines_tb)
            out, _ = jax.lax.scan(
                lambda c, xs: (step(c, xs[0][0], xs[0][1], xs[1]), None),
                init,
                (pairs, ts),
            )
            return out

        return lambda: jax.block_until_ready(run(lines_tb, lens))

    d0 = jnp.full((B, W), 0xFFFFFFFF, dtype=jnp.uint32)
    h0 = jnp.zeros((B, W), dtype=jnp.uint32)

    # -- v_byte: round-3 shipping form (2 per-byte [256, W] takes) -------
    def one_old(carry, b, pos_ok):
        d, hits = carry
        m = jnp.take(mask, b.astype(jnp.int32), axis=0)
        d_new = ((d << 1) & sc) | m
        active = pos_ok[:, None]
        hits = jnp.where(active, hits | ((~d_new) & e), hits)
        return jnp.where(active, d_new, d), hits

    def step_old(carry, b1, b2, t):
        p0 = 2 * t
        carry = one_old(carry, b1, p0 < lens)
        return one_old(carry, b2, p0 + 1 < lens)

    report["v_byte_s"] = round(timeit(scan_of(step_old, (d0, h0)), args.repeats), 4)

    # -- shared pair-composed ingredients -------------------------------
    sc2 = jnp.asarray((sc_np << np.uint32(1)) & sc_np)
    k = jnp.asarray(~sc_np)
    uniq, cls_np = np.unique(mask_np, axis=0, return_inverse=True)
    C = int(uniq.shape[0])
    report["C"] = C
    m2_u = ((uniq << np.uint32(1)) & sc_np)[:, None, :] | uniq[None, :, :]
    t1_u = np.broadcast_to(((~uniq) & e_np)[:, None, :], m2_u.shape)
    cls = jnp.asarray(cls_np.astype(np.int32))

    def pair_step_from(table, widx):
        """widx(b1, b2, d-carry-aux) -> row index; table rows [2W]."""

        def step(carry, b1, b2, t):
            d, hits = carry
            p0 = 2 * t
            row = jnp.take(table, widx(b1, b2), axis=0)
            m2r, t1r = row[:, :W], row[:, W:]
            hit1 = (~(d << 1) | k) & t1r
            d = ((d << 2) & sc2) | m2r
            hit2 = (~d) & e
            hits = (
                hits
                | jnp.where((p0 < lens)[:, None], hit1, zero)
                | jnp.where((p0 + 1 < lens)[:, None], hit2, zero)
            )
            return d, hits

        return step

    # -- v_clspair: [C^2, 2W] table + class map (measured-slower) -------
    tab_cls = jnp.asarray(
        np.concatenate([m2_u, t1_u], axis=-1).reshape(C * C, 2 * W)
    )
    widx_cls = lambda b1, b2: (
        jnp.take(cls, b1.astype(jnp.int32)) * C
        + jnp.take(cls, b2.astype(jnp.int32))
    )
    report["v_clspair_s"] = round(
        timeit(scan_of(pair_step_from(tab_cls, widx_cls), (d0, h0)), args.repeats), 4
    )

    # -- v_clspair_noT1: same but W-wide rows, final-byte hits only -----
    tab_m2 = jnp.asarray(m2_u.reshape(C * C, W))

    def step_not1(carry, b1, b2, t):
        d, hits = carry
        p0 = 2 * t
        m2r = jnp.take(tab_m2, widx_cls(b1, b2), axis=0)
        d = ((d << 2) & sc2) | m2r
        hits = hits | jnp.where((p0 + 1 < lens)[:, None], (~d) & e, zero)
        return d, hits

    report["v_clspair_noT1_s"] = round(
        timeit(scan_of(step_not1, (d0, h0)), args.repeats), 4
    )

    # -- v_2take_precls: two independent [C, 2W] takes, compose on device
    tab_1 = jnp.asarray(
        np.concatenate([((uniq << np.uint32(1)) & sc_np), (~uniq) & e_np], axis=-1)
    )  # [C, 2W] : shifted mask | T1
    tab_2 = jnp.asarray(uniq)  # [C, W]

    def step_2take(carry, b1, b2, t):
        d, hits = carry
        p0 = 2 * t
        r1 = jnp.take(tab_1, jnp.take(cls, b1.astype(jnp.int32)), axis=0)
        m1s, t1r = r1[:, :W], r1[:, W:]
        m2r = jnp.take(tab_2, jnp.take(cls, b2.astype(jnp.int32)), axis=0)
        hit1 = (~(d << 1) | k) & t1r
        d = ((d << 2) & sc2) | m1s | m2r
        hit2 = (~d) & e
        hits = (
            hits
            | jnp.where((p0 < lens)[:, None], hit1, zero)
            | jnp.where((p0 + 1 < lens)[:, None], hit2, zero)
        )
        return d, hits

    report["v_2take_precls_s"] = round(
        timeit(scan_of(step_2take, (d0, h0)), args.repeats), 4
    )

    # -- v_2take_byte: same composition, [256, 2W] tables, no class map
    tab_1b = jnp.asarray(
        np.concatenate(
            [((mask_np << np.uint32(1)) & sc_np), (~mask_np) & e_np], axis=-1
        )
    )

    def step_2tb(carry, b1, b2, t):
        d, hits = carry
        p0 = 2 * t
        r1 = jnp.take(tab_1b, b1.astype(jnp.int32), axis=0)
        m1s, t1r = r1[:, :W], r1[:, W:]
        m2r = jnp.take(mask, b2.astype(jnp.int32), axis=0)
        hit1 = (~(d << 1) | k) & t1r
        d = ((d << 2) & sc2) | m1s | m2r
        hit2 = (~d) & e
        hits = (
            hits
            | jnp.where((p0 < lens)[:, None], hit1, zero)
            | jnp.where((p0 + 1 < lens)[:, None], hit2, zero)
        )
        return d, hits

    report["v_2take_byte_s"] = round(
        timeit(scan_of(step_2tb, (d0, h0)), args.repeats), 4
    )

    print(json.dumps(report))


if __name__ == "__main__":
    main()
