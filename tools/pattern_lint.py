#!/usr/bin/env python3
"""Pattern-library static analyzer CLI.

Lints a pattern library WITHOUT building an engine (log_parser_tpu/
analysis/): YAML schema hygiene, ReDoS shapes on the host fallback path,
device-tier prediction with the build's own reason codes, prefilter
quality, cross-pattern subsumption. The same pass gates ``/patterns/
reload`` under ``--lint-patterns=block`` (docs/OPS.md) and hygiene
check 10 runs it over the builtin bank.

Usage:
  python tools/pattern_lint.py PATH [PATH...]   # files and/or directories
  python tools/pattern_lint.py --builtin        # the builtin bank
  ... --json                                    # machine-readable report

Exit codes: 0 = no gating (error/warn) findings; 1 = gating findings;
2 = a path could not be loaded at all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import yaml  # noqa: E402

from log_parser_tpu.analysis import lint_pattern_sets  # noqa: E402
from log_parser_tpu.models.pattern import PatternSet  # noqa: E402
from log_parser_tpu.patterns.loader import _walk_yaml_files  # noqa: E402

BUILTIN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "log_parser_tpu", "patterns", "builtin",
)


def _load_sets(paths: list[str]) -> list[PatternSet]:
    """Parse sets WITHOUT the loader's validation — lint reports schema
    violations as findings instead of refusing to look at the file."""
    files: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            files.extend(_walk_yaml_files(path))
        else:
            files.append(path)
    sets = []
    for path in files:
        with open(path, "r", encoding="utf-8") as fh:
            data = yaml.safe_load(fh)
        if not isinstance(data, dict):
            raise ValueError(f"{path}: not a YAML mapping")
        sets.append(PatternSet.from_dict(data))
    return sets


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="pattern YAML files/directories")
    ap.add_argument(
        "--builtin", action="store_true",
        help="lint the builtin pattern bank",
    )
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument(
        "--no-subsumption", action="store_true",
        help="skip the product-DFA subsumption pass",
    )
    args = ap.parse_args(argv)

    paths = list(args.paths)
    if args.builtin:
        paths.append(BUILTIN_DIR)
    if not paths:
        ap.error("no paths given (or use --builtin)")
    try:
        sets = _load_sets(paths)
    except Exception as exc:  # unreadable/unparseable input: usage error
        print(f"pattern_lint: cannot load library: {exc}", file=sys.stderr)
        return 2

    report = lint_pattern_sets(
        sets, check_subsumption=not args.no_subsumption
    )
    if args.json:
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        for f in report.findings:
            where = "/".join(x for x in (f.set_id, f.pattern_id) if x)
            rx = f" [{f.regex}]" if f.regex else ""
            code = f" ({f.code})" if f.code else ""
            print(f"{f.severity:5s} {f.rule:28s} {where}: {f.detail}{code}{rx}")
        tiers = {}
        for t in report.tiers.values():
            tiers[t["tier"]] = tiers.get(t["tier"], 0) + 1
        print(
            f"pattern_lint: {report.stats['patterns']} pattern(s), "
            f"{report.stats['columns']} column(s), tiers {tiers}, "
            f"{report.summary()}"
        )
    return 1 if report.gating else 0


if __name__ == "__main__":
    sys.exit(main())
