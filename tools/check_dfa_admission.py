"""Admission oracle: does the builtin bank admit to the Pallas DFA kernel?

PR 8's union-DFA kernel refused the builtin bank for five rounds
(13.1 MB of raw transition planes vs the 12 MB VMEM budget, PERF.md
§12) and nothing pinned that regression — the kernel tier could only be
observed refusing at runtime. This tool IS the pin: it packs the
builtin pattern bank's union groups exactly as MatcherBanks does
(native builder when available, python subset construction otherwise),
runs ``build_dfa_plan`` with per-group entries under the production
VMEM budget, prints one JSON verdict (reason + plane geometry), and
exits nonzero unless the plan admits (REASONS ``byte_classed`` /
``split``). Hygiene check 15 runs it on every full scan and
tests/test_matchdfa_pallas.py pins it as a slow test.

The python union pack costs ~2 minutes cold on a native-less host, so
the MINIMIZED packed groups are cached under the shared cache tree
(``~/.cache/log_parser_tpu/union``, honoring ``LOG_PARSER_TPU_CACHE``)
keyed on the compiler version + the exact column entries; warm runs
take seconds (the admission split itself re-runs every time — it is
the thing under test). ``--force`` ignores the cache.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _builtin_entries() -> list[tuple[int, str, bool]]:
    """(column index, regex, case_insensitive) for every regex column of
    the builtin bank — the same candidate set MatcherBanks offers the
    union tier (tools/probe_kernels.py uses the identical rebuild)."""
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    engine = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
    return [
        (i, c.regex, c.case_insensitive)
        for i, c in enumerate(engine.matchers.bank.columns)
        if getattr(c, "regex", None)
    ]


def _cache_file(key: str):
    from log_parser_tpu.patterns.regex.cache import cache_subdir

    d = cache_subdir("union")
    return None if d is None else d / f"admission-{key}.npz"


def _save_groups(path, groups) -> None:
    from log_parser_tpu.patterns.regex.cache import atomic_publish

    arrs: dict[str, np.ndarray] = {"n_groups": np.int64(len(groups))}
    for gi, (keys, md) in enumerate(groups):
        arrs[f"g{gi}_keys"] = np.asarray(keys, np.int64)
        arrs[f"g{gi}_trans"] = md.trans
        arrs[f"g{gi}_byte_class"] = md.byte_class
        arrs[f"g{gi}_cls_is_word"] = md.cls_is_word
        arrs[f"g{gi}_out2"] = md.out2
        arrs[f"g{gi}_accept_words"] = md.accept_words
        arrs[f"g{gi}_start"] = np.int64(md.start)
        arrs[f"g{gi}_unmin"] = np.int64(md.n_states_unmin)
    atomic_publish(path.parent, path.name, lambda f: np.savez(f, **arrs))


def _load_groups(path):
    from log_parser_tpu.patterns.regex.multidfa import CompiledMultiDfa

    try:
        with np.load(path) as z:
            out = []
            for gi in range(int(z["n_groups"])):
                keys = [int(k) for k in z[f"g{gi}_keys"]]
                trans = z[f"g{gi}_trans"]
                md = CompiledMultiDfa(
                    trans=trans,
                    byte_class=z[f"g{gi}_byte_class"],
                    cls_is_word=z[f"g{gi}_cls_is_word"],
                    out2=z[f"g{gi}_out2"],
                    accept_words=z[f"g{gi}_accept_words"],
                    start=int(z[f"g{gi}_start"]),
                    n_states=trans.shape[0],
                    n_classes=trans.shape[1],
                    n_patterns=len(keys),
                    n_words=z[f"g{gi}_out2"].shape[1],
                    n_states_unmin=int(z[f"g{gi}_unmin"]),
                )
                out.append((keys, md))
            return out
    except Exception:
        return None  # corrupt/stale cache: rebuild (never wrong)


def run_admission(budget: int | None = None, force: bool = False) -> dict:
    """Pack (or load) the builtin union groups and adjudicate kernel
    admission. Returns the JSON-able verdict dict."""
    from log_parser_tpu.ops.match import MatcherBanks, MultiDfaBank
    from log_parser_tpu.ops.matchdfa_pallas import ADMITTED, build_dfa_plan
    from log_parser_tpu.patterns.regex.cache import COMPILER_VERSION
    from log_parser_tpu.patterns.regex.multidfa import pack_union_groups

    t0 = time.time()
    entries = _builtin_entries()
    max_states = MatcherBanks.MULTI_STATE_BUDGET
    max_group = MatcherBanks.MULTI_MAX_GROUP
    h = hashlib.sha256()
    h.update(f"v{COMPILER_VERSION}|ms={max_states}|mg={max_group}".encode())
    for i, rx, ci in entries:
        h.update(f"|{i}|{int(ci)}|{rx}".encode())
    path = _cache_file(h.hexdigest()[:24])
    groups = None
    if not force and path is not None and path.exists():
        groups = _load_groups(path)
    cached = groups is not None
    if groups is None:
        groups, _rejected = pack_union_groups(
            entries, max_states=max_states, max_group=max_group
        )
        if path is not None:
            _save_groups(path, groups)
    emap = {e[0]: e for e in entries}
    banks = [MultiDfaBank(md, keys) for keys, md in groups]
    group_entries = [[emap[k] for k in keys] for keys, _ in groups]
    plan, reason = build_dfa_plan(
        banks, budget=budget, entries=group_entries, max_states=max_states
    )
    return {
        "reason": reason,
        "admitted": reason in ADMITTED,
        "geometry": None if plan is None else plan.geometry,
        "regexColumns": len(entries),
        "unionPackCached": cached,
        "elapsedS": round(time.time() - t0, 2),
    }


def main() -> None:
    ap = argparse.ArgumentParser(
        description="builtin-bank Pallas DFA kernel admission verdict"
    )
    ap.add_argument(
        "--force",
        action="store_true",
        help="ignore the cached union pack and rebuild from the regexes",
    )
    ap.add_argument(
        "--budget",
        type=int,
        default=None,
        help="VMEM budget override in bytes (default: production 12 MB)",
    )
    args = ap.parse_args()
    report = run_admission(budget=args.budget, force=args.force)
    print(json.dumps(report))
    sys.exit(0 if report["admitted"] else 1)


if __name__ == "__main__":
    main()
