"""Session-matched A/B: XLA bitglush stepper vs the Pallas kernel on the
CURRENT (chainless, caret-guarded) bank shape — VERDICT r4 #6 asks the
kernel to earn default status on this bank or be deleted with a recorded
negative.  The round-4 parity verdict (0.197 vs 0.198 s, PERF.md §9) was
measured on the old chained 74-word bank; the chainless carry-free
stepper moved the goalposts (0.064 s at W=88 in tools/probe_chainless.py),
so the kernel's serial-latency floor must be re-priced against a much
faster baseline.

Run on a LIVE TPU session (one process, nothing concurrent — PERF.md §10):

    nohup python tools/probe_pallas_ab.py > /tmp/pallas_ab.out 2>&1 &

Two compiles total (one per variant), well inside relay etiquette.
Prints one JSON line with both times, bit-equality, and the ratio.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_common import timeit  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lines", type=int, default=200_000)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.native.ingest import Corpus
    from log_parser_tpu.ops.match import pack_byte_pairs
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    engine = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
    bank = engine.matchers.bitglush
    if bank is None:
        sys.exit("no bitglush bank under the current tier policy "
                 "(force it like tests do, or run on the TPU policy)")
    corpus = Corpus(bench.build_corpus(args.lines))
    enc = corpus.encoded
    lines_tb = jnp.asarray(enc.u8.T)
    lens = jnp.asarray(enc.lengths)
    jax.block_until_ready((lines_tb, lens))
    B = int(lens.shape[0])

    report = {
        "platform": jax.devices()[0].platform,
        "rows": B,
        "T": int(lines_tb.shape[0]),
        "n_words": bank.n_words,
        "has_chains": bool(bank.has_chains),
        "use_sinks": bool(bank.use_sinks),
    }

    # XLA scan path: the bank's own pair stepper alone in one lax.scan
    # (exact probe_tiers.py methodology, so numbers line up with its
    # bitglush_s row)
    stepper = bank.pair_stepper(B, lens)

    @jax.jit
    def xla_scan(lines_tb, lens):
        pairs, ts = pack_byte_pairs(lines_tb)

        def step(carry, xs):
            pair, t = xs
            return stepper[1](carry, pair[0], pair[1], t), None

        final, _ = jax.lax.scan(step, stepper[0], (pairs, ts))
        return final

    out = xla_scan(lines_tb, lens)
    jax.block_until_ready(out)
    report["xla_stepper_s"] = round(
        timeit(lambda: jax.block_until_ready(xla_scan(lines_tb, lens)),
               n=args.repeats), 4
    )

    from log_parser_tpu.ops.bitglush_pallas import (
        bitglush_hits_pallas,
        pick_tile,
    )

    if pick_tile(B) is None:
        report["pallas_s"] = None
        report["note"] = "no valid pallas tile for this batch size"
        print(json.dumps(report))
        return

    @jax.jit
    def pallas_scan(lines_tb, lens):
        return bitglush_hits_pallas(bank, lines_tb, lens)

    phits = pallas_scan(lines_tb, lens)
    jax.block_until_ready(phits)
    report["pallas_s"] = round(
        timeit(lambda: jax.block_until_ready(pallas_scan(lines_tb, lens)),
               n=args.repeats), 4
    )
    # verdict basis: per-column results must agree (the stepper's carry
    # layout differs from the kernel's hits array — and may be sink-mode
    # on CPU policy — so compare through the bank's own column readers:
    # finish(final_carry) and columns_from_hits both yield [B, n_cols])
    cols_xla = np.asarray(stepper[2](out))
    cols_pallas = np.asarray(bank.columns_from_hits(phits))
    report["bit_equal"] = bool(np.array_equal(cols_xla, cols_pallas))
    report["pallas_over_xla"] = round(
        report["pallas_s"] / report["xla_stepper_s"], 3
    )
    print(json.dumps(report))


if __name__ == "__main__":
    main()
