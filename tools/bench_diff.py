"""Diff two bench artifacts (``BENCH_r*.json`` / ``bench_results/*.json``)
and render a per-metric verdict table.

The refresh loop (tools/refresh_artifacts.sh) stamps one JSON artifact
per bench stem; the PR ladder keeps one ``BENCH_r<NN>.json`` per round.
Both carry the same envelope — ``{"n", "cmd", "rc", "parsed": {...}}`` —
but different stems expose different metric blocks (the rr90 headline has
``line_cache``/``boot_seconds``; the stream stem has ``ttfd_ms``; the
earliest rounds have nothing but ``value``). This tool diffs whatever the
TWO artifacts share and says nothing about the rest, so any OLD/NEW pair
of the same stem compares cleanly:

    python tools/bench_diff.py BENCH_r13.json BENCH_r14.json
    python tools/bench_diff.py bench_results/config2_rr90_lc64_cpu.json \
        /tmp/fresh.json --threshold 3 --json

Direction is inferred per metric: ``*_per_sec`` and hit counters are
higher-is-better; ``*_ms`` / ``*_seconds`` / miss counters are
lower-is-better. A delta inside ``--threshold`` percent is ``ok``
(within noise); outside it the row reads ``improved`` or ``regressed``.

Exit code is 0 unless ``--strict`` is given, in which case any
``regressed`` row exits 1 — the refresh script runs this advisorily
(a slow machine is not a broken bench), CI may opt into --strict.

Bench honesty: artifacts stamped by ``bench_common.emit`` carry a
``host_load`` block (``os.getloadavg()`` + cpu count). When the two
sides ran under per-cpu load that differs by more than 2x, every
verdict here is comparing machine weather, not code — the diff still
prints, but it is marked advisory-untrustworthy (``load_advisory`` in
the JSON summary, a warning banner in the table) and ``--strict``
ignores regressions from such a pair.
"""

from __future__ import annotations

import argparse
import json
import sys

# (dotted path under parsed, higher_is_better). Paths absent from either
# artifact are skipped — the table only ever shows shared metrics.
SCALAR_ROWS = (
    ("value", None),  # direction inferred from parsed.metric
    ("serial_lines_per_sec", True),
    ("boot_seconds", False),
    ("ttfd_over_blob_p50", False),
    ("ttfd_misses", False),
    ("line_cache.hits", True),
    ("line_cache.misses", False),
    ("line_cache.evictions", False),
    ("line_cache.residentBytes", False),
    ("compile_cache.compileHits", True),
    ("compile_cache.compileMisses", False),
)

# lower-is-better name fragments, for parsed.metric and curve columns
_LOWER_HINTS = ("ttfd", "_ms", "_seconds", "latency", "p50", "p99")


def load_parsed(path: str) -> dict:
    """Return the ``parsed`` block; tolerate a bare parsed-level dict so
    a bench's raw stdout line diffs as well as the stamped envelope."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def _dig(d: dict, dotted: str):
    cur = d
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) else None


def _verdict(old: float, new: float, higher_better: bool, threshold: float):
    """(pct_delta, verdict) — pct is signed NEW-vs-OLD in the metric's own
    direction (positive = better), so the table reads uniformly."""
    if old == 0:
        return (None, "ok" if new == 0 else "changed")
    raw = (new - old) / abs(old) * 100.0
    pct = raw if higher_better else -raw
    if abs(pct) <= threshold:
        return (pct, "ok")
    return (pct, "improved" if pct > 0 else "regressed")


def diff(old: dict, new: dict, threshold: float) -> list[dict]:
    rows = []
    for dotted, higher in SCALAR_ROWS:
        a, b = _dig(old, dotted), _dig(new, dotted)
        if a is None or b is None:
            continue
        if higher is None:
            metric = str(new.get("metric") or old.get("metric") or "")
            higher = not any(h in metric for h in _LOWER_HINTS)
            dotted = f"value ({metric})" if metric else dotted
        pct, verdict = _verdict(a, b, higher, threshold)
        rows.append({"metric": dotted, "old": a, "new": b,
                     "pct": pct, "verdict": verdict})
    # ttfd_ms block (stream stem): percentile dict, lower is better
    ot, nt = old.get("ttfd_ms"), new.get("ttfd_ms")
    if isinstance(ot, dict) and isinstance(nt, dict):
        for q in sorted(set(ot) & set(nt)):
            if isinstance(ot[q], (int, float)) and isinstance(nt[q], (int, float)):
                pct, verdict = _verdict(ot[q], nt[q], False, threshold)
                rows.append({"metric": f"ttfd_ms.{q}", "old": ot[q],
                             "new": nt[q], "pct": pct, "verdict": verdict})
    # throughput curve: match rows on concurrency; unmatched rows are
    # dropped (a curve re-shaped between rounds is not a regression)
    oc = {r.get("concurrency"): r for r in old.get("throughput_curve") or []}
    nc = {r.get("concurrency"): r for r in new.get("throughput_curve") or []}
    for c in sorted(set(oc) & set(nc) - {None}):
        for col, higher in (("lines_per_sec", True), ("p50_ms", False),
                            ("p99_ms", False)):
            a, b = oc[c].get(col), nc[c].get(col)
            if isinstance(a, (int, float)) and isinstance(b, (int, float)):
                pct, verdict = _verdict(a, b, higher, threshold)
                rows.append({"metric": f"curve[c={c}].{col}", "old": a,
                             "new": b, "pct": pct, "verdict": verdict})
    return rows


# per-cpu load below this is idle-box noise; ratios of two near-zero
# loads say nothing about comparability
_LOAD_FLOOR = 0.05
_LOAD_RATIO_LIMIT = 2.0


def load_advisory(old: dict, new: dict) -> dict | None:
    """None when the two artifacts ran under comparable host load (or
    either side predates the ``host_load`` stamp); otherwise a dict
    naming the imbalance — the caller marks the whole diff advisory."""

    def norm(doc):
        h = doc.get("host_load")
        if not isinstance(h, dict):
            return None
        la, cpus = h.get("loadavg"), h.get("cpus")
        if not isinstance(la, (list, tuple)) or not la:
            return None
        try:
            return max(float(la[0]), 0.0) / max(int(cpus or 1), 1)
        except (TypeError, ValueError):
            return None

    a, b = norm(old), norm(new)
    if a is None or b is None:
        return None
    lo, hi = sorted((max(a, _LOAD_FLOOR), max(b, _LOAD_FLOOR)))
    ratio = hi / lo
    if ratio <= _LOAD_RATIO_LIMIT:
        return None
    return {
        "old_load_per_cpu": round(a, 3),
        "new_load_per_cpu": round(b, 3),
        "ratio": round(ratio, 2),
        "limit": _LOAD_RATIO_LIMIT,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two bench artifacts with a +/-threshold verdict")
    ap.add_argument("old", help="baseline artifact (JSON)")
    ap.add_argument("new", help="candidate artifact (JSON)")
    ap.add_argument("--threshold", type=float, default=3.0, metavar="PCT",
                    help="noise band in percent (default 3)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the row list as JSON instead of a table")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 if any row regressed (default: advisory)")
    args = ap.parse_args(argv)

    try:
        old, new = load_parsed(args.old), load_parsed(args.new)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"bench_diff: cannot load artifacts: {exc}", file=sys.stderr)
        return 2
    rows = diff(old, new, args.threshold)
    regressed = sum(1 for r in rows if r["verdict"] == "regressed")
    improved = sum(1 for r in rows if r["verdict"] == "improved")
    advisory = load_advisory(old, new)
    summary = {"rows": rows, "compared": len(rows), "regressed": regressed,
               "improved": improved, "threshold_pct": args.threshold,
               "old": args.old, "new": args.new,
               "load_advisory": advisory,
               "trustworthy": advisory is None}

    if args.as_json:
        print(json.dumps(summary, indent=2))
    elif not rows:
        print("bench_diff: no shared numeric metrics between the two "
              "artifacts (different stems?)")
    else:
        w = max(len(r["metric"]) for r in rows)
        print(f"{'metric':<{w}}  {'old':>14}  {'new':>14}  {'delta':>9}  verdict")
        for r in rows:
            pct = "n/a" if r["pct"] is None else f"{r['pct']:+8.2f}%"
            print(f"{r['metric']:<{w}}  {r['old']:>14,.1f}  "
                  f"{r['new']:>14,.1f}  {pct:>9}  {r['verdict']}")
        print(f"-- {len(rows)} compared, {improved} improved, "
              f"{regressed} regressed (threshold ±{args.threshold}%)")
    if advisory is not None:
        print(
            "!! ADVISORY: host load differed "
            f"{advisory['ratio']}x between the two runs "
            f"(old {advisory['old_load_per_cpu']}/cpu, "
            f"new {advisory['new_load_per_cpu']}/cpu, limit "
            f"{advisory['limit']}x) — verdicts above compare machine "
            "weather, not code; re-run on a quiet host before trusting "
            "them",
            file=sys.stderr,
        )
    return 1 if (args.strict and regressed and advisory is None) else 0


if __name__ == "__main__":
    sys.exit(main())
