#!/usr/bin/env python3
"""Repo hygiene gate — the Spotless analogue (reference: pom.xml:82-105
enforces AOSP format at verify; .pre-commit-config.yaml runs
whitespace/EOF/YAML hooks). This repo's gate is implemented with the
stdlib + pyyaml only, so it runs identically in pre-commit, CI, and a
bare container with zero network access.

Checks (all files tracked by git, minus excluded dirs):
  1. no trailing whitespace;
  2. text files end with exactly one newline;
  3. YAML files parse;
  4. no file larger than 1 MiB enters the repo;
  5. every Python file compiles (syntax gate);
  6. Python files use 4-space indentation, never tabs;
  7. every serve-path flag declared in serve/__main__.py is documented in
     docs/OPS.md (flag drift from new PRs fails the gate, not a reader);
  8. every fault-injection site fired anywhere in log_parser_tpu/ appears
     in the docs/OPS.md fault-site table (a chaos point nobody can look
     up is a chaos point nobody exercises);
  9. every counter key the runtime can emit on GET /trace/last (the dict
     literals under any ``def stats`` in the package, plus the
     ``payload["..."]`` blocks of serve/http.py) is documented in
     docs/OPS.md (an observability counter nobody can look up during an
     incident is noise, not signal);
 10. the static analyzers hold: tools/conlint.py is clean over
     runtime/serve/parallel, tools/pattern_lint.py is gating-clean over
     the builtin library, every pattern-lint rule id and regex reason
     code has a row in docs/PATTERNS.md, and every conlint rule id has a
     row in docs/OPS.md (an invariant nobody can look up is an invariant
     nobody repairs);
 11. every kernel-tier admission reason code (``REASONS`` in
     ops/matchdfa_pallas.py — the /trace/last ``kernel.reason``
     vocabulary) has a row in docs/OPS.md;
 12. every streaming frame type (``FRAME_TYPES`` in runtime/stream.py —
     the ``type`` field vocabulary of the NDJSON / gRPC frames a
     follow-mode session emits) has a row in docs/OPS.md (an operator
     reading a captured stream must be able to look up every frame
     shape);
 13. the tenancy chaos vocabulary (``FAULT_SITES`` in runtime/tenancy.py)
     is pinned in BOTH directions: every key has a docs/OPS.md row AND a
     live ``faults.fire`` site somewhere in the package (check 8's
     pattern cannot see fire calls that carry a waiver comment between
     the paren and the site string, so the tenancy sites get their own
     table-driven check);
 14. the template-miner vocabularies (log_parser_tpu/mining/) are
     pinned: every admission rejection-reason code (``REJECT_REASONS``
     in mining/admit.py — the /trace/last ``miner.rejected``
     vocabulary) has a docs/PATTERNS.md row; every miner fault site
     (``FAULT_SITES`` in mining/miner.py) has a docs/OPS.md row AND a
     live ``faults.fire`` call site (check 13's idiom); every
     ``--miner*``/``--mined-*`` serve flag has a docs/OPS.md table row
     (stricter than check 7's substring: a backtick-quoted row); and
     every key of the /trace/last ``miner`` block (the miner's
     ``stats()`` dict) has a backtick-quoted docs/OPS.md entry
     (stricter than check 9's word match);
 15. the builtin bank ADMITS to the Pallas union-DFA kernel:
     tools/check_dfa_admission.py must report an ADMITTED reason
     (``byte_classed``/``split``) under the production VMEM budget — a
     pattern or compiler change that regresses the verdict to
     ``table_too_large`` fails the gate, not a silent runtime fallback
     (the union pack is disk-cached, so warm runs cost seconds);
 16. the observability vocabulary is pinned: every ``METRICS`` family
     and every ``--trace-*``/``--slo-*`` serve flag has a
     backtick-quoted docs/OPS.md row, and collector coverage holds in
     both directions — every GET /trace/last payload block has a
     ``TRACE_BLOCKS`` entry naming its covering registry families,
     every entry names a block /trace/last still emits, and every
     family it names exists in ``METRICS``;
 17. the causal-span vocabulary (``SPANS`` in obs/spans.py — the
     ``GET /trace/spans`` / OTLP span-name contract) and the
     ``logparser_device_*`` utilization families each have a
     backtick-quoted docs/OPS.md row;
 18. the tenant-migration vocabulary is pinned by name: the migration
     fault sites (``FAULT_SITES`` in runtime/migrate.py) each have a
     docs/OPS.md row AND a live ``faults.fire`` call site, the
     migration spans and ``logparser_migration_*`` families exist and
     have rows, and every ``--drain-*`` serve flag has a
     backtick-quoted row;
 19. the warm-standby replication vocabulary is pinned the same way:
     the replication fault sites (``FAULT_SITES`` in
     runtime/replicate.py — ``replica_send`` / ``replica_apply`` /
     ``promote``) each have a docs/OPS.md row AND a live
     ``faults.fire`` call site, the replication spans (``replicate`` /
     ``promote`` / ``demote``) and the ``logparser_replication_*``
     metric families exist and have backtick-quoted rows, and the
     ``--replica-*``/``--failover-*`` serve flags meet the same
     backtick-row standard (losing any of these must read as a hole in
     the failover runbook, not a routine vocabulary shrink);
 22. the deterministic-simulation vocabulary is pinned: every schedule
     op (``SCHEDULE_OPS`` in sim/schedule.py) has a backtick-quoted
     docs/OPS.md row in the schedule-grammar table AND a live handler
     in the harness interpreter; every invariant id declared in
     sim/invariants.py (``SIM-I1``..) has a backtick-quoted docs/OPS.md
     row; the ids are contiguous from SIM-I1; and the replay runbook
     names ``sim_sweep.py`` (a failing seed nobody can replay is a
     failing seed nobody fixes).

``--fix`` rewrites what is mechanically fixable (1 and 2).
Exit 0 = clean, 1 = violations (listed on stdout).
"""

from __future__ import annotations

import argparse
import py_compile
import re
import subprocess
import sys
from pathlib import Path

MAX_BYTES = 1 << 20
TEXT_SUFFIXES = {
    ".py", ".md", ".yml", ".yaml", ".toml", ".json", ".proto", ".cpp",
    ".h", ".cfg", ".ini", ".txt", ".sh",
}
EXCLUDE_PARTS = {".git", "build", "__pycache__", ".pytest_cache"}
# round artifacts written by the build driver, not authored in this repo
EXCLUDE_NAMES = {"ADVICE.md", "VERDICT.md", "COPYCHECK.json", "PROGRESS.jsonl"}
EXCLUDE_PREFIXES = ("BENCH_r", "MULTICHIP_r")


def excluded(p: Path) -> bool:
    return (
        bool(EXCLUDE_PARTS.intersection(p.parts))
        or p.name in EXCLUDE_NAMES
        or p.name.startswith(EXCLUDE_PREFIXES)
    )


def tracked_files(root: Path) -> list[Path]:
    out = subprocess.run(
        ["git", "ls-files"], cwd=root, capture_output=True, text=True, check=True
    )
    return [
        p
        for rel in out.stdout.splitlines()
        if (p := root / rel).is_file() and not excluded(p)
    ]


def check_file(path: Path, fix: bool) -> list[str]:
    problems: list[str] = []
    size = path.stat().st_size
    if size > MAX_BYTES:
        problems.append(f"{path}: {size} bytes exceeds {MAX_BYTES} limit")
        return problems
    if path.suffix not in TEXT_SUFFIXES:
        return problems

    raw = path.read_bytes()
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError:
        problems.append(f"{path}: not valid UTF-8")
        return problems

    lines = text.split("\n")
    stripped = [ln.rstrip() for ln in lines]
    fixed = "\n".join(stripped).rstrip("\n") + "\n" if text.strip() else ""
    if any(ln != s for ln, s in zip(lines, stripped)):
        problems.append(f"{path}: trailing whitespace")
    if text and text != fixed and fixed == "\n".join(stripped).rstrip("\n") + "\n":
        if not text.endswith("\n") or text.endswith("\n\n"):
            problems.append(f"{path}: must end with exactly one newline")
    if fix and problems and fixed:
        # mechanical rewrite; the content checks below still run on the
        # fixed text (a --fix pass must not mask YAML/syntax violations)
        path.write_text(fixed, encoding="utf-8")
        problems = []
        text = fixed

    if path.suffix in (".yml", ".yaml"):
        import yaml

        try:
            list(yaml.safe_load_all(text))
        except yaml.YAMLError as exc:
            problems.append(f"{path}: invalid YAML: {exc}")

    if path.suffix == ".py":
        if "\t" in text:
            problems.append(f"{path}: tab character in Python source")
        try:
            py_compile.compile(str(path), doraise=True, cfile=None)
        except py_compile.PyCompileError as exc:
            problems.append(f"{path}: does not compile: {exc.msg}")

    return problems


def check_serve_flags_documented(root: Path) -> list[str]:
    """Check 7: the operator-facing flag surface of ``serve/__main__.py``
    must appear in docs/OPS.md (the serve-flags reference table). A
    literal-substring check is deliberate — it catches a renamed or
    undocumented flag without parsing argparse."""
    src = root / "log_parser_tpu" / "serve" / "__main__.py"
    ops = root / "docs" / "OPS.md"
    if not src.is_file() or not ops.is_file():
        return []  # partial checkouts (pre-commit on a subset) skip this
    flags = re.findall(r'add_argument\(\s*"(--[a-z0-9-]+)"', src.read_text())
    ops_text = ops.read_text()
    return [
        f"{src}: serve flag {flag} is not documented in docs/OPS.md"
        for flag in flags
        if flag not in ops_text
    ]


def check_fault_sites_documented(root: Path) -> list[str]:
    """Check 8: every ``faults.fire("<site>")`` call site in the package
    must appear in docs/OPS.md. Same literal-substring philosophy as
    check 7 — a new chaos point lands with its docs row or the gate
    fails."""
    pkg = root / "log_parser_tpu"
    ops = root / "docs" / "OPS.md"
    if not pkg.is_dir() or not ops.is_file():
        return []
    ops_text = ops.read_text()
    problems: list[str] = []
    seen: set[str] = set()
    for path in sorted(pkg.rglob("*.py")):
        if excluded(path):
            continue
        for site in re.findall(
            r'faults\.fire\(\s*"([a-z0-9_]+)"', path.read_text()
        ):
            if site in seen:
                continue
            seen.add(site)
            if f"`{site}`" not in ops_text:
                problems.append(
                    f"{path}: fault site {site!r} is not documented in "
                    "docs/OPS.md"
                )
    return problems


def check_trace_counters_documented(root: Path) -> list[str]:
    """Check 9: the /trace/last observability surface must be documented.
    Keys are harvested from (a) string keys of dict literals inside any
    ``def stats`` in the package — every stats() feeds /trace/last — and
    (b) ``payload["..."]`` assignments in serve/http.py. Each key must
    appear as a word somewhere in docs/OPS.md, so a new counter lands
    with its doc line (or a past one regains its lost doc) or the gate
    fails."""
    import ast

    pkg = root / "log_parser_tpu"
    ops = root / "docs" / "OPS.md"
    if not pkg.is_dir() or not ops.is_file():
        return []
    ops_text = ops.read_text()
    keys: dict[str, Path] = {}
    for path in sorted(pkg.rglob("*.py")):
        if excluded(path):
            continue
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue  # check 5 owns syntax reporting
        for node in ast.walk(tree):
            if not (isinstance(node, ast.FunctionDef) and node.name == "stats"):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Dict):
                    continue
                for k in sub.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        keys.setdefault(k.value, path)
    http_src = pkg / "serve" / "http.py"
    if http_src.is_file():
        for key in re.findall(r'payload\["(\w+)"\]', http_src.read_text()):
            keys.setdefault(key, http_src)
    return [
        f"{path}: /trace/last counter {key!r} is not documented in docs/OPS.md"
        for key, path in sorted(keys.items())
        if not re.search(rf"\b{re.escape(key)}\b", ops_text)
    ]


def _dict_keys_of(path: Path, name: str) -> list[str]:
    """String keys of the module-level dict literal assigned to ``name``
    in ``path`` — harvested via ast so hygiene never imports the package
    (the analysis package pulls in the jax stack)."""
    import ast

    try:
        tree = ast.parse(path.read_text())
    except SyntaxError:
        return []  # check 5 owns syntax reporting
    consts: dict[str, str] = {}  # NAME = "literal" assignments seen so far
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        value = node.value
        if (
            isinstance(value, ast.Constant)
            and isinstance(value.value, str)
        ):
            for t in targets:
                if isinstance(t, ast.Name):
                    consts[t.id] = value.value
        if not any(isinstance(t, ast.Name) and t.id == name for t in targets):
            continue
        if isinstance(value, ast.Dict):
            keys = []
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.append(k.value)
                elif isinstance(k, ast.Name) and k.id in consts:
                    keys.append(consts[k.id])
            return keys
    return []


def check_static_analyzers(root: Path) -> list[str]:
    """Check 10: run both static analyzers and pin their vocabularies to
    the docs. ``conlint`` must be clean over its default scope and
    ``pattern_lint --builtin`` gating-clean (a concurrency-invariant or
    pattern-library regression fails the gate, not a 3am page); every
    pattern-lint rule id and reason code needs its docs/PATTERNS.md row,
    every conlint rule id its docs/OPS.md row."""
    rules_src = root / "log_parser_tpu" / "analysis" / "rules.py"
    reasons_src = root / "log_parser_tpu" / "patterns" / "regex" / "reasons.py"
    conlint_src = root / "tools" / "conlint.py"
    patterns_doc = root / "docs" / "PATTERNS.md"
    ops_doc = root / "docs" / "OPS.md"
    if not (rules_src.is_file() and conlint_src.is_file()):
        return []
    problems: list[str] = []

    for tool, args, what in (
        ("conlint.py", [], "concurrency-invariant findings"),
        ("pattern_lint.py", ["--builtin"], "gating lint findings"),
    ):
        proc = subprocess.run(
            [sys.executable, str(root / "tools" / tool), *args, "--json"],
            cwd=root, capture_output=True, text=True,
        )
        if proc.returncode != 0:
            cmd = " ".join(["python", f"tools/{tool}", *args])
            problems.append(f"tools/{tool}: {what} (run `{cmd}` for the list)")

    patterns_text = patterns_doc.read_text() if patterns_doc.is_file() else ""
    for src, name in ((rules_src, "RULES"), (reasons_src, "REASONS")):
        for key in _dict_keys_of(src, name):
            if f"`{key}`" not in patterns_text:
                problems.append(
                    f"{src}: {name} entry {key!r} is not documented in "
                    "docs/PATTERNS.md"
                )
    ops_text = ops_doc.read_text() if ops_doc.is_file() else ""
    for key in _dict_keys_of(conlint_src, "RULES"):
        if f"`{key}`" not in ops_text:
            problems.append(
                f"{conlint_src}: conlint rule {key!r} is not documented in "
                "docs/OPS.md"
            )
    return problems


def check_kernel_reasons_documented(root: Path) -> list[str]:
    """Check 11: the kernel tier's admission reason codes (``REASONS``
    in ops/matchdfa_pallas.py, surfaced as /trace/last
    ``kernel.reason``) must each have a docs/OPS.md row — an operator
    chasing a tier that silently fell back needs the lookup table."""
    src = root / "log_parser_tpu" / "ops" / "matchdfa_pallas.py"
    ops_doc = root / "docs" / "OPS.md"
    if not src.is_file() or not ops_doc.is_file():
        return []
    ops_text = ops_doc.read_text()
    return [
        f"{src}: kernel-tier reason {key!r} is not documented in docs/OPS.md"
        for key in _dict_keys_of(src, "REASONS")
        if f"`{key}`" not in ops_text
    ]


def check_stream_frames_documented(root: Path) -> list[str]:
    """Check 12: the streaming frame vocabulary (``FRAME_TYPES`` in
    runtime/stream.py, the ``type`` field of every frame ``POST
    /parse/stream`` and the gRPC ``StreamParse`` emit) must each have a
    docs/OPS.md row — same contract-pinning as checks 10/11."""
    src = root / "log_parser_tpu" / "runtime" / "stream.py"
    ops_doc = root / "docs" / "OPS.md"
    if not src.is_file() or not ops_doc.is_file():
        return []
    ops_text = ops_doc.read_text()
    return [
        f"{src}: stream frame type {key!r} is not documented in docs/OPS.md"
        for key in _dict_keys_of(src, "FRAME_TYPES")
        if f"`{key}`" not in ops_text
    ]


def check_tenancy_vocab_pinned(root: Path) -> list[str]:
    """Check 13: the multi-tenant fault-site vocabulary (``FAULT_SITES``
    in runtime/tenancy.py) must each have a docs/OPS.md row and a live
    ``faults.fire`` call site in the package — pinning the table to the
    docs and to reality. The fire-site scan tolerates a comment between
    ``faults.fire(`` and the site string (conlint waivers live there),
    which is exactly the shape check 8's stricter pattern skips."""
    src = root / "log_parser_tpu" / "runtime" / "tenancy.py"
    ops_doc = root / "docs" / "OPS.md"
    pkg = root / "log_parser_tpu"
    if not src.is_file() or not ops_doc.is_file():
        return []
    ops_text = ops_doc.read_text()
    fired: set[str] = set()
    for path in sorted(pkg.rglob("*.py")):
        if excluded(path):
            continue
        fired.update(
            re.findall(
                r'faults\.fire\([^"]*?"([a-z0-9_]+)"',
                path.read_text(),
                re.S,
            )
        )
    problems: list[str] = []
    for key in _dict_keys_of(src, "FAULT_SITES"):
        if f"`{key}`" not in ops_text:
            problems.append(
                f"{src}: tenancy fault site {key!r} is not documented in "
                "docs/OPS.md"
            )
        if key not in fired:
            problems.append(
                f"{src}: tenancy fault site {key!r} has no live "
                "faults.fire call site"
            )
    return problems


def check_miner_vocab_pinned(root: Path) -> list[str]:
    """Check 14: the template-miner vocabularies must be pinned the way
    check 13 pins tenancy's. Rejection-reason codes (``REJECT_REASONS``
    in mining/admit.py) are the triage vocabulary an operator reads off
    ``/trace/last`` ``miner.rejected`` — each needs its
    docs/PATTERNS.md row. Miner fault sites (``FAULT_SITES`` in
    mining/miner.py) each need a docs/OPS.md row and a live
    ``faults.fire`` call site (the comment-tolerant scan, since the
    miner's fire calls carry conlint waivers). The miner serve flags
    and the /trace/last ``miner`` block keys are held to the stricter
    backtick-row standard (checks 7/9 would pass on an incidental
    substring)."""
    import ast

    admit_src = root / "log_parser_tpu" / "mining" / "admit.py"
    miner_src = root / "log_parser_tpu" / "mining" / "miner.py"
    serve_src = root / "log_parser_tpu" / "serve" / "__main__.py"
    patterns_doc = root / "docs" / "PATTERNS.md"
    ops_doc = root / "docs" / "OPS.md"
    pkg = root / "log_parser_tpu"
    if not admit_src.is_file() or not miner_src.is_file():
        return []
    problems: list[str] = []
    patterns_text = patterns_doc.read_text() if patterns_doc.is_file() else ""
    for key in _dict_keys_of(admit_src, "REJECT_REASONS"):
        if f"`{key}`" not in patterns_text:
            problems.append(
                f"{admit_src}: rejection reason {key!r} is not documented "
                "in docs/PATTERNS.md"
            )
    ops_text = ops_doc.read_text() if ops_doc.is_file() else ""
    fired: set[str] = set()
    for path in sorted(pkg.rglob("*.py")):
        if excluded(path):
            continue
        fired.update(
            re.findall(
                r'faults\.fire\([^"]*?"([a-z0-9_]+)"',
                path.read_text(),
                re.S,
            )
        )
    for key in _dict_keys_of(miner_src, "FAULT_SITES"):
        if f"`{key}`" not in ops_text:
            problems.append(
                f"{miner_src}: miner fault site {key!r} is not documented "
                "in docs/OPS.md"
            )
        if key not in fired:
            problems.append(
                f"{miner_src}: miner fault site {key!r} has no live "
                "faults.fire call site"
            )
    if serve_src.is_file():
        for flag in re.findall(
            r'add_argument\(\s*"(--mine[rd][a-z0-9-]*)"', serve_src.read_text()
        ):
            if f"`{flag}`" not in ops_text:
                problems.append(
                    f"{serve_src}: miner serve flag {flag} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    # the /trace/last ``miner`` block: string keys of every dict literal
    # under the mining package's stats() methods (the miner merges the
    # tap's and clusterer's stats into its own payload)
    stats_keys: dict[str, Path] = {}
    for path in sorted((pkg / "mining").rglob("*.py")):
        if excluded(path):
            continue
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue  # check 5 owns syntax reporting
        for node in ast.walk(tree):
            if not (isinstance(node, ast.FunctionDef) and node.name == "stats"):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    for k in sub.keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            stats_keys.setdefault(k.value, path)
    tap_src = root / "log_parser_tpu" / "runtime" / "linecache.py"
    if tap_src.is_file():
        tree = ast.parse(tap_src.read_text())
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == "MissTap":
                for fn in ast.walk(node):
                    if isinstance(fn, ast.FunctionDef) and fn.name == "stats":
                        for sub in ast.walk(fn):
                            if isinstance(sub, ast.Dict):
                                for k in sub.keys:
                                    if isinstance(k, ast.Constant) and isinstance(
                                        k.value, str
                                    ):
                                        stats_keys.setdefault(k.value, tap_src)
    for key, path in sorted(stats_keys.items()):
        if f"`{key}`" not in ops_text:
            problems.append(
                f"{path}: /trace/last miner counter {key!r} has no "
                "backtick-quoted docs/OPS.md entry"
            )
    return problems


def check_kernel_admission(root: Path) -> list[str]:
    """Check 15: the PR that shrank the union DFA under the VMEM budget
    (Hopcroft minimization + byte-class planes + admissible splits) is
    pinned here — tools/check_dfa_admission.py packs the builtin bank's
    union groups and must come back with an ADMITTED verdict. Runs as a
    subprocess (check 10's idiom) so hygiene itself never imports the
    jax stack; the tool's union-pack disk cache keeps warm runs cheap."""
    import json
    import os

    tool = root / "tools" / "check_dfa_admission.py"
    if not tool.is_file():
        return []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(tool)],
        cwd=root, capture_output=True, text=True, env=env,
    )
    if proc.returncode == 0:
        return []
    try:
        reason = json.loads(proc.stdout.splitlines()[-1]).get("reason")
    except Exception:
        reason = None
    detail = (
        f"verdict {reason!r}" if reason
        else f"tool failed (rc={proc.returncode}): {proc.stderr.strip()[-300:]}"
    )
    return [
        f"{tool}: builtin bank no longer admits to the union-DFA kernel — "
        f"{detail} (run `python tools/check_dfa_admission.py` to reproduce)"
    ]


def _trace_blocks_of(path: Path) -> dict[str, tuple[str, ...]]:
    """The ``TRACE_BLOCKS`` literal of obs/registry.py as a plain dict —
    string keys mapped to their tuple-of-metric-family values, harvested
    via ast (same no-import rule as ``_dict_keys_of``)."""
    import ast

    try:
        tree = ast.parse(path.read_text())
    except SyntaxError:
        return {}
    for node in tree.body:
        if not (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "TRACE_BLOCKS"
                for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            continue
        out: dict[str, tuple[str, ...]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                continue
            fams = tuple(
                e.value
                for e in (v.elts if isinstance(v, (ast.Tuple, ast.List)) else [])
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
            out[k.value] = fams
        return out
    return {}


def _trace_payload_keys(http_src: Path) -> list[str]:
    """Every key of the GET /trace/last payload: the dict literal that
    initializes ``payload`` plus every ``payload["..."] = ...``
    assignment in serve/http.py."""
    import ast

    try:
        tree = ast.parse(http_src.read_text())
    except SyntaxError:
        return []
    keys: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Name)
                and t.id == "payload"
                and isinstance(node.value, ast.Dict)
            ):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        if k.value not in keys:
                            keys.append(k.value)
            elif (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id == "payload"
                and isinstance(t.slice, ast.Constant)
                and isinstance(t.slice.value, str)
            ):
                if t.slice.value not in keys:
                    keys.append(t.slice.value)
    # the IfExp form `payload = {...} if trace is None else {...}` hides
    # its dicts one level down; harvest those too
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "payload" for t in node.targets
        ):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Dict):
                    for k in sub.keys:
                        if (
                            isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                            and k.value not in keys
                        ):
                            keys.append(k.value)
    return keys


def check_obs_vocab_pinned(root: Path) -> list[str]:
    """Check 16: the observability vocabulary must be pinned the way
    checks 9/12/14 pin their surfaces. Every metric name in the
    ``METRICS`` literal (log_parser_tpu/obs/registry.py) is a dashboard
    and alert-rule contract — each needs a backtick-quoted row in
    docs/OPS.md so a rename shows up as a doc diff, not a silently
    broken scrape. The obs serve flags (``--trace-*`` / ``--slo-*``)
    are held to the same backtick-row standard. Collector coverage is
    pinned in both directions: every GET /trace/last payload block must
    have a ``TRACE_BLOCKS`` entry naming the registry families that
    cover it (a trace block an alert rule cannot see is an incident
    nobody is paged for), every ``TRACE_BLOCKS`` key must still exist
    on /trace/last, and every family it names must be a ``METRICS``
    entry."""
    registry_src = root / "log_parser_tpu" / "obs" / "registry.py"
    serve_src = root / "log_parser_tpu" / "serve" / "__main__.py"
    http_src = root / "log_parser_tpu" / "serve" / "http.py"
    ops_doc = root / "docs" / "OPS.md"
    if not registry_src.is_file():
        return []
    problems: list[str] = []
    ops_text = ops_doc.read_text() if ops_doc.is_file() else ""
    metrics = set(_dict_keys_of(registry_src, "METRICS"))
    for name in sorted(metrics):
        if f"`{name}`" not in ops_text:
            problems.append(
                f"{registry_src}: metric {name!r} has no backtick-quoted "
                "docs/OPS.md row"
            )
    if serve_src.is_file():
        for flag in re.findall(
            r'add_argument\(\s*"(--trace-[a-z0-9-]+|--slo-[a-z0-9-]+)"',
            serve_src.read_text(),
        ):
            if f"`{flag}`" not in ops_text:
                problems.append(
                    f"{serve_src}: observability serve flag {flag} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    blocks = _trace_blocks_of(registry_src)
    if blocks and http_src.is_file():
        payload_keys = _trace_payload_keys(http_src)
        for key in payload_keys:
            if key not in blocks:
                problems.append(
                    f"{http_src}: /trace/last block {key!r} has no "
                    "TRACE_BLOCKS entry naming its covering registry "
                    "families"
                )
        for key, fams in blocks.items():
            if key not in payload_keys:
                problems.append(
                    f"{registry_src}: TRACE_BLOCKS entry {key!r} maps a "
                    "block GET /trace/last no longer emits"
                )
            if not fams:
                problems.append(
                    f"{registry_src}: TRACE_BLOCKS entry {key!r} names no "
                    "registry families"
                )
            for fam in fams:
                if fam not in metrics:
                    problems.append(
                        f"{registry_src}: TRACE_BLOCKS entry {key!r} names "
                        f"unknown registry family {fam!r}"
                    )
    return problems


def check_span_vocab_pinned(root: Path) -> list[str]:
    """Check 17: the causal-span vocabulary (``SPANS`` in obs/spans.py —
    every span name ``GET /trace/spans`` and the OTLP dump can emit)
    must each have a backtick-quoted docs/OPS.md row: an operator
    walking a causal tree during an incident needs the lookup table.
    The device-utilization families (``logparser_device_*``) are pinned
    by name here as well — check 16 already demands a row for every
    METRICS entry, but these carry the per-dispatch cost semantics the
    span runbook leans on, so losing one must point at the span docs.
    (The span serve flags ``--trace-sample``/``--trace-spans`` match
    check 16's ``--trace-*`` pattern and are pinned there.)"""
    spans_src = root / "log_parser_tpu" / "obs" / "spans.py"
    registry_src = root / "log_parser_tpu" / "obs" / "registry.py"
    ops_doc = root / "docs" / "OPS.md"
    if not spans_src.is_file() or not ops_doc.is_file():
        return []
    ops_text = ops_doc.read_text()
    problems: list[str] = []
    names = _dict_keys_of(spans_src, "SPANS")
    if not names:
        problems.append(f"{spans_src}: SPANS vocabulary is empty or unparsable")
    for name in names:
        if f"`{name}`" not in ops_text:
            problems.append(
                f"{spans_src}: span name {name!r} has no backtick-quoted "
                "docs/OPS.md row"
            )
    if registry_src.is_file():
        for fam in _dict_keys_of(registry_src, "METRICS"):
            if fam.startswith("logparser_device_") and f"`{fam}`" not in ops_text:
                problems.append(
                    f"{registry_src}: device-utilization family {fam!r} has "
                    "no backtick-quoted docs/OPS.md row"
                )
    return problems


def check_migrate_vocab_pinned(root: Path) -> list[str]:
    """Check 18: the tenant-migration vocabulary must be pinned the way
    checks 13/17 pin tenancy's and the span store's. The migration fault
    sites (``FAULT_SITES`` in runtime/migrate.py — ``migrate_export`` /
    ``migrate_import`` / ``migrate_cutover``) each need a docs/OPS.md
    row and a live ``faults.fire`` call site (comment-tolerant scan: the
    fire calls carry conlint waivers). The migration span names and the
    ``logparser_migration_*`` metric families are pinned BY NAME to
    their vocabularies and to docs/OPS.md — checks 16/17 already demand
    rows for whatever exists, but losing one of these must point at the
    migration runbook, not read as a routine vocabulary shrink. The
    ``--drain-*`` serve flags get the same backtick-row standard the
    miner and obs flags are held to."""
    src = root / "log_parser_tpu" / "runtime" / "migrate.py"
    spans_src = root / "log_parser_tpu" / "obs" / "spans.py"
    registry_src = root / "log_parser_tpu" / "obs" / "registry.py"
    serve_src = root / "log_parser_tpu" / "serve" / "__main__.py"
    ops_doc = root / "docs" / "OPS.md"
    pkg = root / "log_parser_tpu"
    if not src.is_file() or not ops_doc.is_file():
        return []
    ops_text = ops_doc.read_text()
    problems: list[str] = []
    fired: set[str] = set()
    for path in sorted(pkg.rglob("*.py")):
        if excluded(path):
            continue
        fired.update(
            re.findall(
                r'faults\.fire\([^"]*?"([a-z0-9_]+)"',
                path.read_text(),
                re.S,
            )
        )
    sites = _dict_keys_of(src, "FAULT_SITES")
    for required in ("migrate_export", "migrate_import", "migrate_cutover"):
        if required not in sites:
            problems.append(
                f"{src}: migration fault site {required!r} is missing from "
                "FAULT_SITES — the crash-matrix drills depend on it"
            )
    for key in sites:
        if f"`{key}`" not in ops_text:
            problems.append(
                f"{src}: migration fault site {key!r} is not documented in "
                "docs/OPS.md"
            )
        if key not in fired:
            problems.append(
                f"{src}: migration fault site {key!r} has no live "
                "faults.fire call site"
            )
    if spans_src.is_file():
        span_names = set(_dict_keys_of(spans_src, "SPANS"))
        for name in (
            "migration",
            "migrate_export",
            "migrate_import",
            "migrate_cutover",
            "drain",
        ):
            if name not in span_names:
                problems.append(
                    f"{spans_src}: migration span {name!r} is missing from "
                    "SPANS — the migration causal trace depends on it"
                )
            elif f"`{name}`" not in ops_text:
                problems.append(
                    f"{spans_src}: migration span {name!r} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    if registry_src.is_file():
        metrics = set(_dict_keys_of(registry_src, "METRICS"))
        migration_fams = {m for m in metrics if m.startswith("logparser_migration_")}
        if not migration_fams:
            problems.append(
                f"{registry_src}: no logparser_migration_* metric families — "
                "the migration dashboards and alert rules depend on them"
            )
        for fam in sorted(migration_fams):
            if f"`{fam}`" not in ops_text:
                problems.append(
                    f"{registry_src}: migration family {fam!r} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    if serve_src.is_file():
        for flag in re.findall(
            r'add_argument\(\s*"(--drain-[a-z0-9-]+)"', serve_src.read_text()
        ):
            if f"`{flag}`" not in ops_text:
                problems.append(
                    f"{serve_src}: drain serve flag {flag} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    return problems


def check_replica_vocab_pinned(root: Path) -> list[str]:
    """Check 19: the warm-standby replication vocabulary must be pinned
    the way check 18 pins migration's. The replication fault sites
    (``FAULT_SITES`` in runtime/replicate.py — ``replica_send`` /
    ``replica_apply`` / ``promote``, one per protocol leg) each need a
    docs/OPS.md row and a live ``faults.fire`` call site
    (comment-tolerant scan). The replication span names and the
    ``logparser_replication_*`` families are pinned BY NAME — losing
    one must point at the failover runbook. The ``--replica-*`` and
    ``--failover-*`` serve flags get the backtick-row standard."""
    src = root / "log_parser_tpu" / "runtime" / "replicate.py"
    spans_src = root / "log_parser_tpu" / "obs" / "spans.py"
    registry_src = root / "log_parser_tpu" / "obs" / "registry.py"
    serve_src = root / "log_parser_tpu" / "serve" / "__main__.py"
    ops_doc = root / "docs" / "OPS.md"
    pkg = root / "log_parser_tpu"
    if not src.is_file() or not ops_doc.is_file():
        return []
    ops_text = ops_doc.read_text()
    problems: list[str] = []
    fired: set[str] = set()
    for path in sorted(pkg.rglob("*.py")):
        if excluded(path):
            continue
        fired.update(
            re.findall(
                r'faults\.fire\([^"]*?"([a-z0-9_]+)"',
                path.read_text(),
                re.S,
            )
        )
    sites = _dict_keys_of(src, "FAULT_SITES")
    for required in ("replica_send", "replica_apply", "promote"):
        if required not in sites:
            problems.append(
                f"{src}: replication fault site {required!r} is missing "
                "from FAULT_SITES — the failover chaos drills depend on it"
            )
    for key in sites:
        if f"`{key}`" not in ops_text:
            problems.append(
                f"{src}: replication fault site {key!r} is not documented "
                "in docs/OPS.md"
            )
        if key not in fired:
            problems.append(
                f"{src}: replication fault site {key!r} has no live "
                "faults.fire call site"
            )
    if spans_src.is_file():
        span_names = set(_dict_keys_of(spans_src, "SPANS"))
        for name in ("replicate", "promote", "demote"):
            if name not in span_names:
                problems.append(
                    f"{spans_src}: replication span {name!r} is missing "
                    "from SPANS — the failover causal trace depends on it"
                )
            elif f"`{name}`" not in ops_text:
                problems.append(
                    f"{spans_src}: replication span {name!r} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    if registry_src.is_file():
        metrics = set(_dict_keys_of(registry_src, "METRICS"))
        replica_fams = {
            m for m in metrics if m.startswith("logparser_replication_")
        }
        if not replica_fams:
            problems.append(
                f"{registry_src}: no logparser_replication_* metric "
                "families — the replication-lag alerts depend on them"
            )
        for fam in sorted(replica_fams):
            if f"`{fam}`" not in ops_text:
                problems.append(
                    f"{registry_src}: replication family {fam!r} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    if serve_src.is_file():
        for flag in re.findall(
            r'add_argument\(\s*"(--(?:replica|failover)-[a-z0-9-]+)"',
            serve_src.read_text(),
        ):
            if f"`{flag}`" not in ops_text:
                problems.append(
                    f"{serve_src}: replication serve flag {flag} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    return problems


def check_fleet_vocab_pinned(root: Path) -> list[str]:
    """Check 20: the fleet-router vocabulary must be pinned the way
    check 19 pins replication's. The router fault sites (``FAULT_SITES``
    in fleet/router.py — ``route`` / ``route_backend`` /
    ``placement_move``, one per routing leg) each need a docs/OPS.md row
    and a live ``faults.fire`` call site (comment-tolerant scan). The
    ``route`` span and the ``logparser_fleet_*`` families are pinned BY
    NAME — losing one must point at the fleet runbook. The
    ``--role``/``--backends*``/``--shim-port``/``--grpc-port``/
    ``--fleet-*`` serve flags get the backtick-row standard."""
    src = root / "log_parser_tpu" / "fleet" / "router.py"
    spans_src = root / "log_parser_tpu" / "obs" / "spans.py"
    registry_src = root / "log_parser_tpu" / "obs" / "registry.py"
    serve_src = root / "log_parser_tpu" / "serve" / "__main__.py"
    ops_doc = root / "docs" / "OPS.md"
    pkg = root / "log_parser_tpu"
    if not src.is_file() or not ops_doc.is_file():
        return []
    ops_text = ops_doc.read_text()
    problems: list[str] = []
    fired: set[str] = set()
    for path in sorted(pkg.rglob("*.py")):
        if excluded(path):
            continue
        fired.update(
            re.findall(
                r'faults\.fire\([^"]*?"([a-z0-9_]+)"',
                path.read_text(),
                re.S,
            )
        )
    sites = _dict_keys_of(src, "FAULT_SITES")
    for required in ("route", "route_backend", "placement_move"):
        if required not in sites:
            problems.append(
                f"{src}: fleet fault site {required!r} is missing from "
                "FAULT_SITES — the fleet chaos drills depend on it"
            )
    for key in sites:
        if f"`{key}`" not in ops_text:
            problems.append(
                f"{src}: fleet fault site {key!r} is not documented in "
                "docs/OPS.md"
            )
        if key not in fired:
            problems.append(
                f"{src}: fleet fault site {key!r} has no live "
                "faults.fire call site"
            )
    if spans_src.is_file():
        span_names = set(_dict_keys_of(spans_src, "SPANS"))
        if "route" not in span_names:
            problems.append(
                f"{spans_src}: fleet span 'route' is missing from SPANS "
                "— the router causal trace depends on it"
            )
        elif "`route`" not in ops_text:
            problems.append(
                f"{spans_src}: fleet span 'route' has no backtick-quoted "
                "docs/OPS.md row"
            )
    if registry_src.is_file():
        metrics = set(_dict_keys_of(registry_src, "METRICS"))
        fleet_fams = {m for m in metrics if m.startswith("logparser_fleet_")}
        if not fleet_fams:
            problems.append(
                f"{registry_src}: no logparser_fleet_* metric families — "
                "the fleet routing alerts depend on them"
            )
        for fam in sorted(fleet_fams):
            if f"`{fam}`" not in ops_text:
                problems.append(
                    f"{registry_src}: fleet family {fam!r} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    if serve_src.is_file():
        for flag in re.findall(
            r'add_argument\(\s*"(--(?:role|backends|backends-shim'
            r'|shim-port|grpc-port|fleet-[a-z0-9-]+))"',
            serve_src.read_text(),
        ):
            if f"`{flag}`" not in ops_text:
                problems.append(
                    f"{serve_src}: fleet serve flag {flag} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    return problems


def _tuple_items_of(path: Path, name: str) -> list[str]:
    """String items of the module-level tuple/list literal assigned to
    ``name`` in ``path`` — ast-harvested like :func:`_dict_keys_of`."""
    import ast

    try:
        tree = ast.parse(path.read_text())
    except SyntaxError:
        return []  # check 5 owns syntax reporting
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == name for t in targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            return [
                el.value
                for el in node.value.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, str)
            ]
    return []


def check_pressure_vocab_pinned(root: Path) -> list[str]:
    """Check 21: the resource-pressure vocabulary must be pinned the way
    check 20 pins the fleet's. The pressure fault sites (``FAULT_SITES``
    in runtime/pressure.py — ``disk_enospc`` / ``mem_pressure`` /
    ``retry_storm``) each need a docs/OPS.md row and a live
    ``faults.fire`` call site; every guarded durability site in
    ``DISK_SITES`` (the ``@match=`` targets operators drill against)
    needs a docs/OPS.md row; the ``pressure`` span and every
    ``logparser_pressure_*`` family are pinned BY NAME; the
    ``--disk-soft-mb``/``--disk-hard-mb``/``--mem-soft-mb``/
    ``--retry-budget`` serve flags get the backtick-row standard."""
    src = root / "log_parser_tpu" / "runtime" / "pressure.py"
    spans_src = root / "log_parser_tpu" / "obs" / "spans.py"
    registry_src = root / "log_parser_tpu" / "obs" / "registry.py"
    serve_src = root / "log_parser_tpu" / "serve" / "__main__.py"
    ops_doc = root / "docs" / "OPS.md"
    pkg = root / "log_parser_tpu"
    if not src.is_file() or not ops_doc.is_file():
        return []
    ops_text = ops_doc.read_text()
    problems: list[str] = []
    fired: set[str] = set()
    for path in sorted(pkg.rglob("*.py")):
        if excluded(path):
            continue
        fired.update(
            re.findall(
                r'faults\.fire\([^"]*?"([a-z0-9_]+)"',
                path.read_text(),
                re.S,
            )
        )
    sites = _dict_keys_of(src, "FAULT_SITES")
    for required in ("disk_enospc", "mem_pressure", "retry_storm"):
        if required not in sites:
            problems.append(
                f"{src}: pressure fault site {required!r} is missing from "
                "FAULT_SITES — the resource-exhaustion drills depend on it"
            )
    for key in sites:
        if f"`{key}`" not in ops_text:
            problems.append(
                f"{src}: pressure fault site {key!r} is not documented in "
                "docs/OPS.md"
            )
        if key not in fired:
            problems.append(
                f"{src}: pressure fault site {key!r} has no live "
                "faults.fire call site"
            )
    disk_sites = _tuple_items_of(src, "DISK_SITES")
    if not disk_sites:
        problems.append(
            f"{src}: DISK_SITES is empty or missing — the ENOSPC drill "
            "matrix depends on it"
        )
    for site in disk_sites:
        if f"`{site}`" not in ops_text:
            problems.append(
                f"{src}: durability site {site!r} (a disk_enospc @match "
                "target) has no backtick-quoted docs/OPS.md row"
            )
    if spans_src.is_file():
        span_names = set(_dict_keys_of(spans_src, "SPANS"))
        if "pressure" not in span_names:
            problems.append(
                f"{spans_src}: span 'pressure' is missing from SPANS — "
                "the ladder-transition trace depends on it"
            )
        elif "`pressure`" not in ops_text:
            problems.append(
                f"{spans_src}: span 'pressure' has no backtick-quoted "
                "docs/OPS.md row"
            )
    if registry_src.is_file():
        metrics = set(_dict_keys_of(registry_src, "METRICS"))
        fams = {m for m in metrics if m.startswith("logparser_pressure_")}
        if not fams:
            problems.append(
                f"{registry_src}: no logparser_pressure_* metric families "
                "— the resource-exhaustion alerts depend on them"
            )
        for fam in sorted(fams):
            if f"`{fam}`" not in ops_text:
                problems.append(
                    f"{registry_src}: pressure family {fam!r} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    if serve_src.is_file():
        for flag in re.findall(
            r'add_argument\(\s*"(--(?:disk-soft-mb|disk-hard-mb'
            r'|mem-soft-mb|retry-budget))"',
            serve_src.read_text(),
        ):
            if f"`{flag}`" not in ops_text:
                problems.append(
                    f"{serve_src}: pressure serve flag {flag} has no "
                    "backtick-quoted docs/OPS.md row"
                )
    return problems


def check_sim_vocab_pinned(root: Path) -> list[str]:
    """Check 22: the deterministic-simulation vocabulary must be pinned
    the way check 21 pins the pressure ladder's. Every schedule op
    (``SCHEDULE_OPS`` in sim/schedule.py) needs a backtick-quoted
    docs/OPS.md row and a live handler in the harness interpreter;
    every invariant id (``SIM-I<n>`` declared in sim/invariants.py)
    needs a backtick-quoted docs/OPS.md row and the sequence must be
    contiguous from SIM-I1; the replay runbook must name
    ``sim_sweep.py``."""
    sched_src = root / "log_parser_tpu" / "sim" / "schedule.py"
    inv_src = root / "log_parser_tpu" / "sim" / "invariants.py"
    harness_src = root / "log_parser_tpu" / "sim" / "harness.py"
    ops_doc = root / "docs" / "OPS.md"
    if not sched_src.is_file() or not ops_doc.is_file():
        return []
    ops_text = ops_doc.read_text()
    problems: list[str] = []
    ops = _dict_keys_of(sched_src, "SCHEDULE_OPS")
    if not ops:
        problems.append(
            f"{sched_src}: SCHEDULE_OPS is empty or missing — the seeded"
            " fault schedules depend on it"
        )
    harness_text = harness_src.read_text() if harness_src.is_file() else ""
    for op in ops:
        if f"`{op}`" not in ops_text:
            problems.append(
                f"{sched_src}: schedule op {op!r} has no backtick-quoted"
                " docs/OPS.md row in the schedule-grammar table"
            )
        if f'"{op}"' not in harness_text:
            problems.append(
                f"{sched_src}: schedule op {op!r} has no handler in the"
                " harness interpreter (sim/harness.py) — the generator"
                " would emit ops the fleet cannot apply"
            )
    ids: list[str] = []
    if inv_src.is_file():
        ids = re.findall(r'"(SIM-I\d+)"', inv_src.read_text())
    if not ids:
        problems.append(
            f"{inv_src}: no SIM-I<n> invariant ids declared — the sweep"
            " has nothing to check"
        )
    if ids != [f"SIM-I{i}" for i in range(1, len(ids) + 1)]:
        problems.append(
            f"{inv_src}: invariant ids {ids} are not contiguous from"
            " SIM-I1 — ids are pinned in failure output and the sweep"
            " artifact, never renumbered"
        )
    for inv_id in ids:
        if f"`{inv_id}`" not in ops_text:
            problems.append(
                f"{inv_src}: invariant {inv_id} has no backtick-quoted"
                " docs/OPS.md row in the invariant table"
            )
    if ops and "sim_sweep.py" not in ops_text:
        problems.append(
            f"{ops_doc}: the deterministic-simulation runbook must name"
            " sim_sweep.py — a failing seed nobody can replay is a"
            " failing seed nobody fixes"
        )
    return problems


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--fix", action="store_true", help="rewrite fixable problems")
    ap.add_argument("paths", nargs="*", help="restrict to these files (pre-commit)")
    args = ap.parse_args()

    root = Path(__file__).resolve().parents[1]
    # explicit paths (pre-commit's pass_filenames) honor the same
    # exclusions as the full scan — the two gates must agree on one tree
    files = (
        [q for p in args.paths if not excluded(q := Path(p).resolve())]
        if args.paths
        else tracked_files(root)
    )

    problems: list[str] = []
    for path in files:
        problems.extend(check_file(path, args.fix))
    if not args.paths:
        # repo-wide invariants, only meaningful on a full scan
        problems.extend(check_serve_flags_documented(root))
        problems.extend(check_fault_sites_documented(root))
        problems.extend(check_trace_counters_documented(root))
        problems.extend(check_static_analyzers(root))
        problems.extend(check_kernel_reasons_documented(root))
        problems.extend(check_stream_frames_documented(root))
        problems.extend(check_tenancy_vocab_pinned(root))
        problems.extend(check_miner_vocab_pinned(root))
        problems.extend(check_kernel_admission(root))
        problems.extend(check_obs_vocab_pinned(root))
        problems.extend(check_span_vocab_pinned(root))
        problems.extend(check_migrate_vocab_pinned(root))
        problems.extend(check_replica_vocab_pinned(root))
        problems.extend(check_fleet_vocab_pinned(root))
        problems.extend(check_pressure_vocab_pinned(root))
        problems.extend(check_sim_vocab_pinned(root))

    for p in problems:
        print(p)
    if problems:
        print(f"\nhygiene: {len(problems)} problem(s) in {len(files)} files", file=sys.stderr)
        return 1
    print(f"hygiene: clean ({len(files)} files)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
