#!/usr/bin/env python3
"""Concurrency-invariant linter for the runtime (hygiene check 10).

The serving stack's thread-safety rests on three documented-by-convention
invariants that nothing enforced until now. This checker enforces them
statically (stdlib ``ast``, no imports of the checked code) across
``log_parser_tpu/runtime/``, ``log_parser_tpu/serve/``, and
``log_parser_tpu/parallel/``:

``conlint-lock-order``
    The request-scope quiescence gate (``_request_scope()``) must be
    entered BEFORE ``state_lock`` (or its documented aliases
    ``analyze_lock``/``self.lock = engine.state_lock``), never while the
    lock is already held — the reload swap quiesces scopes while holding
    the lock, so the inverted order deadlocks with a concurrent reload.

``conlint-blocking-under-lock``
    No blocking wait while holding ``state_lock``: ``time.sleep``,
    thread-style ``.join()``, bare ``.wait()``, and ``subprocess.*``
    calls stall every analyze/demux/swap on the box.

``conlint-uncontained-fire``
    Every ``faults.fire(...)`` call must sit lexically inside a ``try``
    with an except handler in the same function, so an injected fault is
    exercised WITH its containment. Sites whose containment is the
    caller's by design carry a ``# conlint: contained-by-caller`` waiver
    comment on the call line (the fault-site table in docs/OPS.md names
    the containing path).

The analysis is intra-procedural and lexical: a ``with`` statement's
items are checked left-to-right (Python enters them in that order), and
explicit ``state_lock.acquire()``/``release()`` pairs toggle the held
state for the statements that follow in the same suite. Calls into
helper functions are not traced — keep lock manipulation local, which
is itself the convention this repo follows.

Usage: ``python tools/conlint.py [--json] [PATH...]``; exits 1 on
findings. The known-bad fixture ``tests/fixtures/conlint_bad_fixture.py``
pins each rule against regressions (tests/test_conlint.py).
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_SCAN_DIRS = (
    os.path.join("log_parser_tpu", "runtime"),
    os.path.join("log_parser_tpu", "serve"),
    os.path.join("log_parser_tpu", "parallel"),
)

LOCK_NAMES = ("state_lock", "analyze_lock")
SCOPE_NAME = "_request_scope"

WAIVERS = {
    "conlint-uncontained-fire": "contained-by-caller",
    "conlint-blocking-under-lock": "allow-blocking",
    "conlint-lock-order": "allow-lock-order",
}

RULES = {
    "conlint-lock-order": "request-scope entered while state_lock held "
    "(deadlocks against the reload swap's quiesce-under-lock)",
    "conlint-blocking-under-lock": "blocking call while holding "
    "state_lock stalls every request on the box",
    "conlint-uncontained-fire": "faults.fire outside a containing try: "
    "the injected fault escapes the path it is meant to exercise",
}


@dataclasses.dataclass
class Finding:
    file: str
    line: int
    rule: str
    detail: str

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _is_lock_expr(node: ast.AST) -> bool:
    return any(name in _expr_text(node) for name in LOCK_NAMES)


def _is_scope_expr(node: ast.AST) -> bool:
    return SCOPE_NAME in _expr_text(node)


def _is_blocking_call(call: ast.Call) -> str | None:
    """Name of the blocking operation, or None."""
    func = call.func
    if isinstance(func, ast.Attribute):
        base = _expr_text(func.value)
        if func.attr == "sleep" and base == "time":
            return "time.sleep"
        if base == "subprocess" or base.startswith("subprocess."):
            return f"subprocess.{func.attr}"
        if func.attr == "wait":
            return ".wait()"
        if func.attr == "join":
            # str.join takes exactly one iterable positional; thread-style
            # join takes none, a numeric timeout, or timeout= keyword
            if not call.args and not call.keywords:
                return ".join()"
            if any(kw.arg == "timeout" for kw in call.keywords):
                return ".join(timeout=...)"
            if len(call.args) == 1 and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, (int, float)):
                return ".join(<seconds>)"
    return None


def _is_fire_call(call: ast.Call) -> bool:
    func = call.func
    return (
        isinstance(func, ast.Attribute)
        and func.attr == "fire"
        and _expr_text(func.value).endswith("faults")
    )


class _FunctionChecker(ast.NodeVisitor):
    """Checks one function body. ``lock_depth`` counts state_lock
    regions currently held; ``try_depth`` counts enclosing try-bodies
    that have an except handler."""

    def __init__(self, path: str, source_lines: list[str],
                 findings: list[Finding]):
        self.path = path
        self.lines = source_lines
        self.findings = findings
        self.lock_depth = 0
        self.try_depth = 0

    # nested defs get their own checker via _check_tree; don't descend
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def _waived(self, line: int, rule: str) -> bool:
        if 1 <= line <= len(self.lines):
            text = self.lines[line - 1]
            return f"conlint: {WAIVERS[rule]}" in text
        return False

    def _report(self, node: ast.AST, rule: str, detail: str) -> None:
        line = getattr(node, "lineno", 0)
        if not self._waived(line, rule):
            self.findings.append(Finding(self.path, line, rule, detail))

    def visit_With(self, node: ast.With) -> None:
        entered_locks = 0
        for item in node.items:
            expr = item.context_expr
            if _is_scope_expr(expr) and self.lock_depth + entered_locks > 0:
                self._report(
                    expr, "conlint-lock-order",
                    f"{_expr_text(expr)} entered while state_lock is held",
                )
            if _is_lock_expr(expr):
                entered_locks += 1
            self.visit(expr)
        self.lock_depth += entered_locks
        for stmt in node.body:
            self.visit(stmt)
        self.lock_depth -= entered_locks

    def visit_Try(self, node: ast.Try) -> None:
        has_handler = bool(node.handlers)
        if has_handler:
            self.try_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if has_handler:
            self.try_depth -= 1
        for handler in node.handlers:
            for stmt in handler.body:
                self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)
        for stmt in node.finalbody:
            self.visit(stmt)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        # explicit acquire/release toggles the held state for the
        # remainder of the suite (batcher's acquire ... try/finally
        # release idiom); release may appear in a finally visited later,
        # so depth is floored at zero
        if isinstance(func, ast.Attribute) and _is_lock_expr(func.value):
            if func.attr == "acquire":
                self.lock_depth += 1
            elif func.attr == "release":
                self.lock_depth = max(0, self.lock_depth - 1)
        if self.lock_depth > 0:
            blocking = _is_blocking_call(node)
            if blocking is not None and not _is_lock_expr(
                getattr(func, "value", func)
            ):
                # lock.acquire()/cv.wait() ON the lock itself is the
                # locking protocol, not a foreign blocking wait
                self._report(
                    node, "conlint-blocking-under-lock",
                    f"{blocking} while holding state_lock",
                )
        if _is_fire_call(node) and self.try_depth == 0:
            self._report(
                node, "conlint-uncontained-fire",
                f"{_expr_text(node)} has no containing try in this "
                "function",
            )
        self.generic_visit(node)


def _check_tree(path: str, tree: ast.AST, source: str,
                findings: list[Finding]) -> None:
    lines = source.splitlines()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            checker = _FunctionChecker(path, lines, findings)
            for stmt in node.body:
                checker.visit(stmt)


def check_file(path: str, rel: str | None = None) -> list[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    findings: list[Finding] = []
    _check_tree(rel or path, ast.parse(source, filename=path), source,
                findings)
    return findings


def check_paths(paths: list[str]) -> list[Finding]:
    findings: list[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for root, _dirs, files in sorted(
                (r, d, f) for r, d, f in os.walk(path)
            ):
                for name in sorted(files):
                    if name.endswith(".py"):
                        full = os.path.join(root, name)
                        findings.extend(
                            check_file(full, os.path.relpath(full, REPO))
                        )
        else:
            findings.extend(check_file(path, os.path.relpath(path, REPO)))
    return findings


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to check (default: runtime/, serve/, "
        "parallel/)",
    )
    ap.add_argument("--json", action="store_true", help="JSON findings")
    args = ap.parse_args(argv)
    paths = args.paths or [os.path.join(REPO, d) for d in DEFAULT_SCAN_DIRS]
    findings = check_paths(paths)
    if args.json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f"{f.file}:{f.line}: {f.rule}: {f.detail}")
        print(f"conlint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
