"""Host-phase profiler: the phases PERF.md §11 says bound the cached
ceiling, timed in isolation the way profile_fused.py isolates device
phases.

With the routing tier short-circuiting the match cube, a repeat-heavy
request's cost is host-side: ingest (blob → padded u8 batch), keying
(line → unique slot + digest), extraction (bits → MatchRecords),
assembly (unique rows → per-line bit matrix + override splice), and
finalize (records → scores + factor rows). Each phase is timed both as
the scalar reference path and (where one exists) the vectorized lane
that serves production, so a regression in either side is attributable
to one phase instead of "the request got slower".

The scalar reference lanes are pinned bit-identical to the vectorized
ones by tests/test_ingest_vec.py — this profiler measures, it does not
re-verify.

Usage:
    python tools/profile_host.py [--lines 200000] [--repeat-ratio 0.9]
                                 [--repeats 5]

Prints exactly one JSON line (wired into tools/refresh_artifacts.sh as
the ``profile_host_*`` artifact).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import statistics
import sys
import time

import numpy as np

# make the repo root importable without touching PYTHONPATH (overriding
# PYTHONPATH would drop /root/.axon_site and with it the TPU plugin)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, n=5, warmup=1):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts), statistics.median(ts)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lines", type=int, default=200_000)
    ap.add_argument(
        "--repeat-ratio",
        type=float,
        default=None,
        help="repeat-heavy corpus (bench_common.repeat_corpus) instead "
        "of bench.build_corpus's ~unique config-2 shape",
    )
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    import bench
    import bench_common

    import log_parser_tpu.native.ingest as ingest_mod
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.golden.javacompat import java_split_lines
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.ops.encode import encode_lines
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine
    from log_parser_tpu.runtime.finalize import finalize_batch
    from log_parser_tpu.runtime.linecache import (
        KeyInterner,
        dedup_slots,
        line_key,
        records_from_bits,
    )

    if args.repeat_ratio is not None:
        logs = bench_common.repeat_corpus(
            args.lines, args.repeat_ratio, "prof", random.Random(0xC0FFEE)
        )
    else:
        logs = bench.build_corpus(args.lines)

    report: dict = {
        "lines": args.lines,
        "repeat_ratio": args.repeat_ratio,
        "native_available": ingest_mod.get_lib() is not None,
    }

    # ---- ingest: scalar reference vs the vectorized Corpus fallback -----
    t_min, _ = timeit(
        lambda: encode_lines(java_split_lines(logs)), n=args.repeats
    )
    report["ingest_scalar_s"] = round(t_min, 4)
    real_get_lib = ingest_mod.get_lib
    ingest_mod.get_lib = lambda: None  # force the vectorized fallback
    try:
        t_min, _ = timeit(lambda: ingest_mod.Corpus(logs), n=args.repeats)
        report["ingest_vec_s"] = round(t_min, 4)
        corpus = ingest_mod.Corpus(logs)
    finally:
        ingest_mod.get_lib = real_get_lib
    enc = corpus.encoded
    report["batch_rows"], report["batch_cols"] = (int(x) for x in enc.u8.shape)

    # ---- keying: per-line dict loop vs lexsort dedup ---------------------
    def key_scalar():
        slot_of: dict[bytes, int] = {}
        line_slot = np.empty(corpus.n_lines, dtype=np.int64)
        for i in range(corpus.n_lines):
            lb = corpus.line_key_bytes(i)
            s = slot_of.get(lb)
            if s is None:
                s = len(slot_of)
                slot_of[lb] = s
            line_slot[i] = s
        return [line_key(lb) for lb in slot_of], line_slot

    t_min, _ = timeit(key_scalar, n=args.repeats)
    report["key_scalar_s"] = round(t_min, 4)
    t_min, _ = timeit(lambda: dedup_slots(corpus), n=args.repeats)
    report["key_vec_s"] = round(t_min, 4)
    # two-level keying: warm interner turns the per-unique-line blake2b
    # into a vectorized probe64 + memcmp verify (first touch paid once in
    # the warmup pass), the serving shape for repeat-heavy traffic
    interner = KeyInterner()
    dedup_slots(corpus, interner=interner)  # first touch: populate
    t_min, _ = timeit(
        lambda: dedup_slots(corpus, interner=interner), n=args.repeats
    )
    report["key_vec_interned_s"] = round(t_min, 4)
    report["interner"] = interner.stats()
    line_slot, rep_lines, keys, counts = dedup_slots(corpus)
    report["unique_lines"] = len(keys)

    # the digest sub-phase in isolation (the part the interner replaces;
    # the lexsort dedup above it is shared by both lanes): per-unique
    # blake2b vs warm probe64+verify digest recovery
    kv = corpus.key_view()
    blob, starts, ends = kv
    nl = corpus.n_lines
    starts, ends = starts[:nl], ends[:nl]
    width = corpus.encoded.u8.shape[1]
    lengths = (ends - starts).astype(np.int64)
    kw = -(-(width + 8) // 8) * 8
    km = np.zeros((nl, kw), dtype=np.uint8)
    km[:, :width] = corpus.encoded.u8[:nl]
    km[:, width : width + 8] = (
        lengths.astype("<i8").reshape(nl, 1).view(np.uint8)
    )
    v64 = km.view("<i8")
    s_l = starts[rep_lines].tolist()
    e_l = ends[rep_lines].tolist()
    t_min, _ = timeit(
        lambda: [line_key(blob[a:b]) for a, b in zip(s_l, e_l)],
        n=args.repeats,
    )
    report["digest_blake2b_s"] = round(t_min, 4)
    t_min, _ = timeit(
        lambda: interner.digests(
            v64[rep_lines], lengths[rep_lines], width, blob, s_l, e_l
        ),
        n=args.repeats,
    )
    report["digest_interned_s"] = round(t_min, 4)

    # ---- extract + assemble: the cache-hit serving path ------------------
    sets = load_builtin_pattern_sets()
    engine = AnalysisEngine(sets, ScoringConfig())
    report["patterns"] = sum(len(s.patterns or []) for s in sets)
    n = corpus.n_lines
    U = len(keys)
    # synthesize the post-cache unique bit matrix exactly as the cached
    # path would hold it (content of the bits doesn't change the cost;
    # use the real device-equivalent rows for honest record counts)
    bits_u = np.zeros((U, engine.bank.n_columns), dtype=bool)
    probe = engine.analyze(
        PodFailureData(pod={"metadata": {"name": "prof"}}, logs=logs)
    )
    assert probe.summary is not None
    fin_ref = engine.last_finalized

    def assemble():
        bits = bits_u[line_slot]  # unique rows → per-line fan-out
        return bits

    t_min, _ = timeit(assemble, n=args.repeats)
    report["assemble_s"] = round(t_min, 4)

    bits = bits_u[line_slot]

    def extract():
        return records_from_bits(bits, n, engine.bank, engine.tables)

    t_min, _ = timeit(extract, n=args.repeats)
    report["extract_s"] = round(t_min, 4)

    # ---- finalize: records → scores → factor rows ------------------------
    recs = engine._verify_approx(corpus, extract())
    freq_base = np.zeros(max(1, engine.bank.n_freq_slots), dtype=np.float64)
    freq_exists = np.zeros(max(1, engine.bank.n_freq_slots), dtype=bool)

    def finalize():
        return finalize_batch(
            engine.bank, engine.tables, engine.config, recs, n,
            freq_base, freq_exists,
        )

    t_min, _ = timeit(finalize, n=args.repeats)
    report["finalize_s"] = round(t_min, 4)

    if fin_ref is not None and len(fin_ref.scores):
        t_min, _ = timeit(
            lambda: fin_ref.factor_rows(engine.bank), n=args.repeats
        )
        report["factor_rows_s"] = round(t_min, 4)
        report["factor_rows_n"] = int(len(fin_ref.scores))

    report["host_total_scalar_s"] = round(
        report["ingest_scalar_s"] + report["key_scalar_s"], 4
    )
    report["host_total_vec_s"] = round(
        report["ingest_vec_s"] + report["key_vec_s"], 4
    )
    report["host_total_interned_s"] = round(
        report["ingest_vec_s"] + report["key_vec_interned_s"], 4
    )
    print(json.dumps(report))


if __name__ == "__main__":
    main()
