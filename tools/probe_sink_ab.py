"""Session-matched A/B of the Shift-Or stepper forms on the live
backend (the probe that decided the platform-split layout, PERF.md
§9d), all sharing the CURRENT bank's constants:

- v_ship:         the shipping stepper for this platform (TPU: bare
                  nh-carry hits; CPU: pair-composed sinks)
- v_perbyte_sink: per-byte sink update (only on a sink-layout bank)
- v_perbyte_hits: gate-free per-byte hits form on the current bank
- v_nosink_hits:  the bare 81-word layout rebuilt from scratch
- v_nosink_chain: bare layout + one 36-char chained literal (the
                  historical col-80 routing question)

Also times the bitglush shipping stepper alone so the cube split is
attributable in the same session. Prints one JSON line.

Usage: python tools/probe_sink_ab.py [--lines 200000] [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_common import pin_platform, timeit  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lines", type=int, default=200_000)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    pin_platform()
    import jax
    import jax.numpy as jnp

    import bench
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.native.ingest import Corpus
    from log_parser_tpu.ops.match import pack_byte_pairs
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    engine = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
    s = engine.matchers.shiftor
    corpus = Corpus(bench.build_corpus(args.lines))
    enc = corpus.encoded
    lines_tb = jnp.asarray(enc.u8.T)
    lens = jnp.asarray(enc.lengths)
    jax.block_until_ready((lines_tb, lens))
    B = int(lens.shape[0])
    report = {
        "platform": jax.devices()[0].platform,
        "rows": B,
        "T": int(lines_tb.shape[0]),
        "W": s.n_words,
    }

    def scan_of(step, init):
        @jax.jit
        def run(lines_tb, lens):
            pairs, ts = pack_byte_pairs(lines_tb)
            out, _ = jax.lax.scan(
                lambda c, xs: (step(c, xs[0][0], xs[0][1], xs[1]), None),
                init,
                (pairs, ts),
            )
            return out

        return lambda: jax.block_until_ready(run(lines_tb, lens))

    # -- v_ship: the shipping pair-composed sink stepper ----------------
    init, step, _fin = s.pair_stepper(B, lens)
    report["v_ship_s"] = round(timeit(scan_of(step, init), args.repeats), 4)

    # -- v_perbyte_sink: same sink semantics, one byte per update -------
    d0 = jnp.full((B, s.n_words), 0xFFFFFFFF, dtype=jnp.uint32)
    sc = s.start_clear[None, :]
    if s.sinks:
        not_sink = s.not_sink[None, :]

        def step_pb_sink(d, b1, b2, t):
            for b in (b1, b2):
                m = s._row_select(b)
                cand = (s._s1(d) & sc) | m
                d = cand & (d | not_sink)
            return d

        report["v_perbyte_sink_s"] = round(
            timeit(scan_of(step_pb_sink, d0), args.repeats), 4
        )

    # -- v_perbyte_hits: gate-free round-3 shape on the current bank ----
    e = s.end_mask[None, :]
    h0 = jnp.zeros((B, s.n_words), dtype=jnp.uint32)

    def step_pb_hits(carry, b1, b2, t):
        d, hits = carry
        for b in (b1, b2):
            m = s._row_select(b)
            d = (s._s1(d) & sc) | m
            hits = hits | ((~d) & e)
        return d, hits

    report["v_perbyte_hits_s"] = round(
        timeit(scan_of(step_pb_hits, (d0, h0)), args.repeats), 4
    )

    # -- v_nosink: round-3-shaped bank (alloc = m, no sink bits) --------
    import numpy as np

    bank = engine.matchers.bank
    flat = [
        (i, seq)
        for i in engine.matchers.shiftor_cols
        for seq in bank.columns[i].exact_seqs
    ]
    starts2: list[int] = []
    word_fill: list[int] = []
    for _, seq in flat:
        alloc = len(seq)
        if alloc > 32:
            w0 = len(word_fill)
            nw = (alloc + 31) // 32
            starts2.append(w0 * 32)
            word_fill.extend([32] * (nw - 1))
            word_fill.append(alloc - 32 * (nw - 1))
        else:
            w = next(
                (i for i, u in enumerate(word_fill) if u + alloc <= 32), None
            )
            if w is None:
                w = len(word_fill)
                word_fill.append(0)
            starts2.append(w * 32 + word_fill[w])
            word_fill[w] += alloc
    W2 = max(1, len(word_fill))
    mask2 = np.full((256, W2), 0xFFFFFFFF, dtype=np.uint32)
    sc2_np = np.full(W2, 0xFFFFFFFF, dtype=np.uint32)
    e2_np = np.zeros(W2, dtype=np.uint32)
    cont2 = np.zeros(W2, dtype=np.uint32)
    for (_, seq), g in zip(flat, starts2):
        sc2_np[g // 32] &= ~np.uint32(1 << (g % 32))
        for j, byteset in enumerate(seq):
            p = g + j
            bit = np.uint32(1 << (p % 32))
            for c in byteset:
                if c != 0:
                    mask2[c, p // 32] &= ~bit
        for w in range(g // 32 + 1, (g + len(seq) - 1) // 32 + 1):
            cont2[w] |= np.uint32(1)
        ee = g + len(seq) - 1
        e2_np[ee // 32] |= np.uint32(1 << (ee % 32))
    report["W_nosink"] = W2
    mask2_j = jnp.asarray(mask2)
    sc2_j = jnp.asarray(sc2_np)[None, :]
    e2_j = jnp.asarray(e2_np)[None, :]
    cont2_j = jnp.asarray(cont2)[None, :]
    has_chains2 = bool(cont2.any())
    d02 = jnp.full((B, W2), 0xFFFFFFFF, dtype=jnp.uint32)
    h02 = jnp.zeros((B, W2), dtype=jnp.uint32)

    def s1_2(x):
        sh = x << 1
        if has_chains2:
            carry = jnp.concatenate(
                [jnp.zeros_like(x[:, :1]), x[:, :-1] >> 31], axis=1
            )
            sh = sh | (carry & cont2_j)
        return sh

    def step_nosink(carry, b1, b2, t):
        d, hits = carry
        for b in (b1, b2):
            m = jnp.take(mask2_j, b.astype(jnp.int32), axis=0)
            d = (s1_2(d) & sc2_j) | m
            hits = hits | ((~d) & e2_j)
        return d, hits

    report["v_nosink_hits_s"] = round(
        timeit(scan_of(step_nosink, (d02, h02)), args.repeats), 4
    )

    # -- v_nosink_chain: same bank + one 36-char chained literal --------
    # (the col-80 routing question: what does turning the carry on for
    # the whole bank cost when a >32-bit literal joins it?)
    W3 = W2 + 2
    mask3 = np.pad(mask2, ((0, 0), (0, 2)), constant_values=0xFFFFFFFF)
    sc3 = np.pad(sc2_np, (0, 2), constant_values=0xFFFFFFFF)
    e3 = np.pad(e2_np, (0, 2))
    cont3 = np.pad(cont2, (0, 2))
    g0 = W2 * 32
    sc3[W2] &= ~np.uint32(1)
    lit = b"Back-off restarting failed container"
    for j, ch in enumerate(lit):
        p = g0 + j
        mask3[ch, p // 32] &= ~np.uint32(1 << (p % 32))
    cont3[W2 + 1] |= 1
    e3[(g0 + 35) // 32] |= np.uint32(1 << ((g0 + 35) % 32))
    mask3_j = jnp.asarray(mask3)
    sc3_j = jnp.asarray(sc3)[None, :]
    e3_j = jnp.asarray(e3)[None, :]
    cont3_j = jnp.asarray(cont3)[None, :]
    d03 = jnp.full((B, W3), 0xFFFFFFFF, dtype=jnp.uint32)
    h03 = jnp.zeros((B, W3), dtype=jnp.uint32)

    def s1_3(x):
        carry = jnp.concatenate(
            [jnp.zeros_like(x[:, :1]), x[:, :-1] >> 31], axis=1
        )
        return (x << 1) | (carry & cont3_j)

    def step_chain(carry, b1, b2, t):
        d, hits = carry
        for b in (b1, b2):
            m = jnp.take(mask3_j, b.astype(jnp.int32), axis=0)
            d = (s1_3(d) & sc3_j) | m
            hits = hits | ((~d) & e3_j)
        return d, hits

    report["v_nosink_chain_s"] = round(
        timeit(scan_of(step_chain, (d03, h03)), args.repeats), 4
    )

    # -- bitglush shipping stepper, same session ------------------------
    g = engine.matchers.bitglush
    if g is not None:
        gi, gstep, _gf = g.pair_stepper(B, lens)
        report["bitglush_ship_s"] = round(
            timeit(scan_of(gstep, gi), args.repeats), 4
        )
        report["bitglush_words"] = g.n_words

    print(json.dumps(report))


if __name__ == "__main__":
    main()
