"""Isolate per-tier cube cost: run each matcher tier's stepper alone in
its own scan over the config-2 corpus, plus the full fused cube, so the
cube's time can be attributed (PERF.md §1 methodology).

Usage: python tools/probe_tiers.py [--lines 200000] [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_common import timeit  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lines", type=int, default=200_000)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import bench
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.native.ingest import Corpus
    from log_parser_tpu.ops.match import pack_byte_pairs
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    engine = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
    m = engine.matchers
    corpus = Corpus(bench.build_corpus(args.lines))
    enc = corpus.encoded
    lines_tb = jnp.asarray(enc.u8.T)
    lens = jnp.asarray(enc.lengths)
    jax.block_until_ready((lines_tb, lens))
    B = int(lens.shape[0])
    report = {
        "platform": jax.devices()[0].platform,
        "rows": B,
        "T": int(lines_tb.shape[0]),
    }

    def scan_only(stepper_fns):
        """Compile ONE scan advancing the given steppers' carries."""
        inits = tuple(s[0] for s in stepper_fns)

        @jax.jit
        def run(lines_tb, lens):
            pairs, ts = pack_byte_pairs(lines_tb)

            def step(carries, xs):
                pair, t = xs
                return tuple(
                    s[1](c, pair[0], pair[1], t)
                    for s, c in zip(stepper_fns, carries)
                ), None

            finals, _ = jax.lax.scan(step, inits, (pairs, ts))
            return finals

        return lambda: jax.block_until_ready(run(lines_tb, lens))

    # each multi-DFA group alone, then all groups, then shiftor, then all
    for gi, g in enumerate(m.multi_groups):
        fn = scan_only([g.pair_stepper(B, lens)])
        report[f"multi_g{gi}_s"] = round(timeit(fn, n=args.repeats), 4)
        report[f"multi_g{gi}_states"] = g.n_states
    if m.multi_groups:
        fn = scan_only([g.pair_stepper(B, lens) for g in m.multi_groups])
        report["multi_separate_s"] = round(timeit(fn, n=args.repeats), 4)
    if m.shiftor is not None:
        fn = scan_only([m.shiftor.pair_stepper(B, lens)])
        report["shiftor_s"] = round(timeit(fn, n=args.repeats), 4)
        report["shiftor_words"] = m.shiftor.n_words
    if m.bitglush is not None:
        fn = scan_only([m.bitglush.pair_stepper(B, lens)])
        report["bitglush_s"] = round(timeit(fn, n=args.repeats), 4)
        report["bitglush_words"] = m.bitglush.n_words

    cube_jit = jax.jit(m.cube)
    full = lambda: jax.block_until_ready(cube_jit(lines_tb, lens))
    report["cube_s"] = round(timeit(full, n=args.repeats), 4)

    # cluster A/B LAST: on CPU the shipped path has no cluster, and
    # building a throwaway one re-points every group's table at the
    # concatenated buffer (MultiDfaCluster adopts tables) — anything
    # measured after this line is a hybrid shape, so nothing is
    if m.multi_groups:
        from log_parser_tpu.ops.match import MultiDfaCluster

        cluster = m.multi_cluster or MultiDfaCluster(m.multi_groups)
        fn = scan_only([cluster.pair_stepper(B, lens)])
        report["multi_cluster_s"] = round(timeit(fn, n=args.repeats), 4)

    print(json.dumps(report))


if __name__ == "__main__":
    main()
