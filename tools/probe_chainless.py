"""Timing probe that decided the first-fit chainless bitglush layout
(PERF.md §9d: carry removal measured 0.162 -> 0.064 s on v5e; the
shipping stepper has been chainless since). Still useful for width
sensitivity on the live backend:

- v_ship:        the shipping stepper (now first-fit, carry-free on
                 chainless banks)
- v_nocarry:     the synthetic carry-free form at the bank's width
                 (≈ v_ship on a chainless bank — the historical A/B)
- v_nocarry_w:   same ops at a padded width (fragmentation estimate,
                 default 112 words)

Usage: python tools/probe_chainless.py [--lines 200000] [--width 112]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_common import pin_platform, timeit  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lines", type=int, default=200_000)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--width", type=int, default=112)
    args = ap.parse_args()

    pin_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.native.ingest import Corpus
    from log_parser_tpu.ops.match import pack_byte_pairs
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    engine = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
    g = engine.matchers.bitglush
    corpus = Corpus(bench.build_corpus(args.lines))
    enc = corpus.encoded
    lines_tb = jnp.asarray(enc.u8.T)
    lens = jnp.asarray(enc.lengths)
    jax.block_until_ready((lines_tb, lens))
    B = int(lens.shape[0])
    report = {
        "platform": jax.devices()[0].platform,
        "rows": B,
        "T": int(lines_tb.shape[0]),
        "W": g.n_words,
        "max_skip_run": g.max_skip_run,
    }

    def scan_of(step, init):
        @jax.jit
        def run(lines_tb, lens):
            pairs, ts = pack_byte_pairs(lines_tb)
            out, _ = jax.lax.scan(
                lambda c, xs: (step(c, xs[0][0], xs[0][1], xs[1]), None),
                init,
                (pairs, ts),
            )
            return out

        return lambda: jax.block_until_ready(run(lines_tb, lens))

    gi, gstep, _gf = g.pair_stepper(B, lens)
    report["v_ship_s"] = round(timeit(scan_of(gstep, gi), args.repeats), 4)

    def chainless_stepper(W, bmask, s_all, s, k, ss):
        # mirrors the shipping (guard-bit, carry-free) sink stepper
        init = (jnp.zeros((B, W), jnp.uint32), jnp.zeros((B,), bool))

        def one(d, pw, b, pos):
            c = (d << 1) | jnp.where(pos == 0, s_all, s)
            for _ in range(g.max_skip_run):
                c = c | ((c & k) << 1)
            brow = jnp.take(bmask, b.astype(jnp.int32), axis=0)
            return brow & (c | (d & ss)), pw

        def step(carry, b1, b2, t):
            d, pw = carry
            p0 = 2 * t
            d, pw = one(d, pw, b1, p0)
            d, pw = one(d, pw, b2, p0 + 1)
            return (d, pw)

        return init, step

    # same width, no carry
    init, step = chainless_stepper(
        g.n_words, g.bmask, g.start_all, g.start, g.k_skip, g.s_static
    )
    report["v_nocarry_s"] = round(timeit(scan_of(step, init), args.repeats), 4)

    # padded width, no carry (first-fit fragmentation estimate)
    Wp = args.width
    pad = Wp - g.n_words
    if pad > 0:
        bm = jnp.asarray(
            np.pad(np.asarray(g.bmask), ((0, 0), (0, pad)))
        )
        padv = lambda a: jnp.asarray(  # noqa: E731
            np.pad(np.asarray(a), (0, pad))
        )
        init, step = chainless_stepper(
            Wp, bm, padv(g.start_all), padv(g.start),
            padv(g.k_skip), padv(g.s_static),
        )
        report["v_nocarry_wide_s"] = round(
            timeit(scan_of(step, init), args.repeats), 4
        )
        report["wide_W"] = Wp

    print(json.dumps(report))


if __name__ == "__main__":
    main()
