"""Extended engine-vs-golden parity sweep.

Reuses the suite's own generators (tests/test_engine_parity.py) over an
arbitrary seed range — the suite pins small seed sets for CI speed; this
tool runs the long tail on demand. Every seed builds a random pattern
library, then runs corpora through BOTH a device engine (CPU backend,
fallback disabled) and the pure-host golden analyzer, asserting
event-for-event equality and score deltas <= 1e-9 with evolving
cross-request frequency state.

Three modes:
- default: single-device ``AnalysisEngine`` — mirrors
  ``test_random_library_parity`` (suite seeds 0..7).
- ``--sharded``: ``ShardedEngine`` over the virtual 8-device mesh
  (shard_map halos, all_gather chains, cross-shard frequency prefix) —
  mirrors ``test_random_parity_small_batches`` (suite seeds 1000..1003;
  pass raw offsets, the tool adds nothing).
- ``--pattern-sharded``: ``PatternShardedEngine`` with per-seed block
  counts (the pattern-axis / TP-analogue path, stable (line, pattern)
  merge) — mirrors ``test_pattern_sharded.test_random_parity_vs_golden``
  (suite seeds 9000..9002 x n_blocks {1,3,4}).
- ``--long``: single-device engine under the TPU tier policy (bit tiers
  on) with >31-char-literal libraries and prefix-poisoned corpora — the
  bitglush truncation + host verify / distance-repair paths; mirrors
  ``test_random_long_literal_parity_bit_policy`` (suite seeds
  31000..31005).
- ``--admin``: NOT a parity sweep — a rejection sweep over the admin
  surface. An in-process ``ParseServer`` takes seeded malformed bodies
  (broken YAML, wrong JSON shapes, negative/NaN ages, oversized
  payloads) on ``POST /patterns/reload`` and ``POST /frequency/restore``
  and every response must be 400/409/413 with the engine provably
  untouched: same bank object, same frequency stats, same reload epoch.
- ``--ingest``: NOT a parity sweep — a robustness sweep over the parse
  ingest path. An in-process ``ParseServer`` takes seeded hostile
  ``POST /parse`` traffic — invalid-UTF-8 raw bodies, NUL bytes, lone
  surrogates (``\\udXXX`` escapes survive json.loads unpaired),
  control-character soup, binary-ish blobs, and multi-MiB single lines —
  and every request must answer 200 or a structured 4xx JSON error,
  never an unhandled 500; on every reject the engine must be provably
  untouched (same bank object, same frequency stats). Runs with fallback
  DISABLED, so a hostile input that faults the device step surfaces as a
  500 finding instead of hiding behind golden.
- ``--stream``: adversarial-chunking sweep over the streaming session
  layer (runtime/stream.py). Seeded corpora — CRLF endings, multi-byte
  UTF-8, raw invalid bytes, NULs, control soup — are fed through
  sessions under hostile chunkings (1-byte chunks, empty chunks, splits
  inside UTF-8 sequences and inside ``\\r\\n``); every session must
  produce only well-formed frames, end in exactly one terminal ``final``
  (or structured ``error``) frame, release its admission slot, and the
  final result must be bit-identical to one-shot ``analyze()`` on the
  reassembled blob with serially-equivalent frequency state. A periodic
  raw-socket pass sends garbage HTTP chunk framing at
  ``POST /parse/stream`` and must get a structured ``bad-frame`` error
  frame with the server still healthy — a wedged session/server is the
  finding.
- ``--miner``: NOT a parity sweep — a robustness sweep over the template
  miner (log_parser_tpu/mining/). Seeded hostile miss lines — invalid
  UTF-8, NULs, 1 MB single lines, regex-metacharacter soup, control
  bytes — go through the REAL pipeline (tap offer → pump → cluster →
  synthesize → vet) at ``min_support=1``: the miner must never raise
  (``errors`` stays 0), the serving bank must stay object-identical in
  review mode, and every regex the synthesizer emits must re-parse
  through the bank's own compile entry points (``compile_java_regex``,
  ``classify_regex`` off the skipped tier).

- ``--router``: NOT a parity sweep — a robustness sweep over the fleet
  router front-door (log_parser_tpu/fleet/router.py). A real router
  proxies to a real in-process backend while seeded hostile traffic
  hits the edge: hostile ``X-Tenant`` headers (traversal, control soup,
  overlong ids — refused 400 AT the router, never forwarded), hostile
  request bodies and paths (relayed verbatim, the backend's verdict
  passed through), malformed ``POST /fleet/override`` bodies (400 with
  the ring provably untouched), and raw-socket garbage at the router
  port. After every seed the router must still answer ``/q/health`` UP,
  the ring must still hold its backend, and a clean ``POST /parse``
  must still round-trip — a wedged or 5xx-ing router is the finding.

Usage: python tools/fuzz_sweep.py [--start N] [--end M]
       [--sharded | --pattern-sharded | --long | --admin | --ingest |
        --stream | --miner | --router | --quick]
(defaults per mode: 8..200 single-device, 1004..1054 sharded,
9003..9053 pattern-sharded, 31006..31056 long — a bare run reproduces
the documented records below; --end exclusive)
``--quick`` is the CI tier: the first 5 seeds of EVERY mode in one
process (~2 min), run as a workflow job after the suite so a parity
regression in any engine mode fails the PR (VERDICT r4 #5).
Record (round-4 engine, 2026-07-30): default seeds 8..199 (192 libraries,
576 corpora) clean; sharded seeds 1004..1053 (50 libraries) clean;
pattern-sharded seeds 9003..9052 (50 libraries, n_blocks cycling 1/3/4)
clean.
Record (round-4 engine, 2026-07-31, truncation/repair build): long seeds
31006..31055 (50 libraries, 150 corpora) clean; default 8..199 (192
libraries, 576 corpora), sharded 1004..1053, and pattern-sharded
9003..9052 all re-run clean on the same build.
Record (round-5 engine, 2026-08-01 — native batched regex pipeline,
pack-file cache, exact bitglush pricing, \\Q quoting): ALL FOUR full
sweeps clean — default 8..199 (192 libraries), sharded 1004..1053,
pattern-sharded 9003..9052, long 31006..31055.
Record (round-9 engine, 2026-08-05 — streaming session layer): stream
seeds 61000..61049 (50 corpora x 3 chunkings, periodic garbage-framing
passes) clean.
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
# append-if-missing (the conftest idiom), NOT setdefault: a pre-set
# XLA_FLAGS would otherwise silently drop the 8-device topology and turn
# the --sharded sweep into a vacuous 1-device pass (make_mesh slices
# devices[:n] without complaint)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["LOG_PARSER_TPU_NO_FALLBACK"] = "1"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))


def main() -> int:
    if sys.flags.optimize:
        # the parity checks (assert_results_match, shared with the test
        # suite) are assert-based; -O would strip them and report a
        # vacuous clean pass
        sys.exit("refusing to run under python -O: parity asserts would be stripped")
    ap = argparse.ArgumentParser()
    ap.add_argument("--start", type=int, default=None)
    ap.add_argument("--end", type=int, default=None)
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--sharded", action="store_true")
    mode.add_argument("--pattern-sharded", action="store_true")
    mode.add_argument("--long", action="store_true")
    mode.add_argument("--admin", action="store_true")
    mode.add_argument("--ingest", action="store_true")
    mode.add_argument("--stream", action="store_true")
    mode.add_argument("--miner", action="store_true")
    mode.add_argument("--router", action="store_true")
    mode.add_argument(
        "--quick",
        action="store_true",
        help="CI tier: 5 seeds of EVERY mode (VERDICT r4 #5 — a parity "
        "regression in any engine mode fails the PR, not a future "
        "manual sweep); --start/--end are ignored",
    )
    args = ap.parse_args()
    if args.quick:
        rc = 0
        for m in ("default", "sharded", "pattern-sharded", "long"):
            start = _MODE_DEFAULTS[m][0]
            print(f"== quick sweep: {m} seeds {start}..{start + 4}", flush=True)
            rc |= run_sweep(m, start, start + 5)
        start = _MODE_DEFAULTS["admin"][0]
        print(f"== quick sweep: admin seeds {start}..{start + 4}", flush=True)
        rc |= run_admin_sweep(start, start + 5)
        start = _MODE_DEFAULTS["ingest"][0]
        print(f"== quick sweep: ingest seeds {start}..{start + 4}", flush=True)
        rc |= run_ingest_sweep(start, start + 5)
        start = _MODE_DEFAULTS["stream"][0]
        print(f"== quick sweep: stream seeds {start}..{start + 4}", flush=True)
        rc |= run_stream_sweep(start, start + 5)
        start = _MODE_DEFAULTS["miner"][0]
        print(f"== quick sweep: miner seeds {start}..{start + 4}", flush=True)
        rc |= run_miner_sweep(start, start + 5)
        start = _MODE_DEFAULTS["router"][0]
        print(f"== quick sweep: router seeds {start}..{start + 4}", flush=True)
        rc |= run_router_sweep(start, start + 5)
        return rc
    if args.router:
        start, end = _MODE_DEFAULTS["router"]
        if args.start is not None:
            start = args.start
        if args.end is not None:
            end = args.end
        return run_router_sweep(start, end)
    if args.miner:
        start, end = _MODE_DEFAULTS["miner"]
        if args.start is not None:
            start = args.start
        if args.end is not None:
            end = args.end
        return run_miner_sweep(start, end)
    if args.stream:
        start, end = _MODE_DEFAULTS["stream"]
        if args.start is not None:
            start = args.start
        if args.end is not None:
            end = args.end
        return run_stream_sweep(start, end)
    if args.ingest:
        start, end = _MODE_DEFAULTS["ingest"]
        if args.start is not None:
            start = args.start
        if args.end is not None:
            end = args.end
        return run_ingest_sweep(start, end)
    if args.admin:
        start, end = _MODE_DEFAULTS["admin"]
        if args.start is not None:
            start = args.start
        if args.end is not None:
            end = args.end
        return run_admin_sweep(start, end)
    m = (
        "sharded"
        if args.sharded
        else "pattern-sharded"
        if args.pattern_sharded
        else "long"
        if args.long
        else "default"
    )
    # per-mode defaults: a bare run reproduces the documented record,
    # and each mode's seed space stays disjoint from the suite's pinned
    # seeds and the other modes' sweeps
    start, end = _MODE_DEFAULTS[m]
    if args.start is not None:
        start = args.start
    if args.end is not None:
        end = args.end
    return run_sweep(m, start, end)


_MODE_DEFAULTS = {
    "default": (8, 200),
    "sharded": (1004, 1054),
    "pattern-sharded": (9003, 9053),
    "long": (31006, 31056),
    "admin": (41000, 41050),
    "ingest": (51000, 51050),
    "stream": (61000, 61050),
    "miner": (71000, 71024),
    "router": (81000, 81050),
}


def _admin_reload_bodies(rng: "random.Random") -> list[bytes]:
    """Seeded malformed YAML for POST /patterns/reload. Every body is
    malformed BY SHAPE (not by luck), so a 200 is always a real finding:
    the engine swapped banks on garbage."""
    junk = "".join(rng.choice("abcxyz(){}<>|&*?!") for _ in range(rng.randrange(1, 12)))
    n = rng.randrange(1, 9)
    return [
        b"\xff\xfe" + junk.encode() * n,                   # not UTF-8 -> 400
        b"{unclosed: [" + junk.encode(),                   # YAML error
        f"- {rng.randrange(1 << 30)}\n- {n}\n".encode(),   # docs: list of ints
        f"{junk}: [unbalanced\n".encode(),                 # YAML error
        f"scalar-{junk}".encode(),                         # non-mapping doc
        f"name: {junk}\npatterns: {n}\n".encode(),         # patterns not a list
        f"patterns:\n- {junk}\n- {n}\n".encode(),          # members not mappings
        b"#" * ((4 << 20) + 1 + n),                        # > _ADMIN_MAX_BODY -> 413
    ]


def _admin_restore_bodies(rng: "random.Random") -> list[bytes]:
    """Seeded malformed JSON for POST /frequency/restore: wrong shapes,
    negative/NaN ages, bad envelopes, oversized."""
    pid = "".join(rng.choice("abcdefgh") for _ in range(rng.randrange(1, 8)))
    neg = -rng.random() - 1e-6
    return [
        b"not json " + pid.encode(),                       # parse error
        b"[1, 2, 3]",                                      # not a mapping
        f'{{"{pid}": 1}}'.encode(),                        # value not a list
        f'{{"{pid}": ["x", 1]}}'.encode(),                 # non-numeric age
        f'{{"{pid}": [{neg}]}}'.encode(),                  # negative age
        f'{{"{pid}": [NaN]}}'.encode(),                    # NaN never >= 0
        f'{{"ages": {{"{pid}": [{neg}]}}, "epoch": 0}}'.encode(),  # bad envelope
        f'{{"ages": "{pid}", "epoch": 0}}'.encode(),       # envelope, ages not dict
        b'{"' + pid.encode() + b'": [' + b"0," * (3 << 20) + b"0]}",  # oversized
    ]


def run_admin_sweep(start: int, end: int) -> int:
    """Fuzz the admin mutation surface of an in-process ParseServer: every
    malformed body must be rejected (400/409/413) and the engine must be
    bit-for-bit untouched — same bank object identity, same frequency
    stats, same reload epoch. Explicit raises (not asserts) so the
    startup -O guard is belt-and-braces here."""
    import json
    import random
    import threading
    import urllib.error
    import urllib.request

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.patterns import load_pattern_directory
    from log_parser_tpu.runtime import AnalysisEngine
    from log_parser_tpu.runtime.reload import PatternReloader
    from log_parser_tpu.serve.http import make_server

    pattern_dir = os.path.join(_REPO, "log_parser_tpu", "patterns", "builtin")
    engine = AnalysisEngine(load_pattern_directory(pattern_dir), ScoringConfig())
    server = make_server(engine, "127.0.0.1", 0)
    server.reloader = PatternReloader(engine, pattern_dir)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}"

    def post(path: str, body: bytes) -> int:
        if len(body) > (4 << 20):
            # the server 413s from Content-Length alone, before draining
            # the body; urllib would die on the resulting broken pipe, so
            # declare the length raw and never send the payload
            import socket

            host, port = server.server_address[:2]
            with socket.create_connection((host, port), timeout=60) as sock:
                sock.sendall(
                    b"POST %s HTTP/1.1\r\nHost: fuzz\r\n"
                    b"Content-Length: %d\r\nConnection: close\r\n\r\n"
                    % (path.encode(), len(body))
                )
                raw = b""
                while True:
                    chunk = sock.recv(65536)
                    if not chunk:
                        break
                    raw = raw + chunk
            return int(raw.split(b" ", 2)[1])
        req = urllib.request.Request(
            url + path, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                resp.read()
                return resp.status
        except urllib.error.HTTPError as e:
            e.read()
            return e.code

    # prime real frequency state so "stats unchanged" is a non-vacuous check
    engine.analyze(
        PodFailureData(
            pod={"metadata": {"name": "fuzz-admin"}},
            logs="INFO boot\njava.lang.OutOfMemoryError: heap\nINFO after",
        )
    )
    base_bank = engine.bank
    base_stats = json.dumps(
        engine.frequency.get_frequency_statistics(), sort_keys=True
    )
    base_epoch = engine.reload_epoch

    t0 = time.time()
    fails: list[tuple[int, str]] = []
    try:
        for seed in range(start, end):
            rng = random.Random(seed)
            cases = [("/patterns/reload", b) for b in _admin_reload_bodies(rng)]
            cases += [("/frequency/restore", b) for b in _admin_restore_bodies(rng)]
            for path, body in cases:
                try:
                    status = post(path, body)
                    if status not in (400, 409, 413):
                        raise AssertionError(
                            f"{path} accepted garbage with {status}: {body[:80]!r}"
                        )
                    if engine.bank is not base_bank:
                        raise AssertionError(f"{path} swapped the bank on a reject")
                    stats = json.dumps(
                        engine.frequency.get_frequency_statistics(), sort_keys=True
                    )
                    if stats != base_stats:
                        raise AssertionError(
                            f"{path} mutated frequency state on a reject: "
                            f"{stats} != {base_stats}"
                        )
                    if engine.reload_epoch != base_epoch:
                        raise AssertionError(f"{path} bumped the reload epoch")
                except Exception as exc:  # noqa: BLE001 - recorded, sweep continues
                    fails.append((seed, repr(exc)[:300]))
                    print(f"SEED {seed} FAILED: {exc!r}", flush=True)
            if seed % 20 == 0:
                print(f"seed {seed} done ({time.time() - t0:.0f}s)", flush=True)
    finally:
        server.shutdown()
        server.server_close()
    print(f"DONE admin seeds {start}..{end - 1} fails: {fails} "
          f"({time.time() - t0:.0f}s)")
    return 1 if fails else 0


def _ingest_logs_cases(rng: "random.Random") -> list[str]:
    """Seeded hostile log blobs for POST /parse — valid JSON strings whose
    CONTENT is hostile to the ingest/encode path: NULs, lone surrogates,
    control soup, binary-ish bytes, and one multi-MiB single line."""
    n = rng.randrange(1, 6)
    junk = "".join(chr(rng.randrange(0x20, 0x7F)) for _ in range(16))
    return [
        # content NUL bytes mid-line (needs_host NUL rule)
        f"INFO {junk}\nbad\x00line\x00here\nINFO after" * n,
        # lone surrogates: json.dumps escapes them, json.loads round-trips
        # them unpaired — the str the engine sees cannot utf-8 encode
        f"lead \ud800 trail\n{junk}\npair \udfff\ud800 reversed",
        # control-character soup + carriage returns
        "".join(chr(rng.randrange(0, 32)) for _ in range(64)) + "\n" + junk,
        # binary-ish: every latin-1 code point, shuffled
        "".join(map(chr, rng.sample(range(256), 256))) * n,
        # multi-MiB single line, no newline (capped-width tail re-match)
        junk * ((2 << 20) // len(junk)),
        # empty and whitespace-only corpora
        rng.choice(["", " ", "\n" * rng.randrange(1, 9), "\x00"]),
    ]


def run_ingest_sweep(start: int, end: int) -> int:
    """Fuzz the parse ingest path of an in-process ParseServer: hostile
    bodies must answer 200 or a STRUCTURED 4xx (JSON with an "error" key),
    never an unhandled 500, and a reject must leave the engine untouched.
    Fallback stays disabled (module env), so a device fault caused by
    hostile input is a 500 finding, not a silent golden save."""
    import json
    import random
    import threading
    import urllib.error
    import urllib.request

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.patterns import load_pattern_directory
    from log_parser_tpu.runtime import AnalysisEngine
    from log_parser_tpu.serve.http import make_server

    pattern_dir = os.path.join(_REPO, "log_parser_tpu", "patterns", "builtin")
    engine = AnalysisEngine(load_pattern_directory(pattern_dir), ScoringConfig())
    server = make_server(engine, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}/parse"

    def post(body: bytes) -> tuple[int, bytes]:
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def freq_stats() -> str:
        return json.dumps(
            engine.frequency.get_frequency_statistics(), sort_keys=True
        )

    base_bank = engine.bank
    t0 = time.time()
    fails: list[tuple[int, str]] = []
    try:
        for seed in range(start, end):
            rng = random.Random(seed)
            bodies: list[bytes] = [
                # raw invalid UTF-8 / non-JSON bodies -> 400
                bytes(rng.randrange(128, 256) for _ in range(rng.randrange(1, 64))),
                b"\xff\xfe{" + bytes([rng.randrange(256)]) * 8,
                b"[1,2,3]",                       # JSON, wrong shape
                b'{"pod": null, "logs": "x"}',    # null pod -> 400
            ] + [
                json.dumps(
                    {"pod": {"metadata": {"name": f"fuzz-{seed}"}}, "logs": logs}
                ).encode("utf-8")
                for logs in _ingest_logs_cases(rng)
            ]
            for body in bodies:
                before = freq_stats()
                try:
                    status, payload = post(body)
                    if status == 200:
                        continue  # legitimate parse; state may evolve
                    if not 400 <= status < 500:
                        raise AssertionError(
                            f"unstructured failure {status}: {body[:80]!r}"
                        )
                    err = json.loads(payload)
                    if not isinstance(err, dict) or "error" not in err:
                        raise AssertionError(
                            f"4xx without structured error: {payload[:120]!r}"
                        )
                    if engine.bank is not base_bank:
                        raise AssertionError("reject swapped the bank")
                    if freq_stats() != before:
                        raise AssertionError(
                            f"reject mutated frequency state: {body[:80]!r}"
                        )
                except Exception as exc:  # noqa: BLE001 - recorded, sweep continues
                    fails.append((seed, repr(exc)[:300]))
                    print(f"SEED {seed} FAILED: {exc!r}", flush=True)
            if seed % 10 == 0:
                print(f"seed {seed} done ({time.time() - t0:.0f}s)", flush=True)
    finally:
        server.shutdown()
        server.server_close()
    print(f"DONE ingest seeds {start}..{end - 1} fails: {fails} "
          f"({time.time() - t0:.0f}s)")
    return 1 if fails else 0


def _stream_corpus(rng: "random.Random") -> bytes:
    """Seeded hostile byte corpus for the stream sweep: LF/CRLF mixes,
    multi-byte UTF-8, raw invalid bytes, NULs, control characters,
    over-budget lines, and real matching lines — ending sometimes on a
    dangling ``\\r`` or a truncated multi-byte sequence."""
    parts: list[bytes] = []
    for _ in range(rng.randrange(2, 14)):
        kind = rng.randrange(7)
        if kind == 0:
            parts.append(b"java.lang.OutOfMemoryError: Java heap space")
        elif kind == 1:
            parts.append(
                ("café über 你好 \U0001f600"
                 * rng.randrange(1, 3)).encode()
            )
        elif kind == 2:  # invalid UTF-8 runs -> U+FFFD, split-invariantly
            parts.append(
                bytes(rng.randrange(128, 256)
                      for _ in range(rng.randrange(1, 24)))
            )
        elif kind == 3:  # content NUL + control bytes (needs_host lines)
            parts.append(b"bad\x00nul" + bytes([rng.randrange(1, 32)]) * 4)
        elif kind == 4:
            parts.append(
                "".join(chr(rng.randrange(0x20, 0x7F))
                        for _ in range(rng.randrange(0, 40))).encode()
            )
        elif kind == 5:  # may exceed the per-line device budget
            parts.append(b"x" * rng.randrange(100, 5000))
        else:
            parts.append(b"OutOfMemoryError unable to create new native thread")
        parts.append(rng.choice([b"\n", b"\r\n"]))
    blob = b"".join(parts)
    if rng.random() < 0.3:
        blob = blob[: -rng.randrange(1, 3)]  # dangling tail / lone \r
    if rng.random() < 0.25:
        blob += "€".encode()[: rng.randrange(1, 3)]  # truncated sequence
    return blob


def _stream_chunkings(
    rng: "random.Random", data: bytes
) -> list[list[bytes]]:
    """Adversarial chunkings of one corpus: byte-at-a-time, random chunks
    with empties interspersed, and cuts placed exactly at every non-ASCII
    byte and every ``\\r``/``\\n`` — guaranteed splits inside multi-byte
    sequences and inside ``\\r\\n`` pairs."""
    outs: list[list[bytes]] = []
    if len(data) <= 400:
        outs.append([data[i : i + 1] for i in range(len(data))])
    chunks: list[bytes] = []
    i = 0
    while i < len(data):
        if rng.random() < 0.15:
            chunks.append(b"")
        n = rng.randrange(1, 17)
        chunks.append(data[i : i + n])
        i += n
    chunks.append(b"")
    outs.append(chunks)
    cuts = sorted(
        {i for i, b in enumerate(data) if b >= 0x80 or b in (0x0D, 0x0A)}
        | {0, len(data)}
    )
    outs.append([data[a:b] for a, b in zip(cuts, cuts[1:]) if a < b])
    return outs


def run_stream_sweep(start: int, end: int) -> int:
    """Fuzz the streaming session layer under adversarial chunkings: every
    session must produce only well-formed frames, terminate in exactly one
    ``final`` (or structured ``error``) frame, release its admission slot,
    and close bit-identical to one-shot ``analyze()`` on the reassembled
    blob — with frequency state staying serially equivalent between the
    streamed engine and a reference engine fed the same blobs. A periodic
    raw-socket pass throws garbage HTTP chunk framing at
    ``POST /parse/stream`` and must get a structured ``bad-frame`` error
    with the server still answering ``/health`` — a wedged session or
    server is the finding."""
    import json
    import random
    import socket
    import threading
    import urllib.request

    from tests.conftest import FakeClock

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.patterns import load_pattern_directory
    from log_parser_tpu.runtime import AnalysisEngine
    from log_parser_tpu.runtime.stream import FRAME_TYPES
    from log_parser_tpu.serve.admission import shared_gate
    from log_parser_tpu.serve.http import make_server

    pattern_dir = os.path.join(_REPO, "log_parser_tpu", "patterns", "builtin")
    sets = load_pattern_directory(pattern_dir)
    engine = AnalysisEngine(sets, ScoringConfig(), clock=FakeClock())
    ref = AnalysisEngine(sets, ScoringConfig(), clock=FakeClock())
    server = make_server(engine, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    mgr = server.get_stream_manager()
    host, port = server.server_address[:2]

    def events_of(result_dict: dict) -> list[tuple]:
        return [
            (e["lineNumber"], e["matchedPattern"]["id"], e["score"])
            for e in result_dict.get("events", [])
        ]

    def run_session(chunks: list[bytes]) -> list[dict]:
        sess = mgr.open()
        frames: list[dict] = []
        for c in chunks:
            frames += sess.feed(c)
            if sess.closed:
                break
        if not sess.closed:
            frames += sess.close()
        if not sess.closed:
            raise AssertionError("session wedged: close() left it open")
        return frames

    def garbage_framing_pass() -> None:
        with socket.create_connection((host, port), timeout=60) as sock:
            sock.sendall(
                b"POST /parse/stream HTTP/1.1\r\nHost: fuzz\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"
                b"3\r\nOOM\r\nZZZ\r\n"
            )
            raw = b""
            while True:
                part = sock.recv(65536)
                if not part:
                    break
                raw += part
        body = raw.split(b"\r\n\r\n", 1)[1]
        err = [
            json.loads(ln)
            for ln in body.splitlines()
            if ln.strip() and json.loads(ln).get("type") == "error"
        ]
        if not err or err[-1]["reason"] != "bad-frame":
            raise AssertionError(f"garbage framing not contained: {body!r}")
        with urllib.request.urlopen(
            f"http://{host}:{port}/health", timeout=60
        ) as resp:
            if resp.status != 200:
                raise AssertionError("server unhealthy after garbage framing")

    t0 = time.time()
    fails: list[tuple[int, str]] = []
    try:
        for seed in range(start, end):
            rng = random.Random(seed)
            try:
                data = _stream_corpus(rng)
                blob = data.decode("utf-8", errors="replace")
                for chunks in _stream_chunkings(rng, data):
                    frames = run_session(chunks)
                    for f in frames:
                        if not isinstance(f, dict) or f.get("type") not in FRAME_TYPES:
                            raise AssertionError(f"malformed frame: {f!r}")
                    terminal = [f for f in frames if f["type"] in ("final", "error")]
                    if len(terminal) != 1 or frames[-1] is not terminal[0]:
                        raise AssertionError(
                            f"bad termination: {[f['type'] for f in frames]}"
                        )
                    if terminal[0]["type"] == "error":
                        continue  # structured failure is a legal outcome
                    want = ref.analyze(
                        PodFailureData(
                            pod={"metadata": {"name": "fuzz-stream"}}, logs=blob
                        )
                    ).to_dict(drop_none=True)
                    got = terminal[0]["result"]
                    if events_of(got) != events_of(want):
                        raise AssertionError(
                            f"replay divergence: {events_of(got)} != "
                            f"{events_of(want)}"
                        )
                ef = engine.frequency.get_frequency_statistics()
                rf = ref.frequency.get_frequency_statistics()
                if ef != rf:
                    raise AssertionError(
                        f"frequency stats diverge: {ef} != {rf}"
                    )
                if mgr.stats()["openSessions"] != 0:
                    raise AssertionError("leaked open session")
                if shared_gate(engine).stats()["inflight"] != 0:
                    raise AssertionError("leaked admission slot")
                if seed % 10 == 0:
                    garbage_framing_pass()
            except Exception as exc:  # noqa: BLE001 - recorded, sweep continues
                fails.append((seed, repr(exc)[:300]))
                print(f"SEED {seed} FAILED: {exc!r}", flush=True)
            if seed % 10 == 0:
                print(f"seed {seed} done ({time.time() - t0:.0f}s)", flush=True)
    finally:
        server.shutdown()
        server.server_close()
        mgr.shutdown()
    print(f"DONE stream seeds {start}..{end - 1} fails: {fails} "
          f"({time.time() - t0:.0f}s)")
    return 1 if fails else 0


def run_sweep(mode: str, start: int, end: int) -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from test_engine_parity import (  # the suite's generators ARE the spec
        _force_bit_policy,
        assert_results_match,
        random_library,
        random_logs,
        random_long_library,
        random_long_logs,
    )
    from tests.conftest import FakeClock

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.golden import GoldenAnalyzer
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.parallel import (
        PatternShardedEngine,
        ShardedEngine,
        make_mesh,
    )
    from log_parser_tpu.runtime import AnalysisEngine

    mesh = make_mesh(8) if mode == "sharded" else None
    t0 = time.time()
    fails: list[tuple[int, str]] = []
    for seed in range(start, end):
        rng = random.Random(seed)
        # construction inside the guard: a library the compiler rejects
        # is exactly the kind of find the sweep records, not an abort.
        # Config variation, corpus counts, and the end-of-seed
        # frequency-stats check mirror the corresponding suite test
        # exactly (rng call order included, so seed N here draws the
        # same library the suite's seed N would).
        try:
            if mode == "sharded":
                sets = random_library(rng, rng.randrange(2, 6))
                config = ScoringConfig(frequency_threshold=rng.choice([2.0, 10.0]))
                engine = ShardedEngine(sets, config, mesh=mesh, clock=FakeClock())
                n_runs, lines_lo, lines_hi = 2, 5, 90
            elif mode == "pattern-sharded":
                sets = random_library(rng, rng.randrange(3, 7))
                config = ScoringConfig(frequency_threshold=rng.choice([2.0, 10.0]))
                engine = PatternShardedEngine(
                    sets,
                    config,
                    n_blocks=(1, 3, 4)[seed % 3],
                    clock=FakeClock(),
                )
                n_runs, lines_lo, lines_hi = 2, 20, 200
            elif mode == "long":
                sets = random_long_library(rng, rng.randrange(2, 6))
                config = ScoringConfig(proximity_max_window=rng.choice([5, 100]))
                engine = AnalysisEngine(sets, config, clock=FakeClock())
                _force_bit_policy(engine)
                # guard against a vacuous pass: the mode exists to fuzz
                # the bit tier's truncation/repair paths
                assert engine.matchers.bitglush is not None
                n_runs, lines_lo, lines_hi = 3, 5, 80
            else:
                sets = random_library(rng, rng.randrange(2, 8))
                config = ScoringConfig(
                    frequency_threshold=rng.choice([2.0, 10.0]),
                    proximity_max_window=rng.choice([5, 100]),
                )
                engine = AnalysisEngine(sets, config, clock=FakeClock())
                n_runs, lines_lo, lines_hi = 3, 5, 120
            golden = GoldenAnalyzer(sets, config, clock=FakeClock())
            gen_logs = random_long_logs if mode == "long" else random_logs
            for _ in range(n_runs):  # frequency state must evolve identically
                logs = gen_logs(rng, rng.randrange(lines_lo, lines_hi))
                data = PodFailureData(pod={"metadata": {"name": "fuzz"}}, logs=logs)
                assert_results_match(engine.analyze(data), golden.analyze(data))
            # explicit raise, not assert: python -O would strip an
            # assert (the startup guard below protects the suite-shared
            # assert-based checks too)
            ef = engine.frequency.get_frequency_statistics()
            gf = golden.frequency.get_frequency_statistics()
            if ef != gf:
                raise AssertionError(f"frequency stats diverge: {ef} != {gf}")
        except Exception as exc:  # noqa: BLE001 - recorded, sweep continues
            fails.append((seed, repr(exc)[:300]))
            print(f"SEED {seed} FAILED: {exc!r}", flush=True)
        if seed % 20 == 0:
            print(f"seed {seed} done ({time.time() - t0:.0f}s)", flush=True)
    print(f"DONE {mode} seeds {start}..{end - 1} fails: {fails} "
          f"({time.time() - t0:.0f}s)")
    return 1 if fails else 0


def _miner_hostile_lines(rng: "random.Random") -> list[bytes]:
    """Seeded hostile miss lines: everything a real corrupted log stream
    or an adversarial tenant could push through the line cache."""
    meta = b".*+?()[]{}|\\^$"
    cases = [
        # invalid UTF-8 runs
        bytes(rng.randrange(128, 256) for _ in range(rng.randrange(1, 200))),
        # NUL-riddled line
        b"abc\x00def \x00\x00 ghi" * rng.randrange(1, 8),
        # 1 MB single line (tokenizer must truncate, never choke)
        bytes([rng.randrange(33, 127)]) * (1 << 20),
        # regex metacharacter soup — the synthesizer must escape or demote
        bytes(rng.choice(meta) for _ in range(rng.randrange(4, 120))),
        # metachar tokens with whitespace structure (clusterable!)
        b" ".join(
            bytes(rng.choice(meta) for _ in range(rng.randrange(1, 12)))
            for _ in range(rng.randrange(2, 10))
        ),
        # control-character soup
        bytes(rng.randrange(0, 32) for _ in range(rng.randrange(1, 100))),
        # plausible template line with hostile slot values
        b"evict shard \xff\xfe\x00 after "
        + bytes([rng.randrange(256)]) * rng.randrange(1, 30),
        # whitespace-only and empty
        b" \t \t " * rng.randrange(1, 5),
        b"",
        # very many tokens (over MAX_TOKENS -> skipped, not mined)
        b"tok " * rng.randrange(40, 200),
    ]
    rng.shuffle(cases)
    return cases


def run_miner_sweep(start: int, end: int) -> int:
    """Fuzz the template miner (log_parser_tpu/mining/): hostile miss
    lines through the real tap → pump → cluster → synthesize → vet
    pipeline at ``min_support=1``. Findings: the miner raised (``errors``
    moved), the serving bank changed in review mode, or a synthesized
    regex failed the bank's own compile entry points."""
    import random

    from log_parser_tpu.analysis.tiers import classify_regex
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.golden.javacompat import compile_java_regex
    from log_parser_tpu.mining.synthesize import synthesize, template_regex
    from log_parser_tpu.mining.templates import TemplateClusterer
    from log_parser_tpu.runtime import AnalysisEngine

    from helpers import make_pattern, make_pattern_set

    engine = AnalysisEngine(
        [make_pattern_set([
            make_pattern("oom", regex="OutOfMemoryError", confidence=0.9),
            make_pattern("conn", regex="Connection refused", confidence=0.7),
        ])],
        ScoringConfig(),
    )
    engine.enable_line_cache(4)
    engine.enable_miner(
        mode="review", min_support=1, stability=0, autostart=False
    )
    base_bank = engine.bank
    t0 = time.time()
    fails: list[tuple[int, str]] = []
    for seed in range(start, end):
        rng = random.Random(seed)
        try:
            lines = _miner_hostile_lines(rng)
            # the real pipeline: offer -> pump (cluster/synthesize/vet)
            for line in lines:
                engine.miner.tap.offer(line)
            engine.miner.pump()
            stats = engine.miner.stats()
            if stats["errors"]:
                raise AssertionError(f"miner raised internally: {stats}")
            if engine.bank is not base_bank:
                raise AssertionError("review-mode miner swapped the bank")
            # independent synthesis check: EVERY promotable hostile
            # cluster's regex must re-parse through the bank's own
            # compile entry points
            cl = TemplateClusterer(min_support=1, stability=0)
            for line in lines:
                cl.observe(line)
            for cluster in cl.promotable():
                regex = template_regex(cluster.template)
                compile_java_regex(regex)  # raises on a bad emit
                pred = classify_regex(regex)
                if pred.tier == "skipped":
                    raise AssertionError(
                        f"synthesized regex off every tier "
                        f"({pred.reason_code}): {regex[:120]!r}"
                    )
                synthesize(cluster)  # full candidate must build too
        except Exception as exc:  # noqa: BLE001 - recorded, sweep continues
            fails.append((seed, repr(exc)[:300]))
            print(f"SEED {seed} FAILED: {exc!r}", flush=True)
        if seed % 10 == 0:
            print(f"seed {seed} done ({time.time() - t0:.0f}s)", flush=True)
    engine.miner.stop()
    print(f"DONE miner seeds {start}..{end - 1} fails: {fails} "
          f"({time.time() - t0:.0f}s)")
    return 1 if fails else 0


def _router_tenant_headers(rng: "random.Random") -> list[str]:
    """Hostile X-Tenant values. urllib refuses header injection itself,
    so the corpus stays latin-1-printable — the interesting surface is
    the edge validator, not the client library."""
    # the trailing "|" is outside [A-Za-z0-9._-], so the soup is always
    # invalid no matter what the prefix draws
    soup = "".join(
        rng.choice("abz09._-/\\~!$%&*()+=:;'\"<>?|{}[] ")
        for _ in range(rng.randrange(1, 40))
    ) + "|"
    return [
        "../evil",                          # traversal
        "..",                               # bare dots
        "a" * rng.randrange(65, 200),       # over the 64-char id bound
        "UPPER CASE",                       # space + case
        "acme/../default",                  # embedded traversal
        ".hidden",                          # leading dot
        "-dash-lead",                       # leading dash is refused
        soup,
        "%2e%2e%2fescape",                  # encoded traversal
        "tab\tin\ttenant",
    ]


def _router_garbage(rng: "random.Random") -> list[bytes]:
    return [
        bytes(rng.randrange(256) for _ in range(rng.randrange(1, 128))),
        b"GET / HTTP/9.9\r\n\r\n",
        b"POST /parse HTTP/1.1\r\nContent-Length: 99999999\r\n\r\nxx",
        b"\r\n\r\n\r\n",
        b"POST /parse HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\nZZZ\r\n",
    ]


def run_router_sweep(start: int, end: int) -> int:
    """Fuzz the fleet-router front-door: hostile tenants are refused 400
    AT the edge (never proxied), hostile bodies/paths relay the
    backend's own verdict, malformed /fleet/override bodies answer 400
    with the ring untouched, raw-socket garbage never wedges the
    listener — and after every seed the router still routes."""
    import json
    import random
    import socket
    import threading
    import urllib.error
    import urllib.request

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.fleet.router import make_router
    from log_parser_tpu.patterns import load_pattern_directory
    from log_parser_tpu.runtime import AnalysisEngine
    from log_parser_tpu.serve.http import make_server

    pattern_dir = os.path.join(_REPO, "log_parser_tpu", "patterns", "builtin")
    engine = AnalysisEngine(load_pattern_directory(pattern_dir), ScoringConfig())
    backend = make_server(engine, "127.0.0.1", 0)
    threading.Thread(target=backend.serve_forever, daemon=True).start()
    backend_url = f"http://127.0.0.1:{backend.server_address[1]}"
    router = make_router("127.0.0.1", 0, [backend_url], down_after=5)
    threading.Thread(target=router.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{router.server_address[1]}"
    parse_body = json.dumps(
        {"pod": {"metadata": {"name": "fuzz"}}, "logs": "INFO boot"}
    ).encode()

    def req(path: str, body: bytes | None = None,
            headers: dict | None = None) -> tuple[int, bytes]:
        r = urllib.request.Request(
            url + path, data=body,
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        try:
            with urllib.request.urlopen(r, timeout=60) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def ring_fingerprint() -> str:
        stats = router.ring.stats()
        return json.dumps(
            {"backends": stats["backends"], "overrides": stats["overrides"]},
            sort_keys=True,
        )

    t0 = time.time()
    fails: list[tuple[int, str]] = []
    try:
        for seed in range(start, end):
            rng = random.Random(seed)
            try:
                for tenant in _router_tenant_headers(rng):
                    try:
                        status, payload = req(
                            "/parse", parse_body, {"X-Tenant": tenant}
                        )
                    except ValueError:
                        continue  # urllib itself refused the header value
                    if status != 400:
                        raise AssertionError(
                            f"hostile tenant {tenant[:40]!r} answered "
                            f"{status}, want 400 at the edge"
                        )
                    err = json.loads(payload)
                    if "error" not in err:
                        raise AssertionError(
                            f"400 without structured error: {payload[:120]!r}"
                        )
                # hostile bodies and paths relay the backend verdict —
                # anything but a router-minted 5xx is acceptable
                hostile = bytes(
                    rng.randrange(256) for _ in range(rng.randrange(1, 256))
                )
                for path, body in (
                    ("/parse", hostile),
                    ("/parse", b"[]"),
                    (f"/no-such-{seed}", None),
                ):
                    status, _ = req(path, body)
                    if status >= 500:
                        raise AssertionError(
                            f"{path} answered {status} with the backend up"
                        )
                # malformed override bodies: 400, ring untouched
                ring_before = ring_fingerprint()
                for body in (
                    b"not json",
                    b"[]",
                    b"{}",
                    json.dumps({"tenant": "../evil",
                                "backend": backend_url}).encode(),
                    json.dumps({"tenant": "acme",
                                "backend": "http://10.0.0.1:1"}).encode(),
                    hostile,
                ):
                    status, _ = req("/fleet/override", body)
                    if status != 400:
                        raise AssertionError(
                            f"override fuzz answered {status}, want 400"
                        )
                if ring_fingerprint() != ring_before:
                    raise AssertionError("override fuzz mutated the ring")
                # raw-socket garbage must never wedge the listener
                for garbage in _router_garbage(rng):
                    with socket.create_connection(
                        ("127.0.0.1", router.server_address[1]), timeout=10
                    ) as s:
                        s.sendall(garbage)
                        s.settimeout(5)
                        try:
                            s.recv(4096)
                        except (socket.timeout, OSError):
                            pass
                # the router still routes after every hostile pass
                status, _ = req("/q/health")
                if status != 200:
                    raise AssertionError(f"health {status} after fuzz")
                status, _ = req("/parse", parse_body)
                if status != 200:
                    raise AssertionError(f"clean parse {status} after fuzz")
            except Exception as exc:  # noqa: BLE001 - recorded, sweep continues
                fails.append((seed, repr(exc)[:300]))
                print(f"SEED {seed} FAILED: {exc!r}", flush=True)
            if seed % 10 == 0:
                print(f"seed {seed} done ({time.time() - t0:.0f}s)", flush=True)
    finally:
        router.shutdown()
        router.server_close()
        backend.shutdown()
        backend.server_close()
    print(f"DONE router seeds {start}..{end - 1} fails: {fails} "
          f"({time.time() - t0:.0f}s)")
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
