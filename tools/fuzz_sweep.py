"""Extended engine-vs-golden parity sweep.

Reuses the suite's own generators (tests/test_engine_parity.py) over an
arbitrary seed range — the suite pins seeds 0..7 for CI speed; this tool
runs the long tail on demand. Every seed builds a random pattern library,
then runs three corpora through BOTH the device engine (CPU backend,
fallback disabled) and the pure-host golden analyzer, asserting
event-for-event equality and score deltas <= 1e-9 with evolving
cross-request frequency state.

Usage: python tools/fuzz_sweep.py [--start 8] [--end 200]
Record: seeds 8..199 (192 libraries, 576 corpora) passed clean on the
round-4 engine (2026-07-30).
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["LOG_PARSER_TPU_NO_FALLBACK"] = "1"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))


def main() -> int:
    if sys.flags.optimize:
        # the parity checks (assert_results_match, shared with the test
        # suite) are assert-based; -O would strip them and report a
        # vacuous clean pass
        sys.exit("refusing to run under python -O: parity asserts would be stripped")
    ap = argparse.ArgumentParser()
    ap.add_argument("--start", type=int, default=8)
    ap.add_argument("--end", type=int, default=200)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from test_engine_parity import (  # the suite's generators ARE the spec
        assert_results_match,
        random_library,
        random_logs,
    )
    from tests.conftest import FakeClock

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.golden import GoldenAnalyzer
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.runtime import AnalysisEngine

    t0 = time.time()
    fails: list[tuple[int, str]] = []
    for seed in range(args.start, args.end):
        rng = random.Random(seed)
        # construction inside the guard: a library the compiler rejects
        # is exactly the kind of find the sweep records, not an abort.
        # Per-seed config variation and the end-of-seed frequency-stats
        # check mirror the suite's test_random_library_parity exactly.
        try:
            sets = random_library(rng, rng.randrange(2, 8))
            config = ScoringConfig(
                frequency_threshold=rng.choice([2.0, 10.0]),
                proximity_max_window=rng.choice([5, 100]),
            )
            engine = AnalysisEngine(sets, config, clock=FakeClock())
            golden = GoldenAnalyzer(sets, config, clock=FakeClock())
            for _ in range(3):  # frequency state must evolve identically
                logs = random_logs(rng, rng.randrange(5, 120))
                data = PodFailureData(pod={"metadata": {"name": "fuzz"}}, logs=logs)
                assert_results_match(engine.analyze(data), golden.analyze(data))
            # explicit raise, not assert: python -O would strip an
            # assert (the startup guard below protects the suite-shared
            # assert-based checks too)
            ef = engine.frequency.get_frequency_statistics()
            gf = golden.frequency.get_frequency_statistics()
            if ef != gf:
                raise AssertionError(f"frequency stats diverge: {ef} != {gf}")
        except Exception as exc:  # noqa: BLE001 - recorded, sweep continues
            fails.append((seed, repr(exc)[:300]))
            print(f"SEED {seed} FAILED: {exc!r}", flush=True)
        if seed % 20 == 0:
            print(f"seed {seed} done ({time.time() - t0:.0f}s)", flush=True)
    print(f"DONE seeds {args.start}..{args.end - 1} fails: {fails} "
          f"({time.time() - t0:.0f}s)")
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
