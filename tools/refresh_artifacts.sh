#!/usr/bin/env bash
# Refresh every bench_results/ artifact on one platform, serially (TPU
# tunnels degrade under concurrent clients — PERF.md §10). Usage:
#
#   tools/refresh_artifacts.sh tpu    # on a machine with the device
#   tools/refresh_artifacts.sh cpu    # labeled CPU floor
#
# Each bench prints one JSON line on stdout; stderr (probe diagnostics)
# is captured beside the artifact. A failed bench leaves the previous
# artifact in place.
set -u
cd "$(dirname "$0")/.."
platform="${1:?usage: refresh_artifacts.sh tpu|cpu}"
export LOG_PARSER_TPU_PLATFORM="$platform"

run() { # run <artifact-stem> <cmd...>
  local stem="$1"; shift
  echo "== $stem: $*" >&2
  local out rc
  # no pipe here: a pipe would mask the bench's exit code with tail's,
  # and a bench that exits 3 with a {"value": null} diagnostics line
  # (bench_common.exit_null) must NOT overwrite the previous artifact.
  # stderr goes to a temp first for the same reason: the kept .json and
  # its committed .stderr provenance must stay a matched pair
  out=$("$@" 2>"bench_results/${stem}.stderr.tmp"); rc=$?
  out=$(printf '%s\n' "$out" | tail -n 1)
  if [ "$rc" -eq 0 ] && [ -n "$out" ]; then
    # keep the artifact this run replaces so bench_diff can report the
    # round-over-round movement below
    if [ -f "bench_results/${stem}.json" ]; then
      cp -f "bench_results/${stem}.json" "bench_results/${stem}.prev.tmp"
    fi
    printf '%s\n' "$out" > "bench_results/${stem}.json"
    mv -f "bench_results/${stem}.stderr.tmp" "bench_results/${stem}.stderr"
    rm -f "bench_results/${stem}.failed.json" "bench_results/${stem}.failed.stderr"
    echo "   -> $out" >&2
    # advisory diff against the previous round's artifact: a slow machine
    # is not a broken bench, so the verdict never fails the refresh
    if [ -f "bench_results/${stem}.prev.tmp" ]; then
      python tools/bench_diff.py "bench_results/${stem}.prev.tmp" \
        "bench_results/${stem}.json" >&2 || true
      rm -f "bench_results/${stem}.prev.tmp"
    fi
  else
    mv -f "bench_results/${stem}.stderr.tmp" "bench_results/${stem}.failed.stderr"
    # a failed bench may still have printed the {"value": null}
    # diagnostics line (bench_common.exit_null) carrying every probe
    # attempt's stderr tail — keep it beside the intact artifact. Remove
    # any previous failure's copy first: the failed.json/.failed.stderr
    # pair must come from the SAME run
    rm -f "bench_results/${stem}.failed.json"
    if [ -n "$out" ]; then
      printf '%s\n' "$out" > "bench_results/${stem}.failed.json"
    fi
    echo "   FAILED rc=$rc (artifact kept); see bench_results/${stem}.failed.*" >&2
  fi
}

run "config2_${platform}"          python bench.py
run "config2_hostcol_${platform}"  python bench.py --host-col
# repeat-heavy cache-on/cache-off pair (BENCH_r10 headline shape): the
# routing-tier aggregate the vectorized host path is meant to raise
run "config2_rr90_lc64_${platform}" python bench.py --repeat-ratio 0.9 --line-cache-mb 64
run "config2_rr90_${platform}"      python bench.py --repeat-ratio 0.9
# host-phase profile (tools/profile_host.py): ingest/key/extract/
# assemble/finalize in isolation, scalar vs vectorized lanes — the
# PERF.md §14 phase table is read from these artifacts
run "profile_host_${platform}"      python tools/profile_host.py
run "profile_host_rr90_${platform}" python tools/profile_host.py --repeat-ratio 0.9
run "config3_1m_singlechip_${platform}" python bench.py --lines 1000000
# the full sharded DP program at corpus scale on the virtual 8-device
# mesh. Runs on EVERY refresh round (bench_mesh.py pins itself to the
# virtual CPU mesh regardless of $platform, hence the fixed cpu stem) so
# the artifact never goes stale beside freshly-stamped siblings; real
# multi-chip mode is LOG_PARSER_TPU_MESH=real on a multi-chip host
run "config3_1m_mesh8_cpu" python bench_mesh.py --devices 8 --lines 1000000
# measured shard-program overhead (VERDICT r4 #4): the FULL ShardedEngine
# vs the plain engine at matched batch. On a TPU host the mesh=1 real row
# isolates program structure (halos/all_gather/concat, zero real
# communication) — the factor under the config-3 "per-chip x N" projection
if [ "$platform" = "tpu" ]; then
  LOG_PARSER_TPU_MESH=real run "config3_shard_overhead_mesh1_tpu" \
    python bench_mesh.py --devices 1 --lines 200000 --overhead
fi
run "config3_shard_overhead_mesh8_cpu" \
  python bench_mesh.py --devices 8 --lines 200000 --overhead
# the Pallas kernel verdicts (PERF.md §9 + §12): session-matched A/B of
# BOTH kernel tiers (bitglush, union multi-DFA) against their XLA scan
# baselines; the bitglush kernel gets deleted if its pallas_over_xla
# comes back >= ~1 (VERDICT r4 #6)
if [ "$platform" = "tpu" ]; then
  run "kernels_ab_tpu" python tools/probe_kernels.py
fi
run "config4_2k_${platform}"       python bench_bank.py --patterns 2000 --lines 65536
run "config4_10k_${platform}"      python bench_bank.py --patterns 10000 --lines 65536
run "config5_direct_${platform}"   python bench_latency.py
run "config5_http_${platform}"     python bench_latency.py --http
run "config5_http_c4_${platform}"  python bench_latency.py --http --concurrency 4
# follow-mode TTFD vs blob-mode end-to-end on the repeat-heavy corpus
# (ISSUE 9 acceptance shape; headline row of BENCH_r09)
run "config5_stream_${platform}" \
  python bench_latency.py --stream --repeat-ratio 0.9 --line-cache-mb 64
# fleet front-door: 1,000 tenants, zipf traffic, 3 backends behind the
# router, one hot tenant moved live by the placement loop, plus the
# compiled-pack dedupe savings. Pure subprocess HTTP — fixed cpu stem
run "fleet_1k_cpu" python bench_mesh.py --fleet
