"""Session-matched A/B of EVERY Pallas kernel tier against its XLA scan
baseline, one structured JSON verdict for both (supersedes the
bitglush-only tools/probe_pallas_ab.py).

Tiers covered:

- ``bitglush``  — ops/bitglush_pallas.py vs the chainless pair stepper
  in one lax.scan (exact probe_tiers.py methodology).  PERF.md §9 owns
  the standing decision rule, encoded in the verdict below: on a LIVE
  TPU, ``pallas_over_xla >= ~1`` means the kernel loses its re-trial
  and gets deleted with a recorded negative.
- ``multidfa``  — ops/matchdfa_pallas.py (union-DFA scan, MXU one-hot
  planes instead of the scalar-unit gather) vs the gate-free
  pair_stepper lax.scan the cube fuses when the kernel is off.  On a
  CPU-policy host with no native union builder the probe rebuilds the
  union groups through the Python construction so the A/B still runs.

Both comparisons are bit-exact or the probe says so loudly
(``verdict: parity_failure`` trumps any timing).  On a non-TPU backend
the kernels run in interpreter mode: parity is meaningful, timing is
not, and the verdict pins ``pending_live_tpu`` — so the default shape
shrinks to keep the interpreter walk honest but fast.

Run on a LIVE TPU session (one process, nothing concurrent — PERF.md
§10):

    nohup python tools/probe_kernels.py > /tmp/probe_kernels.out 2>&1 &

Four compiles total (one per variant per tier), inside relay etiquette.
Prints one JSON line: per-tier times, bit-equality, ``pallas_over_xla``
ratios, and a ``verdicts`` block.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_common import timeit  # noqa: E402

# PERF.md §9: delete the bitglush kernel if the live-TPU ratio comes
# back >= ~1 (the kernel must BEAT the scan path to earn default
# status; parity already lost the re-trial)
BITGLUSH_DELETE_THRESHOLD = 1.0


def _verdict(tier: dict, platform: str, *, delete_at: float | None) -> str:
    if "skipped" in tier:
        return "not_measured"
    if not tier.get("bit_equal", False):
        return "parity_failure"
    if platform != "tpu":
        return "pending_live_tpu"
    ratio = tier["pallas_over_xla"]
    if delete_at is not None:
        return "delete_kernel" if ratio >= delete_at else "keep_kernel"
    return "promote_candidate" if ratio < 1.0 else "keep_off"


def _probe_bitglush(bank, lines_tb, lens, repeats: int) -> dict:
    import jax
    import numpy as np

    from log_parser_tpu.ops.bitglush_pallas import (
        bitglush_hits_pallas,
        pick_tile,
    )
    from log_parser_tpu.ops.match import pack_byte_pairs

    if bank is None:
        return {"skipped": "no bitglush bank under the current tier "
                           "policy (PERF.md §9g)"}
    B = int(lens.shape[0])
    if pick_tile(B) is None:
        return {"skipped": f"no valid pallas tile for B={B}"}
    tier = {
        "n_words": bank.n_words,
        "has_chains": bool(bank.has_chains),
        "use_sinks": bool(bank.use_sinks),
    }

    stepper = bank.pair_stepper(B, lens)

    @jax.jit
    def xla_scan(lines_tb, lens):
        pairs, ts = pack_byte_pairs(lines_tb)

        def step(carry, xs):
            pair, t = xs
            return stepper[1](carry, pair[0], pair[1], t), None

        final, _ = jax.lax.scan(step, stepper[0], (pairs, ts))
        return final

    out = xla_scan(lines_tb, lens)
    jax.block_until_ready(out)
    tier["xla_s"] = round(
        timeit(lambda: jax.block_until_ready(xla_scan(lines_tb, lens)),
               n=repeats), 4
    )

    @jax.jit
    def pallas_scan(lines_tb, lens):
        return bitglush_hits_pallas(bank, lines_tb, lens)

    phits = pallas_scan(lines_tb, lens)
    jax.block_until_ready(phits)
    tier["pallas_s"] = round(
        timeit(lambda: jax.block_until_ready(pallas_scan(lines_tb, lens)),
               n=repeats), 4
    )
    # carry layouts differ (and may be sink-mode on the CPU policy), so
    # parity goes through the bank's own column readers
    cols_xla = np.asarray(stepper[2](out))
    cols_pallas = np.asarray(bank.columns_from_hits(phits))
    tier["bit_equal"] = bool(np.array_equal(cols_xla, cols_pallas))
    tier["pallas_over_xla"] = round(tier["pallas_s"] / tier["xla_s"], 3)
    return tier


# re-pack cap when the bank's own groups (MULTI_STATE_BUDGET = 8192
# states) fail kernel admission: 2048 states pads to lane-aligned
# planes well inside the 12 MB budget at the full 128-row tile, so the
# A/B measures the kernel on groups it would actually admit
REPACK_MAX_STATES = 2048


def _union_groups(matchers, max_states: int | None = None):
    """The engine's union groups plus their per-group entries (the
    admission planner needs entries to re-split oversized groups); on
    hosts where the tier policy left them empty (no native builder), or
    when a ``max_states`` re-pack is requested, rebuild through the
    Python union construction over the same regex columns so the kernel
    A/B runs."""
    if max_states is None and matchers.multi_groups:
        return (
            matchers.multi_groups,
            getattr(matchers, "_multi_entries", None) or None,
            False,
        )
    from log_parser_tpu.ops.match import MatcherBanks, MultiDfaBank
    from log_parser_tpu.patterns.regex.multidfa import pack_union_groups

    entries = [
        (i, c.regex, c.case_insensitive)
        for i, c in enumerate(matchers.bank.columns)
        if getattr(c, "regex", None)
    ]
    if not entries:
        return [], None, False
    groups, _rejected = pack_union_groups(
        entries,
        max_states=max_states or MatcherBanks.MULTI_STATE_BUDGET,
        max_group=MatcherBanks.MULTI_MAX_GROUP,
    )
    emap = {e[0]: e for e in entries}
    return (
        [MultiDfaBank(md, keys) for keys, md in groups],
        [[emap[k] for k in keys] for keys, _ in groups],
        True,
    )


def _probe_multidfa(matchers, lines_tb, lens, repeats: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from log_parser_tpu.ops.match import pack_byte_pairs
    from log_parser_tpu.ops.matchdfa_pallas import (
        build_dfa_plan,
        dfa_tile,
        multidfa_reported_pallas,
    )

    from log_parser_tpu.ops.match import MatcherBanks

    groups, group_entries, forced = _union_groups(matchers)
    if not groups:
        return {"skipped": "no union groups (no regex columns to pack)"}
    plan, reason = build_dfa_plan(
        groups,
        entries=group_entries,
        max_states=MatcherBanks.MULTI_STATE_BUDGET,
    )
    repacked = None
    if plan is None and reason == "table_too_large":
        # admission failed even with the entry-level re-split (or no
        # entries survived to split on) — re-pack tighter as a backstop
        # so the kernel is still measured on admissible groups
        groups, group_entries, forced = _union_groups(
            matchers, REPACK_MAX_STATES
        )
        if groups:
            plan, reason = build_dfa_plan(groups, entries=group_entries)
            repacked = REPACK_MAX_STATES
    if plan is None:
        return {"skipped": f"kernel admission refused: {reason}"}
    # the plan may have re-split groups for admission — the XLA baseline
    # must scan the SAME automata the kernel runs, so adopt plan.groups
    groups = list(plan.groups)
    B = int(lens.shape[0])
    T = int(lines_tb.shape[0])
    tile = dfa_tile(plan, B, T)
    if tile is None:
        return {"skipped": f"no valid batch tile for B={B} at T={T}"}
    tier = {
        "n_groups": plan.n_groups,
        "s_pad": plan.s_pad,
        "tile_b": tile,
        "admission_reason": reason,
        "geometry": plan.geometry,
        "forced_python_union": forced,
        "repacked_max_states": repacked,
    }

    steppers = [g.pair_stepper(B, lens) for g in groups]

    @jax.jit
    def xla_scan(lines_tb, lens):
        pairs, ts = pack_byte_pairs(lines_tb)

        def step(carries, xs):
            pair, t = xs
            return [
                st[1](c, pair[0], pair[1], t)
                for st, c in zip(steppers, carries)
            ], None

        finals, _ = jax.lax.scan(
            step, [st[0] for st in steppers], (pairs, ts)
        )
        return jnp.stack(
            [st[2](f)[1] for st, f in zip(steppers, finals)], axis=1
        ).astype(jnp.int32)

    out = xla_scan(lines_tb, lens)
    jax.block_until_ready(out)
    tier["xla_s"] = round(
        timeit(lambda: jax.block_until_ready(xla_scan(lines_tb, lens)),
               n=repeats), 4
    )

    @jax.jit
    def pallas_scan(lines_tb):
        return multidfa_reported_pallas(plan, lines_tb)

    prep = pallas_scan(lines_tb)
    jax.block_until_ready(prep)
    tier["pallas_s"] = round(
        timeit(lambda: jax.block_until_ready(pallas_scan(lines_tb)),
               n=repeats), 4
    )
    tier["bit_equal"] = bool(
        np.array_equal(np.asarray(out) != 0, np.asarray(prep) != 0)
    )
    tier["pallas_over_xla"] = round(tier["pallas_s"] / tier["xla_s"], 3)
    return tier


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lines", type=int, default=None,
                    help="corpus lines (default: 200000 on tpu, 2000 "
                         "elsewhere — interpreter-mode kernels are for "
                         "parity, not timing)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--tier", choices=("bitglush", "multidfa", "all"),
                    default="all")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import bench
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.native.ingest import Corpus
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    platform = jax.devices()[0].platform
    n_lines = args.lines if args.lines is not None else (
        200_000 if platform == "tpu" else 2_000
    )

    engine = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
    corpus = Corpus(bench.build_corpus(n_lines))
    enc = corpus.encoded
    lines_tb = jnp.asarray(enc.u8.T)
    lens = jnp.asarray(enc.lengths)
    jax.block_until_ready((lines_tb, lens))

    report = {
        "platform": platform,
        "rows": int(lens.shape[0]),
        "T": int(lines_tb.shape[0]),
        "tiers": {},
    }
    if args.tier in ("bitglush", "all"):
        report["tiers"]["bitglush"] = _probe_bitglush(
            engine.matchers.bitglush, lines_tb, lens, args.repeats
        )
    if args.tier in ("multidfa", "all"):
        report["tiers"]["multidfa"] = _probe_multidfa(
            engine.matchers, lines_tb, lens, args.repeats
        )

    report["verdicts"] = {
        name: _verdict(
            tier, platform,
            delete_at=BITGLUSH_DELETE_THRESHOLD
            if name == "bitglush" else None,
        )
        for name, tier in report["tiers"].items()
    }
    print(json.dumps(report))
    if any(v == "parity_failure" for v in report["verdicts"].values()):
        sys.exit(2)


if __name__ == "__main__":
    main()
