"""Config-4 tier A/B (VERDICT r3 #7): does routing the synthetic
library's bitglush-eligible columns to the bit tier move the wide-bank
cube, versus the shipping prefilter+union routing?

Builds the 2k/10k synthetic banks (bench_bank.synth_library), times the
MatcherBanks cube over a 65536-line corpus for bit budgets 0 (tier off)
/ 192 (shipping TPU default) / 512 (wider: 4 lane-tiles), and prints one
JSON line per (patterns, budget) combination plus the tier populations,
so the decision lands in PERF.md §6 with numbers attached.

Usage: python tools/probe_config4_tiers.py [--patterns 2000] [--lines 65536]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_common import timeit  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--patterns", type=int, default=2000)
    ap.add_argument("--lines", type=int, default=65536)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument(
        "--budgets", type=str, default="0,192,512",
        help="comma-separated bitglush word budgets to A/B",
    )
    ap.add_argument(
        "--no-prefilter", action="store_true",
        help="disable the AC prefilter tier so eligible columns flow to "
        "the bit tier (wide banks otherwise route everything literal-"
        "bearing to the prefilter first)",
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import bench_bank
    from log_parser_tpu.native.ingest import Corpus
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.patterns.bank import PatternBank

    bank = PatternBank(bench_bank.synth_library(args.patterns))
    corpus = Corpus(bench_bank.synth_logs(args.lines, args.patterns))
    enc = corpus.encoded
    lines_tb = jnp.asarray(enc.u8.T)
    lens = jnp.asarray(enc.lengths)
    jax.block_until_ready((lines_tb, lens))

    extra = (
        {"prefilter_min_columns": 10**9} if args.no_prefilter else {}
    )
    for budget in (int(b) for b in args.budgets.split(",")):
        mb = MatcherBanks(bank, bitglush_max_words=budget, **extra)
        cube_jit = jax.jit(mb.cube)
        fn = lambda: jax.block_until_ready(cube_jit(lines_tb, lens))
        secs = timeit(fn, n=args.repeats)
        print(
            json.dumps(
                {
                    "platform": jax.devices()[0].platform,
                    "patterns": args.patterns,
                    "lines": int(lens.shape[0]),
                    "bit_budget": budget,
                    "cube_s": round(secs, 4),
                    "tiers": {
                        "shiftor": len(mb.shiftor_cols),
                        "bitglush": len(mb.bitglush_cols),
                        "bitglush_words": mb.bitglush.n_words if mb.bitglush else 0,
                        "prefilter": len(mb.prefilter_cols),
                        "multi": len(mb.multi_cols),
                        "dfa": len(mb.dfa_cols),
                    },
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
