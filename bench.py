"""Benchmark: end-to-end scored log-lines/sec on one chip.

Implements BASELINE.md config 2 (synthetic pod log, full built-in pattern
library, single device). The reference publishes no numbers (BASELINE.md);
``vs_baseline`` is therefore reported against the north-star target of
1M log-lines/sec/chip from BASELINE.json.

Backend contract (VERDICT.md round-2 postmortem): the golden host
fallback is DISABLED for the bench, and backend init runs as a staged
campaign in throwaway subprocesses (bench_common.probe_backend).  If the
device layer never comes up within the total probe budget the bench runs
on the pinned JAX host (CPU) platform and records a clearly-labeled
``{"platform": "cpu"}`` floor with the probe diagnostics embedded — the
artifact is never null.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "platform": "tpu"|"cpu", ...}
"""

from __future__ import annotations

import json
import sys
import threading
import time

import bench_common  # noqa: F401  (sets LOG_PARSER_TPU_NO_FALLBACK=1 on import)

N_LINES = int(sys.argv[sys.argv.index("--lines") + 1]) if "--lines" in sys.argv else 200_000
NORTH_STAR_LINES_PER_SEC = 1_000_000.0
# --host-col: config-2 variant with one injected lookbehind pattern (a
# host-only column). Guards the VERDICT r3 #3 cliff: with the literal
# prefilter this must stay within ~2x of the clean number instead of
# collapsing to a full host-re scan per request.
HOST_COL = "--host-col" in sys.argv


def build_corpus(n: int) -> str:
    rows = []
    for i in range(n):
        m = i % 997
        if m == 5:
            rows.append("java.lang.OutOfMemoryError: Java heap space")
        elif m == 3:
            rows.append("[Full GC (Ergonomics) 255M->250M(256M), 0.41 secs]")
        elif m == 250:
            rows.append("dial tcp 10.0.0.7:5432: Connection refused")
        elif m == 500:
            rows.append("Warning: Liveness probe failed: HTTP 503")
        elif m == 700:
            rows.append("    at com.example.Service.handle(Service.java:42)")
        elif m == 701:
            rows.append("ERROR request failed with IllegalStateException")
        else:
            rows.append(
                f"2026-07-29T07:{i % 60:02d}:{i % 60:02d}Z INFO reconcile tick {i} status=ok"
            )
    return "\n".join(rows)


def main() -> None:
    metric = (
        "log_lines_scored_per_sec_per_chip_hostcol"
        if HOST_COL
        else "log_lines_scored_per_sec_per_chip"
    )
    platform = bench_common.probe_backend(metric, "lines/s")

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    sets = load_builtin_pattern_sets()
    if HOST_COL:
        from log_parser_tpu.models.pattern import (
            Pattern,
            PatternSet,
            PatternSetMetadata,
            PrimaryPattern,
        )

        sets = sets + [
            PatternSet(
                metadata=PatternSetMetadata(
                    library_id="hostcol", name="hostcol"
                ),
                patterns=[
                    Pattern(
                        id="hostcol-lb",
                        name="lookbehind host column",
                        severity="HIGH",
                        primary_pattern=PrimaryPattern(
                            regex=r"(?<=dial tcp )10\.0\.0\.\d+",
                            confidence=0.8,
                        ),
                    )
                ],
            )
        ]
    n_patterns = sum(len(s.patterns or []) for s in sets)
    engine = AnalysisEngine(sets, ScoringConfig())
    assert not engine.fallback_to_golden, "bench must never serve from golden"
    logs = build_corpus(N_LINES)
    data = PodFailureData(pod={"metadata": {"name": "bench"}}, logs=logs)

    engine.analyze(data)  # warmup: compile + caches
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        result = engine.analyze(data)
        times.append(time.perf_counter() - t0)
    best = min(times)
    serial_rate = N_LINES / best
    assert result.summary.significant_events > 0

    # Chip throughput under serving load: ``analyze_pipelined`` overlaps
    # request N+1's ingest + device execution with request N's host-side
    # sync/finalize (only the frequency-coupled finish serializes), so
    # concurrent streams measure what the chip actually sustains — the
    # serial loop leaves it idle during every host round-trip (through
    # the tunneled backend that idle is ~30% of the request). 4 streams
    # x 2 requests, best of 2 rounds; the serial rate stays in the
    # artifact for comparability.
    concurrency, per_thread = 4, 2
    pipe_rate = 0.0
    for _ in range(2):
        errors: list[BaseException] = []

        def client() -> None:
            try:
                for _ in range(per_thread):
                    r = engine.analyze_pipelined(data)
                    assert r.summary.significant_events > 0
            except BaseException as exc:
                errors.append(exc)

        threads = [threading.Thread(target=client) for _ in range(concurrency)]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        dt = time.perf_counter() - t0
        if errors:  # a partial round must never inflate the artifact
            raise errors[0]
        pipe_rate = max(pipe_rate, concurrency * per_thread * N_LINES / dt)

    # headline methodology is PINNED to the pipelined serving throughput
    # (not max(serial, pipelined) — that would silently flip methodology
    # between runs); the serial single-stream rate rides alongside
    lines_per_sec = pipe_rate
    bench_common.emit(
        metric,
        round(lines_per_sec, 1),
        "lines/s",
        round(lines_per_sec / NORTH_STAR_LINES_PER_SEC, 4),
        platform,
        n_lines=N_LINES,
        n_patterns=n_patterns,
        serial_lines_per_sec=round(serial_rate, 1),
        pipeline_concurrency=concurrency,
        # the headline key predates the pipelined methodology; this field
        # disambiguates artifacts across versions (r1-r2: serial best-of,
        # r3+: pipelined serving throughput at the stated concurrency)
        methodology="pipelined-v2",
    )


if __name__ == "__main__":
    main()
