"""Benchmark: end-to-end scored log-lines/sec on one chip.

Implements BASELINE.md config 2 (synthetic pod log, full built-in pattern
library, single device). The reference publishes no numbers (BASELINE.md);
``vs_baseline`` is therefore reported against the north-star target of
1M log-lines/sec/chip from BASELINE.json.

Backend contract (VERDICT.md round-2 postmortem): the golden host
fallback is DISABLED for the bench, and backend init runs as a staged
campaign in throwaway subprocesses (bench_common.probe_backend).  If the
device layer never comes up within the total probe budget the bench runs
on the pinned JAX host (CPU) platform and records a clearly-labeled
``{"platform": "cpu"}`` floor with the probe diagnostics embedded.  A
number is never *silently* wrong, and failure is never silent: paths
where no honest number exists (explicitly-requested platform
unavailable, backend wedged mid-process, a would-be mislabel) emit a
``{"value": null}`` diagnostics line and exit 3
(bench_common.exit_null); if no campaign level completes, the bench
raises.  Consumers must check the exit code, not just parse stdout.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "platform": "tpu"|"cpu", ...}
"""

from __future__ import annotations

import itertools
import os
import random
import sys

import bench_common  # noqa: F401  (sets LOG_PARSER_TPU_NO_FALLBACK=1 on import)

N_LINES = int(sys.argv[sys.argv.index("--lines") + 1]) if "--lines" in sys.argv else 200_000
NORTH_STAR_LINES_PER_SEC = 1_000_000.0
# --repeat-ratio R: repeat-heavy corpus mode (bench_common.repeat_corpus)
# — ~R of each request's lines are zipf template draws, the rest unique.
# --line-cache-mb MB: serve through the exact-match line cache
# (runtime/linecache.py); 0/absent = cache off. Together they make
# cache-on vs cache-off a first-class BENCH_rNN comparison.
REPEAT_RATIO = (
    float(sys.argv[sys.argv.index("--repeat-ratio") + 1])
    if "--repeat-ratio" in sys.argv
    else None
)
LINE_CACHE_MB = (
    float(sys.argv[sys.argv.index("--line-cache-mb") + 1])
    if "--line-cache-mb" in sys.argv
    else 0.0
)
# --novel-ratio R: carve ~R of each repeat corpus into unseen
# generated-template lines (bench_common.NOVEL_TEMPLATES) — guaranteed
# cache misses shaped for the template miner. --miner: run the miner
# (review mode, so the bank never changes mid-measure) against that miss
# stream and embed its tap/cluster counters in the artifact; the
# BENCH_r12 companions are the same command with and without it.
NOVEL_RATIO = (
    float(sys.argv[sys.argv.index("--novel-ratio") + 1])
    if "--novel-ratio" in sys.argv
    else 0.0
)
MINER = "--miner" in sys.argv
# Distinct request payloads the repeat-mode stream cycles through. The
# line cache is a CROSS-request tier: with a single fixed payload every
# line (unique fillers included) becomes a hit after request #1 and the
# ratio stops meaning anything. Rotating a pool keeps template lines
# hitting while each payload's fillers miss on their first serving.
REPEAT_POOL_REQUESTS = 8
# --host-col: config-2 variant with one injected lookbehind pattern (a
# host-only column). Guards the VERDICT r3 #3 cliff: with the literal
# prefilter this must stay within ~2x of the clean number instead of
# collapsing to a full host-re scan per request.
HOST_COL = "--host-col" in sys.argv
# steady-state dwell per concurrency level of the serving campaign
CAMPAIGN_SECONDS = float(os.environ.get("LOG_PARSER_TPU_CAMPAIGN_S", "30"))


def build_corpus(n: int) -> str:
    rows = []
    for i in range(n):
        m = i % 997
        if m == 5:
            rows.append("java.lang.OutOfMemoryError: Java heap space")
        elif m == 3:
            rows.append("[Full GC (Ergonomics) 255M->250M(256M), 0.41 secs]")
        elif m == 250:
            rows.append("dial tcp 10.0.0.7:5432: Connection refused")
        elif m == 500:
            rows.append("Warning: Liveness probe failed: HTTP 503")
        elif m == 700:
            rows.append("    at com.example.Service.handle(Service.java:42)")
        elif m == 701:
            rows.append("ERROR request failed with IllegalStateException")
        else:
            rows.append(
                f"2026-07-29T07:{i % 60:02d}:{i % 60:02d}Z INFO reconcile tick {i} status=ok"
            )
    return "\n".join(rows)


def main() -> None:
    metric = (
        "log_lines_scored_per_sec_per_chip_hostcol"
        if HOST_COL
        else "log_lines_scored_per_sec_per_chip"
    )
    if REPEAT_RATIO is not None:
        metric += f"_rr{int(round(REPEAT_RATIO * 100)):02d}"
    if LINE_CACHE_MB > 0:
        metric += "_lc"
    if NOVEL_RATIO > 0:
        metric += f"_nv{int(round(NOVEL_RATIO * 100)):02d}"
    if MINER:
        metric += "_miner"
    platform = bench_common.probe_backend(metric, "lines/s")

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.runtime import AnalysisEngine

    sets = load_builtin_pattern_sets()
    if HOST_COL:
        from log_parser_tpu.models.pattern import (
            Pattern,
            PatternSet,
            PatternSetMetadata,
            PrimaryPattern,
        )

        sets = sets + [
            PatternSet(
                metadata=PatternSetMetadata(
                    library_id="hostcol", name="hostcol"
                ),
                patterns=[
                    Pattern(
                        id="hostcol-lb",
                        name="lookbehind host column",
                        severity="HIGH",
                        primary_pattern=PrimaryPattern(
                            regex=r"(?<=dial tcp )10\.0\.0\.\d+",
                            confidence=0.8,
                        ),
                    )
                ],
            )
        ]
    n_patterns = sum(len(s.patterns or []) for s in sets)
    # cold-start story (ROADMAP item 5): engine construction + first
    # analyze = bank build + the XLA compile set. With the persistent
    # compile cache warm the same wall-clock drops to a disk replay —
    # compare boot_seconds across a cold/warm artifact pair and read the
    # compile_cache hit/miss tally beside it.
    import time as _time

    _boot0 = _time.perf_counter()
    engine = AnalysisEngine(sets, ScoringConfig())
    assert not engine.fallback_to_golden, "bench must never serve from golden"
    if LINE_CACHE_MB > 0:
        engine.enable_line_cache(LINE_CACHE_MB)
    if MINER:
        assert LINE_CACHE_MB > 0, "--miner rides the line cache"
        # review mode: the worker drains/clusters (the cost under test)
        # but never swaps the bank mid-measure
        engine.enable_miner(mode="review")
    if REPEAT_RATIO is not None:
        rng = random.Random(0xC0FFEE)
        pool = [
            PodFailureData(
                pod={"metadata": {"name": "bench"}},
                logs=bench_common.repeat_corpus(
                    N_LINES, REPEAT_RATIO, f"r{t}", rng,
                    novel_ratio=NOVEL_RATIO,
                ),
            )
            for t in range(REPEAT_POOL_REQUESTS)
        ]
    else:
        pool = [
            PodFailureData(
                pod={"metadata": {"name": "bench"}}, logs=build_corpus(N_LINES)
            )
        ]
    _req = itertools.count()

    def next_data() -> PodFailureData:
        return pool[next(_req) % len(pool)]

    # first request pays the whole XLA compile set (or its disk replay):
    # stamp it as the boot cost before the warmup loop hides it
    _first = engine.analyze(next_data())
    assert _first.summary.significant_events > 0
    boot_seconds = _time.perf_counter() - _boot0

    # warmup + serial measure under the shared wedge wrapper and timing
    # rule (bench_common.measured_phase): a backend that wedges after
    # the probe must yield the diagnostics exit, not a hang
    bounded = bench_common.bounded_runner(metric, "lines/s", platform)
    result, _, best = bench_common.measured_phase(
        bounded, lambda: engine.analyze(next_data())
    )
    assert result.summary.significant_events > 0
    serial_rate = N_LINES / best

    # Dwell policy: the short dwell exists ONLY to keep a dead-backend
    # fallback run (600s exhausted probe budget + bench) inside any
    # reasonable driver budget — bench_common.last_fell_back is the
    # explicit signal for exactly that case. Every run whose probe
    # succeeded promptly keeps the full dwell so its percentiles are
    # comparable across artifacts; that deliberately includes both the
    # explicit-CPU run (LOG_PARSER_TPU_PLATFORM=cpu) and a deviceless
    # host whose auto-select probe lands on cpu on attempt 1 (no probe
    # time was burned, so there is no budget to protect). An explicit
    # LOG_PARSER_TPU_CAMPAIGN_S always wins.
    campaign_s = CAMPAIGN_SECONDS
    if bench_common.last_fell_back and "LOG_PARSER_TPU_CAMPAIGN_S" not in os.environ:
        campaign_s = 8.0

    # Chip throughput under serving load: ``analyze_pipelined`` overlaps
    # request N+1's ingest + device execution with request N's host-side
    # sync/finalize (only the frequency-coupled finish serializes), so
    # concurrent streams measure what the chip actually sustains — the
    # serial loop leaves it idle during every host round-trip (through
    # the tunneled backend that idle is ~30% of the request). The
    # campaign holds each concurrency level at steady state for
    # >= CAMPAIGN_SECONDS of wall clock (VERDICT r3 weak #5: the old
    # 4x2-request burst under a best-of selector was too thin a basis
    # for the headline); the serial rate stays in the artifact for
    # comparability.
    def analyze_once() -> None:
        r = engine.analyze_pipelined(next_data())
        assert r.summary.significant_events > 0

    curve, campaign_error = bench_common.run_campaign(
        analyze_once, N_LINES, campaign_s, request_floor_s=best
    )
    measured = [p for p in curve if "error" not in p]
    if not measured:  # nothing steady-state survived — a number here would be a lie
        raise RuntimeError(f"campaign produced no complete level: {campaign_error}")
    # headline methodology is PINNED to the sustained serving throughput
    # at the curve's best point, with that point named in the artifact
    # (not max(serial, pipelined) — that would silently flip methodology
    # between runs); the serial single-stream rate rides alongside
    headline = max(measured, key=lambda p: p["lines_per_sec"])
    extra = {}
    if campaign_error is not None:
        extra["campaign_error"] = campaign_error
    if REPEAT_RATIO is not None:
        extra["repeat_ratio"] = REPEAT_RATIO
        extra["pool_requests"] = len(pool)
    if engine.line_cache is not None:
        extra["line_cache_mb"] = LINE_CACHE_MB
        extra["line_cache"] = engine.line_cache.stats()
    if NOVEL_RATIO > 0:
        extra["novel_ratio"] = NOVEL_RATIO
    if engine.miner is not None:
        extra["miner"] = engine.miner.stats()
        engine.miner.stop()
    from log_parser_tpu.utils import xlacache

    extra["boot_seconds"] = round(boot_seconds, 3)
    extra["compile_cache"] = xlacache.stats()
    obs = getattr(engine, "obs", None)
    if obs is not None:
        # the same Prometheus exposition GET /metrics serves, snapshotted
        # at campaign end — the artifact carries the full counter state
        # the run produced, not just the headline
        extra["metrics"] = obs.registry.render()
    bench_common.emit(
        metric,
        headline["lines_per_sec"],
        "lines/s",
        round(headline["lines_per_sec"] / NORTH_STAR_LINES_PER_SEC, 4),
        platform,
        n_lines=N_LINES,
        n_patterns=n_patterns,
        serial_lines_per_sec=round(serial_rate, 1),
        pipeline_concurrency=headline["concurrency"],
        throughput_curve=curve,
        campaign_seconds=campaign_s,
        # the headline key predates the pipelined methodology; this field
        # disambiguates artifacts across versions (r1-r2: serial best-of,
        # r3: 4x2-burst best-of-2, r4+: steady-state curve, headline at
        # the named best concurrency)
        methodology="pipelined-sustained-v3",
        **extra,
    )


if __name__ == "__main__":
    main()
