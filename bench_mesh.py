"""Config-3 harness: DP over log shards on a device mesh, 1M-line corpus.

BASELINE.md config 3 targets >= 1M scored log-lines/sec END-TO-END on a
TPU v5e-8 — DP over the line axis with ppermute halos, all_gather
sequence columns, and a psum frequency reduce (parallel/sharded.py).
Multi-chip hardware is not available in this environment (one tunneled
chip), so this harness runs the FULL sharded step in one of two modes:

- ``virtual`` (default): an ``--devices N`` virtual CPU mesh
  (``xla_force_host_platform_device_count``, the standard JAX
  fake-backend idiom — SURVEY.md §4). The artifact is labeled
  ``cpu-virtual-mesh<N>``: it proves the mesh program end-to-end at
  corpus scale, NOT multi-chip performance.
- ``real`` (``LOG_PARSER_TPU_MESH=real``): use the process's real
  devices as-is — the mode a future multi-chip host runs.

Single-chip per-chip throughput rides in ``bench_results/config2_tpu``;
the v5e-8 projection from it is documented in PERF.md §8.

``--tenants N`` switches to the multi-tenant placement scenario
(parallel/pattern_sharded.py TenantPlacement): N disjoint tenant engines
round-robined across the mesh, interleaved round-robin traffic, metric
``tenant_mesh_lines_per_sec``. Same virtual/real mode semantics.

``--tenants N --tenant-residency`` instead drives N tenants through a
``runtime/tenancy.py`` TenantRegistry whose byte budget is auto-sized to
hold only N-1 banks (override with ``--tenant-budget-mb``), so the
interleaved round-robin pays LRU evict + warm rebuild inline — metric
``tenant_fleet_lines_per_sec``, the churn-inclusive fleet figure an
operator sees when the tenant set outgrows ``--tenant-budget-mb``.
``--tenant-migrations K`` additionally live-migrates the first K tenants
between two registries (runtime/migrate.py) inside every measured pass,
folding migration churn into the same fleet figure.

``--fleet`` runs the router front-door scenario instead (no mesh):
``--fleet-backends`` serving subprocesses behind a ``--role router``
subprocess, ``--tenants`` (default 1,000) tenant libraries under
zipf-distributed traffic, one mid-rank tenant going hot mid-run and the
placement loop converting its quota sheds into a live migration —
metric ``fleet_router_lines_per_sec``, with the move count, post-move
recovery, and the compiled-pack dedupe savings in the artifact.

Prints exactly one JSON line like every bench:
    {"metric": "dp_mesh_lines_per_sec", "value": N, "unit": "lines/s",
     "vs_baseline": value / 1e6, "platform": ..., ...}
"""

from __future__ import annotations

import os
import sys

N_DEVICES = (
    int(sys.argv[sys.argv.index("--devices") + 1])
    if "--devices" in sys.argv
    else 8
)
N_LINES = (
    int(sys.argv[sys.argv.index("--lines") + 1])
    if "--lines" in sys.argv
    else 1_000_000
)
# --overhead: additionally run the PLAIN single-device engine on the
# same corpus and emit the sharded-vs-plain ratio (VERDICT r4 #4: the
# config-3 "per-chip x 8" projection needs a measured shard-program
# overhead factor — halo exchange, all_gather sequence columns, record
# concat — under it, not a bare x8).  At mesh=1 on a real chip the ratio
# isolates program-structure overhead with zero real communication.
OVERHEAD = "--overhead" in sys.argv
N_TENANTS = (
    int(sys.argv[sys.argv.index("--tenants") + 1])
    if "--tenants" in sys.argv
    else 0
)
RESIDENCY = "--tenant-residency" in sys.argv
BUDGET_MB = (
    float(sys.argv[sys.argv.index("--tenant-budget-mb") + 1])
    if "--tenant-budget-mb" in sys.argv
    else 0.0
)
# --tenant-migrations K: in the residency scenario, live-migrate the
# first K tenants between two registries (runtime/migrate.py LocalTarget)
# inside every measured pass, so the fleet figure INCLUDES migration
# churn — quiesce, bundle export, warm re-verify, frequency restore —
# the way an operator draining nodes mid-traffic would see it
N_MIGRATIONS = (
    int(sys.argv[sys.argv.index("--tenant-migrations") + 1])
    if "--tenant-migrations" in sys.argv
    else 0
)
# --fleet: the router front-door scenario (log_parser_tpu/fleet/) —
# >= 3 serving SUBPROCESSES behind a router subprocess, >= 1,000
# tenants under zipf traffic, one tenant going hot mid-run and the
# placement loop reacting with a live migration. The parent process
# only drives HTTP, so the mesh env setup below is inert for it.
FLEET = "--fleet" in sys.argv
FLEET_BACKENDS = (
    int(sys.argv[sys.argv.index("--fleet-backends") + 1])
    if "--fleet-backends" in sys.argv
    else 3
)
FLEET_REQUESTS = (
    int(sys.argv[sys.argv.index("--fleet-requests") + 1])
    if "--fleet-requests" in sys.argv
    else 1500
)
MODE = os.environ.get("LOG_PARSER_TPU_MESH", "virtual")
if MODE not in ("virtual", "real"):
    # a typo like "Virtual" must not silently select the real path
    sys.exit(f"unknown LOG_PARSER_TPU_MESH={MODE!r}: use 'virtual' or 'real'")

# the mesh topology must be configured BEFORE jax initializes anywhere in
# this process — bench_common is imported after this block on purpose.
# Any pre-set device-count flag is REPLACED (virtual) or STRIPPED (real),
# never deferred to: --devices is the explicit request, and a stale
# forced-host count from an earlier experiment in the same shell must
# neither override it nor masquerade host-CPU devices as a real mesh
import re

_flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+",
    "",
    os.environ.get("XLA_FLAGS", ""),
).strip()
if MODE == "virtual":
    _flags = (_flags + f" --xla_force_host_platform_device_count={N_DEVICES}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = _flags

import bench_common  # noqa: E402  (sets LOG_PARSER_TPU_NO_FALLBACK=1)
from bench import build_corpus  # noqa: E402  (same corpus as config 2)

NORTH_STAR_LINES_PER_SEC = 1_000_000.0


def tenant_main() -> None:
    """Multi-tenant placement scenario: disjoint per-tenant banks pinned
    round-robin across the mesh, interleaved round-robin traffic. Measures
    AGGREGATE lines/s across all tenants — the fleet-serving figure, not a
    per-tenant one."""
    metric = "tenant_mesh_lines_per_sec"
    platform = f"{'cpu-virtual' if MODE == 'virtual' else 'real'}-mesh{N_DEVICES}"
    bounded = bench_common.bounded_runner(metric, "lines/s", lambda: platform)

    visible_devices = 0
    placements: dict = {}

    def setup():
        nonlocal platform, visible_devices
        import jax

        if MODE == "virtual":
            jax.config.update("jax_platforms", "cpu")
        devices = jax.devices()
        visible_devices = len(devices)
        if MODE == "real":
            platform = f"{devices[0].platform}-mesh{N_DEVICES}"
        if len(devices) < N_DEVICES:
            bench_common.exit_null(
                metric,
                "lines/s",
                platform,
                f"need {N_DEVICES} devices, found {len(devices)} on "
                f"{devices[0].platform}",
            )

        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.parallel import TenantPlacement
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
        from log_parser_tpu.runtime import AnalysisEngine

        placement = TenantPlacement(devices[:N_DEVICES])
        engines = []
        for t in range(N_TENANTS):
            eng = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
            engines.append(placement.assign(eng, f"tenant{t}"))
        placements.update(placement.stats()["placements"])
        return engines

    engines = bounded(setup, bench_common.PROBE_TIMEOUT_S, "device init")

    from log_parser_tpu.models.pod import PodFailureData

    per_tenant = max(1, N_LINES // N_TENANTS)
    corpus = build_corpus(per_tenant)
    datas = [
        PodFailureData(
            pod={"metadata": {"name": f"bench-tenant{t}"}}, logs=corpus
        )
        for t in range(N_TENANTS)
    ]

    def sweep():
        result = None
        # interleaved round-robin: each tenant's request runs on its own
        # pinned device; on a real mesh the async dispatches overlap
        for eng, data in zip(engines, datas):
            result = eng.analyze(data)
        return result

    result, _, dt = bench_common.measured_phase(bounded, sweep)
    assert result.summary.significant_events > 0
    total = per_tenant * N_TENANTS
    rate = total / dt

    bench_common.emit(
        metric,
        round(rate, 1),
        "lines/s",
        round(rate / NORTH_STAR_LINES_PER_SEC, 4),
        platform,
        n_lines=total,
        n_devices=N_DEVICES,
        visible_devices=visible_devices,
        mode=MODE,
        n_tenants=N_TENANTS,
        placements=placements,
        n_events=result.summary.significant_events,
    )


def tenant_residency_main() -> None:
    """Fleet-serving residency scenario: N tenant banks interleaved
    round-robin through a TenantRegistry whose byte budget holds only
    N-1 of them, so steady-state traffic pays LRU evict + warm rebuild
    inline (every resolve of the round-robin tail evicts the head).
    Measures AGGREGATE lines/s INCLUDING that churn — the worst-case
    figure an operator sees when the tenant set outgrows
    ``--tenant-budget-mb`` by one bank."""
    import shutil
    import tempfile

    metric = "tenant_fleet_lines_per_sec"
    platform = "cpu" if MODE == "virtual" else "real"
    bounded = bench_common.bounded_runner(metric, "lines/s", lambda: platform)

    state: dict = {}

    def setup():
        nonlocal platform
        import jax

        if MODE == "virtual":
            jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform

        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
        from log_parser_tpu.runtime import AnalysisEngine
        from log_parser_tpu.runtime.tenancy import TenantRegistry

        builtin_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "log_parser_tpu", "patterns", "builtin",
        )
        root = tempfile.mkdtemp(prefix="bench-tenants-")
        for t in range(N_TENANTS):
            shutil.copytree(builtin_dir, os.path.join(root, f"tenant{t}"))
        default_engine = AnalysisEngine(
            load_builtin_pattern_sets(), ScoringConfig()
        )
        # probe one bank (unlimited budget) to size the real budget at
        # N-1 banks + half, guaranteeing churn without instant thrash of
        # the tenant that was just resolved
        probe = TenantRegistry(default_engine, root=root)
        bank_mb = probe.resolve("tenant0").bank_bytes / 2**20
        probe.shutdown()
        budget_mb = BUDGET_MB or (N_TENANTS - 1 + 0.5) * bank_mb
        reg = TenantRegistry(default_engine, root=root, budget_mb=budget_mb)
        state["registry"] = reg
        state["bank_mb"] = bank_mb
        if N_MIGRATIONS:
            from log_parser_tpu.runtime.migrate import LocalTarget, Migrator

            # a peer registry over the SAME library root (the bank
            # content-hash verify requires identical config) — tenants
            # ping-pong between the two, each hop a full protocol run
            peer = TenantRegistry(
                default_engine, root=root, budget_mb=budget_mb
            )
            mig_a = Migrator(
                reg, state_root=tempfile.mkdtemp(prefix="bench-mig-a-")
            )
            mig_b = Migrator(
                peer, state_root=tempfile.mkdtemp(prefix="bench-mig-b-")
            )
            state["sides"] = [(reg, mig_a), (peer, mig_b)]
            state["side_of"] = {}  # tenant id -> index into sides
            state["migrations"] = 0
        return reg

    reg = bounded(setup, bench_common.PROBE_TIMEOUT_S, "device init")

    from log_parser_tpu.models.pod import PodFailureData

    per_tenant = max(1, N_LINES // N_TENANTS)
    corpus = build_corpus(per_tenant)
    datas = [
        PodFailureData(
            pod={"metadata": {"name": f"bench-tenant{t}"}}, logs=corpus
        )
        for t in range(N_TENANTS)
    ]

    def sweep():
        from log_parser_tpu.runtime.migrate import LocalTarget

        result = None
        # each resolve may evict the LRU tenant and rebuild the target's
        # bank (warm through the compiled-DFA snapshot cache) before the
        # request runs — churn is part of the measured figure on purpose
        for t, data in enumerate(datas):
            tid = f"tenant{t}"
            if N_MIGRATIONS:
                side = state["side_of"].get(tid, 0)
                owner_reg = state["sides"][side][0]
            else:
                owner_reg = reg
            ctx = owner_reg.resolve(tid)
            try:
                result = ctx.engine.analyze(data)
            finally:
                # release the resolve lease: a pinned context is
                # eviction-proof, and this scenario MUST churn
                ctx.unpin()
            if N_MIGRATIONS and t < N_MIGRATIONS:
                # live-migrate the tenant to the other registry: a full
                # protocol pass (quiesce, export, stage + bank-hash
                # verify, cutover, frequency restore) inside the
                # measured window; the next pass migrates it back
                side = state["side_of"].get(tid, 0)
                dst = 1 - side
                src_mig = state["sides"][side][1]
                dst_mig = state["sides"][dst][1]
                src_mig.migrate(
                    tid, LocalTarget(dst_mig, url=f"local://side{dst}")
                )
                state["side_of"][tid] = dst
                state["migrations"] += 1
        return result

    result, _, dt = bench_common.measured_phase(bounded, sweep)
    assert result.summary.significant_events > 0
    stats = reg.stats()
    assert stats["evicted"] >= 1 and stats["rebuilds"] >= 1, (
        "residency scenario must churn: " + repr(stats)
    )
    total = per_tenant * N_TENANTS
    rate = total / dt

    bench_common.emit(
        metric,
        round(rate, 1),
        "lines/s",
        round(rate / NORTH_STAR_LINES_PER_SEC, 4),
        platform,
        n_lines=total,
        mode=MODE,
        n_tenants=N_TENANTS,
        bank_mb=round(state["bank_mb"], 3),
        budget_mb=round(stats["budgetMb"], 3),
        resident_tenants=stats["residentTenants"],
        resident_bank_mb=stats["residentBankMb"],
        resolved=stats["resolved"],
        created=stats["created"],
        evicted=stats["evicted"],
        rebuilds=stats["rebuilds"],
        n_events=result.summary.significant_events,
        **(
            {"migrations": state["migrations"],
             "migrations_per_pass": N_MIGRATIONS}
            if N_MIGRATIONS
            else {}
        ),
    )


_TENANT_LIB_YAML = """
metadata:
  library_id: fleet-lib
patterns:
  - id: oom
    name: Out of memory
    severity: CRITICAL
    primary_pattern:
      regex: OutOfMemoryError
      confidence: 0.9
  - id: err
    name: Errors
    severity: LOW
    primary_pattern:
      regex: "\\\\bERROR\\\\b"
      confidence: 0.5
"""


class _FleetChild:
    """One serve subprocess (backend or router); log to a temp file so
    the parent's stdout stays a single artifact JSON line."""

    def __init__(self, name: str, args: list):
        import socket
        import subprocess
        import tempfile

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            self.port = s.getsockname()[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self.log = tempfile.NamedTemporaryFile(
            "wb", prefix=f"bench_fleet_{name}_", suffix=".log", delete=False
        )
        pattern_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "log_parser_tpu", "patterns", "builtin",
        )
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "log_parser_tpu.serve",
             "--pattern-dir", pattern_dir,
             "--host", "127.0.0.1", "--port", str(self.port), *args],
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONUNBUFFERED": "1"},
            stdout=self.log, stderr=self.log,
        )

    def wait_ready(self, timeout: float = 120.0) -> None:
        import time
        import urllib.request

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"fleet child died rc={self.proc.returncode} "
                    f"(log: {self.log.name})"
                )
            try:
                with urllib.request.urlopen(
                    self.url + "/health/ready", timeout=5
                ) as resp:
                    if resp.status == 200:
                        return
            except OSError:
                time.sleep(0.25)
        raise RuntimeError(f"fleet child never ready (log: {self.log.name})")

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(20)
            except Exception:
                self.proc.kill()
                self.proc.wait(10)


def _fleet_post(url: str, body: bytes, tenant: str) -> int:
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url + "/parse", data=body,
        headers={"Content-Type": "application/json", "X-Tenant": tenant},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            resp.read()
            return resp.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code
    except OSError:
        return -1


def _fleet_metric(url: str, family: str, label: str = "") -> float:
    import urllib.request

    with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
        text = resp.read().decode()
    total = 0.0
    for line in text.splitlines():
        if line.startswith(family) and (not label or label in line):
            try:
                total += float(line.rsplit(None, 1)[1])
            except ValueError:
                pass
    return total


def _dedupe_probe(n_banks: int) -> dict:
    """The compiled-bank substructure-sharing half of the fleet story,
    measured in-process: N identical banks with the pack memo on vs
    off. Sharing must build exactly ONE pack; the unshared baseline
    re-loads (and re-holds) a private pack per bank."""
    import tempfile
    import time

    from log_parser_tpu.patterns import libcache
    from log_parser_tpu.patterns.bank import PatternBank
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

    sets = load_builtin_pattern_sets()
    os.environ["LOG_PARSER_TPU_CACHE"] = tempfile.mkdtemp(
        prefix="bench-fleet-packs-"
    )
    PatternBank(sets)  # seed the on-disk snapshot outside both timings

    libcache.reset_packs()
    t0 = time.perf_counter()
    shared_banks = [PatternBank(sets) for _ in range(n_banks)]
    dt_shared = time.perf_counter() - t0
    stats = libcache.pack_stats()
    assert stats["built"] <= 1 and stats["shared"] >= n_banks - 1, stats

    os.environ["LOG_PARSER_TPU_PACK_SHARE"] = "0"
    libcache.reset_packs()
    t0 = time.perf_counter()
    unshared_banks = [PatternBank(sets) for _ in range(n_banks)]
    dt_unshared = time.perf_counter() - t0
    del os.environ["LOG_PARSER_TPU_PACK_SHARE"]
    assert len(shared_banks) == len(unshared_banks)

    pack_bytes = stats["residentBytes"]
    return {
        "dedupe_banks": n_banks,
        "pack_builds": stats["built"],
        "pack_shared": stats["shared"],
        "pack_bytes": pack_bytes,
        "dedupe_saved_mb": round(pack_bytes * (n_banks - 1) / 2**20, 2),
        "build_s_shared": round(dt_shared, 3),
        "build_s_unshared": round(dt_unshared, 3),
        "build_speedup": round(dt_unshared / max(dt_shared, 1e-9), 1),
    }


def fleet_main() -> None:
    """Fleet front-door scenario: FLEET_BACKENDS serving subprocesses
    behind a router subprocess, >= 1,000 tenants under zipf-distributed
    traffic, one mid-rank tenant going hot mid-run. The placement loop
    must convert the hot tenant's quota sheds into a live migration; the
    artifact records the aggregate routed lines/s, the move count, and
    the hot tenant's post-move recovery, plus the compiled-pack dedupe
    savings that make 1,000 same-pattern tenants per process viable."""
    import bisect
    import json as _json
    import random
    import shutil
    import tempfile
    import threading
    import time
    from concurrent.futures import ThreadPoolExecutor

    n_tenants = N_TENANTS or 1000
    metric = "fleet_router_lines_per_sec"
    platform = f"cpu-fleet{FLEET_BACKENDS}"
    bounded = bench_common.bounded_runner(metric, "lines/s", lambda: platform)

    tmp = tempfile.mkdtemp(prefix="bench-fleet-")
    tenants = [f"t{i:04d}" for i in range(n_tenants)]
    children: list[_FleetChild] = []

    def setup():
        root = os.path.join(tmp, "tenants")
        for tid in tenants:
            d = os.path.join(root, tid)
            os.makedirs(d)
            with open(os.path.join(d, "lib.yaml"), "w") as f:
                f.write(_TENANT_LIB_YAML)
        backends = [
            _FleetChild(
                f"backend{i}",
                ["--tenant-root", root,
                 "--state-dir", os.path.join(tmp, f"state{i}"),
                 "--tenant-lines-per-s", "100"],
            )
            for i in range(FLEET_BACKENDS)
        ]
        children.extend(backends)
        for b in backends:
            b.wait_ready()
        router = _FleetChild(
            "router",
            ["--role", "router",
             "--backends", ",".join(f"127.0.0.1:{b.port}" for b in backends),
             "--fleet-poll-s", "0.5", "--fleet-shed-rate", "0.5",
             # 1,000 cold tenants all build banks on first touch; that
             # is fill, not thrash — park the thrash trigger so the
             # only move is the hot tenant's quota-shed one
             "--fleet-thrash-rebuilds", "100000",
             "--fleet-down-after", "10"],
        )
        children.append(router)
        router.wait_ready()
        return router

    router = bounded(setup, bench_common.PROBE_TIMEOUT_S, "fleet boot")

    # zipf(1.1) over the tenant ranks — a head-heavy fleet traffic shape
    alpha = 1.1
    weights = [1.0 / (r ** alpha) for r in range(1, n_tenants + 1)]
    cum = []
    acc = 0.0
    for w in weights:
        acc += w
        cum.append(acc)
    rng = random.Random(4217)

    def pick() -> str:
        return tenants[bisect.bisect_left(cum, rng.random() * acc)]

    body_lines = 20
    body = _json.dumps(
        {"pod": {"metadata": {"name": "bench-fleet"}},
         "logs": build_corpus(body_lines)}
    ).encode()
    hot_tenant = tenants[42]  # mid-rank: background share is negligible
    hot_body = _json.dumps(
        {"pod": {"metadata": {"name": "bench-fleet-hot"}},
         "logs": build_corpus(200)}
    ).encode()

    counts = {"ok": 0, "shed": 0, "other": 0, "lines_ok": 0}
    lock = threading.Lock()

    def drive(tenant: str, payload: bytes, n_lines: int) -> int:
        status = _fleet_post(router.url, payload, tenant)
        with lock:
            if status == 200:
                counts["ok"] += 1
                counts["lines_ok"] += n_lines
            elif status == 429:
                counts["shed"] += 1
            else:
                counts["other"] += 1
        return status

    report: dict = {}

    def campaign():
        t0 = time.perf_counter()
        # steady zipf phase
        with ThreadPoolExecutor(max_workers=8) as pool:
            for f in [pool.submit(drive, pick(), body, body_lines)
                      for _ in range(FLEET_REQUESTS)]:
                f.result()
        # hot phase: hammer one tenant past its lines/s budget while
        # background zipf traffic keeps flowing, until the placer moves it
        stop = threading.Event()
        hot_sheds = [0]

        def hammer():
            while not stop.is_set():
                if drive(hot_tenant, hot_body, 200) == 429:
                    hot_sheds[0] += 1

        def background():
            while not stop.is_set():
                drive(pick(), body, body_lines)
                time.sleep(0.05)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        threads += [threading.Thread(target=background) for _ in range(2)]
        for t in threads:
            t.start()
        moved_at = None
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if _fleet_metric(router.url,
                                 "logparser_fleet_moves_total") >= 1:
                    moved_at = time.monotonic()
                    break
                time.sleep(0.5)
        finally:
            stop.set()
            for t in threads:
                t.join(60)
        if moved_at is None:
            raise RuntimeError("placer never moved the hot tenant")
        # recovery: the moved tenant lands on a fresh lines/s bucket, so
        # normal-pace traffic must be clean again
        recovered_at = None
        post_ok = 0
        for _ in range(10):
            if drive(hot_tenant, body, body_lines) == 200:
                post_ok += 1
                recovered_at = recovered_at or time.monotonic()
            time.sleep(0.2)
        dt = time.perf_counter() - t0
        report.update(
            requests_ok=counts["ok"],
            requests_shed=counts["shed"],
            requests_other=counts["other"],
            hot_sheds_pre_move=hot_sheds[0],
            moves=_fleet_metric(router.url, "logparser_fleet_moves_total"),
            **{
                f"moves_{reason}": _fleet_metric(
                    router.url, "logparser_fleet_moves_total", reason
                )
                for reason in ("quota_shed", "slo_burn", "residency_thrash")
            },
            backends_up=_fleet_metric(
                router.url, "logparser_fleet_backends_up"
            ),
            post_move_ok=post_ok,
            post_move_recovery_s=(
                round(recovered_at - moved_at, 2) if recovered_at else None
            ),
        )
        assert report["moves_quota_shed"] >= 1, report
        assert report["requests_other"] <= 2, report
        assert post_ok >= 8, report  # SLO burn recovered after the move
        return counts["lines_ok"] / dt

    try:
        rate = bounded(campaign, bench_common.PROBE_TIMEOUT_S,
                       "fleet campaign")
        dedupe = bounded(lambda: _dedupe_probe(64),
                         bench_common.PROBE_TIMEOUT_S, "pack dedupe")
    finally:
        for c in reversed(children):
            c.stop()
        shutil.rmtree(tmp, ignore_errors=True)

    bench_common.emit(
        metric,
        round(rate, 1),
        "lines/s",
        round(rate / NORTH_STAR_LINES_PER_SEC, 4),
        platform,
        n_tenants=n_tenants,
        n_backends=FLEET_BACKENDS,
        zipf_alpha=alpha,
        hot_tenant=hot_tenant,
        **report,
        **dedupe,
    )


def main() -> None:
    if FLEET:
        fleet_main()
        return
    if N_TENANTS and (RESIDENCY or BUDGET_MB):
        tenant_residency_main()
        return
    if N_TENANTS:
        tenant_main()
        return
    metric = "dp_mesh_lines_per_sec"
    platform = f"{'cpu-virtual' if MODE == 'virtual' else 'real'}-mesh{N_DEVICES}"

    # in ``real`` mode device discovery and every analyze() go through a
    # possibly-wedged backend; the contract is a {"value": null}
    # diagnostics exit, never an unbounded hang. The label getter reads
    # the CURRENT platform: setup() refines it in real mode
    bounded = bench_common.bounded_runner(metric, "lines/s", lambda: platform)

    visible_devices = 0

    def setup():
        nonlocal platform, visible_devices
        import jax

        if MODE == "virtual":
            # the axon sitecustomize force-sets jax_platforms="axon,cpu"
            # at config level; honor the virtual-mesh request (same
            # re-pin as __graft_entry__.dryrun_multichip)
            jax.config.update("jax_platforms", "cpu")
        devices = jax.devices()
        visible_devices = len(devices)
        if MODE == "real":
            # label with what the devices actually ARE (the stale-flag
            # masquerade is already prevented by the flag strip above;
            # this makes the artifact self-describing either way)
            platform = f"{devices[0].platform}-mesh{N_DEVICES}"
        if len(devices) < N_DEVICES:
            bench_common.exit_null(
                metric,
                "lines/s",
                platform,
                f"need {N_DEVICES} devices, found {len(devices)} on "
                f"{devices[0].platform}",
            )

        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.parallel import ShardedEngine, make_mesh
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

        mesh = make_mesh(N_DEVICES)
        return ShardedEngine(
            load_builtin_pattern_sets(), ScoringConfig(), mesh=mesh
        )

    engine = bounded(setup, bench_common.PROBE_TIMEOUT_S, "device init")

    from log_parser_tpu.models.pod import PodFailureData

    data = PodFailureData(
        pod={"metadata": {"name": "bench-mesh"}}, logs=build_corpus(N_LINES)
    )

    # warmup (sharded-program compile) + best-of-n under the shared
    # sequence (bench_common.measured_phase)
    result, _, dt = bench_common.measured_phase(
        bounded, lambda: engine.analyze(data)
    )
    assert result.summary.significant_events > 0
    rate = N_LINES / dt

    extra: dict = {}
    if OVERHEAD:
        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
        from log_parser_tpu.runtime import AnalysisEngine

        def plain_setup():
            return AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())

        plain = bounded(plain_setup, bench_common.PROBE_TIMEOUT_S, "plain init")
        plain_result, _, plain_dt = bench_common.measured_phase(
            bounded, lambda: plain.analyze(data)
        )
        plain_rate = N_LINES / plain_dt
        extra = {
            "plain_lines_per_sec": round(plain_rate, 1),
            # two views, because they answer different questions:
            # - per_device: overhead the shard program adds per REAL
            #   device (meaningful on hardware meshes; at mesh=1 it is
            #   pure program structure with zero communication)
            # - total: sharded/plain at equal wall — the right bound on
            #   a TIME-SHARED virtual mesh, where N "devices" split one
            #   core and the per-device division means nothing
            "shard_overhead_per_device": round(
                1.0 - (rate / N_DEVICES) / plain_rate, 4
            ),
            "sharded_vs_plain_total": round(rate / plain_rate, 4),
        }
        if (
            plain_result.summary.significant_events
            != result.summary.significant_events
        ):
            # a parity divergence is the SUITE's job to fail on; the
            # bench's contract is one JSON line — record the
            # disagreement beside the already-measured rates instead of
            # crashing after both expensive phases completed
            extra["overhead_parity_mismatch"] = (
                f"sharded {result.summary.significant_events} != "
                f"plain {plain_result.summary.significant_events} events"
            )

    bench_common.emit(
        metric,
        round(rate, 1),
        "lines/s",
        round(rate / NORTH_STAR_LINES_PER_SEC, 4),
        platform,
        n_lines=N_LINES,
        n_devices=N_DEVICES,
        # OBSERVED count, not an echo of --devices: lets consumers (and
        # the smoke test) verify the topology request actually took
        visible_devices=visible_devices,
        mode=MODE,
        n_events=result.summary.significant_events,
        **extra,
    )


if __name__ == "__main__":
    main()
