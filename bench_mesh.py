"""Config-3 harness: DP over log shards on a device mesh, 1M-line corpus.

BASELINE.md config 3 targets >= 1M scored log-lines/sec END-TO-END on a
TPU v5e-8 — DP over the line axis with ppermute halos, all_gather
sequence columns, and a psum frequency reduce (parallel/sharded.py).
Multi-chip hardware is not available in this environment (one tunneled
chip), so this harness runs the FULL sharded step in one of two modes:

- ``virtual`` (default): an ``--devices N`` virtual CPU mesh
  (``xla_force_host_platform_device_count``, the standard JAX
  fake-backend idiom — SURVEY.md §4). The artifact is labeled
  ``cpu-virtual-mesh<N>``: it proves the mesh program end-to-end at
  corpus scale, NOT multi-chip performance.
- ``real`` (``LOG_PARSER_TPU_MESH=real``): use the process's real
  devices as-is — the mode a future multi-chip host runs.

Single-chip per-chip throughput rides in ``bench_results/config2_tpu``;
the v5e-8 projection from it is documented in PERF.md §8.

Prints exactly one JSON line like every bench:
    {"metric": "dp_mesh_lines_per_sec", "value": N, "unit": "lines/s",
     "vs_baseline": value / 1e6, "platform": ..., ...}
"""

from __future__ import annotations

import os
import sys

N_DEVICES = (
    int(sys.argv[sys.argv.index("--devices") + 1])
    if "--devices" in sys.argv
    else 8
)
N_LINES = (
    int(sys.argv[sys.argv.index("--lines") + 1])
    if "--lines" in sys.argv
    else 1_000_000
)
# --overhead: additionally run the PLAIN single-device engine on the
# same corpus and emit the sharded-vs-plain ratio (VERDICT r4 #4: the
# config-3 "per-chip x 8" projection needs a measured shard-program
# overhead factor — halo exchange, all_gather sequence columns, record
# concat — under it, not a bare x8).  At mesh=1 on a real chip the ratio
# isolates program-structure overhead with zero real communication.
OVERHEAD = "--overhead" in sys.argv
MODE = os.environ.get("LOG_PARSER_TPU_MESH", "virtual")
if MODE not in ("virtual", "real"):
    # a typo like "Virtual" must not silently select the real path
    sys.exit(f"unknown LOG_PARSER_TPU_MESH={MODE!r}: use 'virtual' or 'real'")

# the mesh topology must be configured BEFORE jax initializes anywhere in
# this process — bench_common is imported after this block on purpose.
# Any pre-set device-count flag is REPLACED (virtual) or STRIPPED (real),
# never deferred to: --devices is the explicit request, and a stale
# forced-host count from an earlier experiment in the same shell must
# neither override it nor masquerade host-CPU devices as a real mesh
import re

_flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+",
    "",
    os.environ.get("XLA_FLAGS", ""),
).strip()
if MODE == "virtual":
    _flags = (_flags + f" --xla_force_host_platform_device_count={N_DEVICES}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = _flags

import bench_common  # noqa: E402  (sets LOG_PARSER_TPU_NO_FALLBACK=1)
from bench import build_corpus  # noqa: E402  (same corpus as config 2)

NORTH_STAR_LINES_PER_SEC = 1_000_000.0


def main() -> None:
    metric = "dp_mesh_lines_per_sec"
    platform = f"{'cpu-virtual' if MODE == 'virtual' else 'real'}-mesh{N_DEVICES}"

    # in ``real`` mode device discovery and every analyze() go through a
    # possibly-wedged backend; the contract is a {"value": null}
    # diagnostics exit, never an unbounded hang. The label getter reads
    # the CURRENT platform: setup() refines it in real mode
    bounded = bench_common.bounded_runner(metric, "lines/s", lambda: platform)

    visible_devices = 0

    def setup():
        nonlocal platform, visible_devices
        import jax

        if MODE == "virtual":
            # the axon sitecustomize force-sets jax_platforms="axon,cpu"
            # at config level; honor the virtual-mesh request (same
            # re-pin as __graft_entry__.dryrun_multichip)
            jax.config.update("jax_platforms", "cpu")
        devices = jax.devices()
        visible_devices = len(devices)
        if MODE == "real":
            # label with what the devices actually ARE (the stale-flag
            # masquerade is already prevented by the flag strip above;
            # this makes the artifact self-describing either way)
            platform = f"{devices[0].platform}-mesh{N_DEVICES}"
        if len(devices) < N_DEVICES:
            bench_common.exit_null(
                metric,
                "lines/s",
                platform,
                f"need {N_DEVICES} devices, found {len(devices)} on "
                f"{devices[0].platform}",
            )

        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.parallel import ShardedEngine, make_mesh
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

        mesh = make_mesh(N_DEVICES)
        return ShardedEngine(
            load_builtin_pattern_sets(), ScoringConfig(), mesh=mesh
        )

    engine = bounded(setup, bench_common.PROBE_TIMEOUT_S, "device init")

    from log_parser_tpu.models.pod import PodFailureData

    data = PodFailureData(
        pod={"metadata": {"name": "bench-mesh"}}, logs=build_corpus(N_LINES)
    )

    # warmup (sharded-program compile) + best-of-n under the shared
    # sequence (bench_common.measured_phase)
    result, _, dt = bench_common.measured_phase(
        bounded, lambda: engine.analyze(data)
    )
    assert result.summary.significant_events > 0
    rate = N_LINES / dt

    extra: dict = {}
    if OVERHEAD:
        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
        from log_parser_tpu.runtime import AnalysisEngine

        def plain_setup():
            return AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())

        plain = bounded(plain_setup, bench_common.PROBE_TIMEOUT_S, "plain init")
        plain_result, _, plain_dt = bench_common.measured_phase(
            bounded, lambda: plain.analyze(data)
        )
        plain_rate = N_LINES / plain_dt
        extra = {
            "plain_lines_per_sec": round(plain_rate, 1),
            # two views, because they answer different questions:
            # - per_device: overhead the shard program adds per REAL
            #   device (meaningful on hardware meshes; at mesh=1 it is
            #   pure program structure with zero communication)
            # - total: sharded/plain at equal wall — the right bound on
            #   a TIME-SHARED virtual mesh, where N "devices" split one
            #   core and the per-device division means nothing
            "shard_overhead_per_device": round(
                1.0 - (rate / N_DEVICES) / plain_rate, 4
            ),
            "sharded_vs_plain_total": round(rate / plain_rate, 4),
        }
        if (
            plain_result.summary.significant_events
            != result.summary.significant_events
        ):
            # a parity divergence is the SUITE's job to fail on; the
            # bench's contract is one JSON line — record the
            # disagreement beside the already-measured rates instead of
            # crashing after both expensive phases completed
            extra["overhead_parity_mismatch"] = (
                f"sharded {result.summary.significant_events} != "
                f"plain {plain_result.summary.significant_events} events"
            )

    bench_common.emit(
        metric,
        round(rate, 1),
        "lines/s",
        round(rate / NORTH_STAR_LINES_PER_SEC, 4),
        platform,
        n_lines=N_LINES,
        n_devices=N_DEVICES,
        # OBSERVED count, not an echo of --devices: lets consumers (and
        # the smoke test) verify the topology request actually took
        visible_devices=visible_devices,
        mode=MODE,
        n_events=result.summary.significant_events,
        **extra,
    )


if __name__ == "__main__":
    main()
