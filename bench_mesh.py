"""Config-3 harness: DP over log shards on a device mesh, 1M-line corpus.

BASELINE.md config 3 targets >= 1M scored log-lines/sec END-TO-END on a
TPU v5e-8 — DP over the line axis with ppermute halos, all_gather
sequence columns, and a psum frequency reduce (parallel/sharded.py).
Multi-chip hardware is not available in this environment (one tunneled
chip), so this harness runs the FULL sharded step in one of two modes:

- ``virtual`` (default): an ``--devices N`` virtual CPU mesh
  (``xla_force_host_platform_device_count``, the standard JAX
  fake-backend idiom — SURVEY.md §4). The artifact is labeled
  ``cpu-virtual-mesh<N>``: it proves the mesh program end-to-end at
  corpus scale, NOT multi-chip performance.
- ``real`` (``LOG_PARSER_TPU_MESH=real``): use the process's real
  devices as-is — the mode a future multi-chip host runs.

Single-chip per-chip throughput rides in ``bench_results/config2_tpu``;
the v5e-8 projection from it is documented in PERF.md §8.

``--tenants N`` switches to the multi-tenant placement scenario
(parallel/pattern_sharded.py TenantPlacement): N disjoint tenant engines
round-robined across the mesh, interleaved round-robin traffic, metric
``tenant_mesh_lines_per_sec``. Same virtual/real mode semantics.

``--tenants N --tenant-residency`` instead drives N tenants through a
``runtime/tenancy.py`` TenantRegistry whose byte budget is auto-sized to
hold only N-1 banks (override with ``--tenant-budget-mb``), so the
interleaved round-robin pays LRU evict + warm rebuild inline — metric
``tenant_fleet_lines_per_sec``, the churn-inclusive fleet figure an
operator sees when the tenant set outgrows ``--tenant-budget-mb``.
``--tenant-migrations K`` additionally live-migrates the first K tenants
between two registries (runtime/migrate.py) inside every measured pass,
folding migration churn into the same fleet figure.

Prints exactly one JSON line like every bench:
    {"metric": "dp_mesh_lines_per_sec", "value": N, "unit": "lines/s",
     "vs_baseline": value / 1e6, "platform": ..., ...}
"""

from __future__ import annotations

import os
import sys

N_DEVICES = (
    int(sys.argv[sys.argv.index("--devices") + 1])
    if "--devices" in sys.argv
    else 8
)
N_LINES = (
    int(sys.argv[sys.argv.index("--lines") + 1])
    if "--lines" in sys.argv
    else 1_000_000
)
# --overhead: additionally run the PLAIN single-device engine on the
# same corpus and emit the sharded-vs-plain ratio (VERDICT r4 #4: the
# config-3 "per-chip x 8" projection needs a measured shard-program
# overhead factor — halo exchange, all_gather sequence columns, record
# concat — under it, not a bare x8).  At mesh=1 on a real chip the ratio
# isolates program-structure overhead with zero real communication.
OVERHEAD = "--overhead" in sys.argv
N_TENANTS = (
    int(sys.argv[sys.argv.index("--tenants") + 1])
    if "--tenants" in sys.argv
    else 0
)
RESIDENCY = "--tenant-residency" in sys.argv
BUDGET_MB = (
    float(sys.argv[sys.argv.index("--tenant-budget-mb") + 1])
    if "--tenant-budget-mb" in sys.argv
    else 0.0
)
# --tenant-migrations K: in the residency scenario, live-migrate the
# first K tenants between two registries (runtime/migrate.py LocalTarget)
# inside every measured pass, so the fleet figure INCLUDES migration
# churn — quiesce, bundle export, warm re-verify, frequency restore —
# the way an operator draining nodes mid-traffic would see it
N_MIGRATIONS = (
    int(sys.argv[sys.argv.index("--tenant-migrations") + 1])
    if "--tenant-migrations" in sys.argv
    else 0
)
MODE = os.environ.get("LOG_PARSER_TPU_MESH", "virtual")
if MODE not in ("virtual", "real"):
    # a typo like "Virtual" must not silently select the real path
    sys.exit(f"unknown LOG_PARSER_TPU_MESH={MODE!r}: use 'virtual' or 'real'")

# the mesh topology must be configured BEFORE jax initializes anywhere in
# this process — bench_common is imported after this block on purpose.
# Any pre-set device-count flag is REPLACED (virtual) or STRIPPED (real),
# never deferred to: --devices is the explicit request, and a stale
# forced-host count from an earlier experiment in the same shell must
# neither override it nor masquerade host-CPU devices as a real mesh
import re

_flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+",
    "",
    os.environ.get("XLA_FLAGS", ""),
).strip()
if MODE == "virtual":
    _flags = (_flags + f" --xla_force_host_platform_device_count={N_DEVICES}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = _flags

import bench_common  # noqa: E402  (sets LOG_PARSER_TPU_NO_FALLBACK=1)
from bench import build_corpus  # noqa: E402  (same corpus as config 2)

NORTH_STAR_LINES_PER_SEC = 1_000_000.0


def tenant_main() -> None:
    """Multi-tenant placement scenario: disjoint per-tenant banks pinned
    round-robin across the mesh, interleaved round-robin traffic. Measures
    AGGREGATE lines/s across all tenants — the fleet-serving figure, not a
    per-tenant one."""
    metric = "tenant_mesh_lines_per_sec"
    platform = f"{'cpu-virtual' if MODE == 'virtual' else 'real'}-mesh{N_DEVICES}"
    bounded = bench_common.bounded_runner(metric, "lines/s", lambda: platform)

    visible_devices = 0
    placements: dict = {}

    def setup():
        nonlocal platform, visible_devices
        import jax

        if MODE == "virtual":
            jax.config.update("jax_platforms", "cpu")
        devices = jax.devices()
        visible_devices = len(devices)
        if MODE == "real":
            platform = f"{devices[0].platform}-mesh{N_DEVICES}"
        if len(devices) < N_DEVICES:
            bench_common.exit_null(
                metric,
                "lines/s",
                platform,
                f"need {N_DEVICES} devices, found {len(devices)} on "
                f"{devices[0].platform}",
            )

        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.parallel import TenantPlacement
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
        from log_parser_tpu.runtime import AnalysisEngine

        placement = TenantPlacement(devices[:N_DEVICES])
        engines = []
        for t in range(N_TENANTS):
            eng = AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())
            engines.append(placement.assign(eng, f"tenant{t}"))
        placements.update(placement.stats()["placements"])
        return engines

    engines = bounded(setup, bench_common.PROBE_TIMEOUT_S, "device init")

    from log_parser_tpu.models.pod import PodFailureData

    per_tenant = max(1, N_LINES // N_TENANTS)
    corpus = build_corpus(per_tenant)
    datas = [
        PodFailureData(
            pod={"metadata": {"name": f"bench-tenant{t}"}}, logs=corpus
        )
        for t in range(N_TENANTS)
    ]

    def sweep():
        result = None
        # interleaved round-robin: each tenant's request runs on its own
        # pinned device; on a real mesh the async dispatches overlap
        for eng, data in zip(engines, datas):
            result = eng.analyze(data)
        return result

    result, _, dt = bench_common.measured_phase(bounded, sweep)
    assert result.summary.significant_events > 0
    total = per_tenant * N_TENANTS
    rate = total / dt

    bench_common.emit(
        metric,
        round(rate, 1),
        "lines/s",
        round(rate / NORTH_STAR_LINES_PER_SEC, 4),
        platform,
        n_lines=total,
        n_devices=N_DEVICES,
        visible_devices=visible_devices,
        mode=MODE,
        n_tenants=N_TENANTS,
        placements=placements,
        n_events=result.summary.significant_events,
    )


def tenant_residency_main() -> None:
    """Fleet-serving residency scenario: N tenant banks interleaved
    round-robin through a TenantRegistry whose byte budget holds only
    N-1 of them, so steady-state traffic pays LRU evict + warm rebuild
    inline (every resolve of the round-robin tail evicts the head).
    Measures AGGREGATE lines/s INCLUDING that churn — the worst-case
    figure an operator sees when the tenant set outgrows
    ``--tenant-budget-mb`` by one bank."""
    import shutil
    import tempfile

    metric = "tenant_fleet_lines_per_sec"
    platform = "cpu" if MODE == "virtual" else "real"
    bounded = bench_common.bounded_runner(metric, "lines/s", lambda: platform)

    state: dict = {}

    def setup():
        nonlocal platform
        import jax

        if MODE == "virtual":
            jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform

        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
        from log_parser_tpu.runtime import AnalysisEngine
        from log_parser_tpu.runtime.tenancy import TenantRegistry

        builtin_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "log_parser_tpu", "patterns", "builtin",
        )
        root = tempfile.mkdtemp(prefix="bench-tenants-")
        for t in range(N_TENANTS):
            shutil.copytree(builtin_dir, os.path.join(root, f"tenant{t}"))
        default_engine = AnalysisEngine(
            load_builtin_pattern_sets(), ScoringConfig()
        )
        # probe one bank (unlimited budget) to size the real budget at
        # N-1 banks + half, guaranteeing churn without instant thrash of
        # the tenant that was just resolved
        probe = TenantRegistry(default_engine, root=root)
        bank_mb = probe.resolve("tenant0").bank_bytes / 2**20
        probe.shutdown()
        budget_mb = BUDGET_MB or (N_TENANTS - 1 + 0.5) * bank_mb
        reg = TenantRegistry(default_engine, root=root, budget_mb=budget_mb)
        state["registry"] = reg
        state["bank_mb"] = bank_mb
        if N_MIGRATIONS:
            from log_parser_tpu.runtime.migrate import LocalTarget, Migrator

            # a peer registry over the SAME library root (the bank
            # content-hash verify requires identical config) — tenants
            # ping-pong between the two, each hop a full protocol run
            peer = TenantRegistry(
                default_engine, root=root, budget_mb=budget_mb
            )
            mig_a = Migrator(
                reg, state_root=tempfile.mkdtemp(prefix="bench-mig-a-")
            )
            mig_b = Migrator(
                peer, state_root=tempfile.mkdtemp(prefix="bench-mig-b-")
            )
            state["sides"] = [(reg, mig_a), (peer, mig_b)]
            state["side_of"] = {}  # tenant id -> index into sides
            state["migrations"] = 0
        return reg

    reg = bounded(setup, bench_common.PROBE_TIMEOUT_S, "device init")

    from log_parser_tpu.models.pod import PodFailureData

    per_tenant = max(1, N_LINES // N_TENANTS)
    corpus = build_corpus(per_tenant)
    datas = [
        PodFailureData(
            pod={"metadata": {"name": f"bench-tenant{t}"}}, logs=corpus
        )
        for t in range(N_TENANTS)
    ]

    def sweep():
        from log_parser_tpu.runtime.migrate import LocalTarget

        result = None
        # each resolve may evict the LRU tenant and rebuild the target's
        # bank (warm through the compiled-DFA snapshot cache) before the
        # request runs — churn is part of the measured figure on purpose
        for t, data in enumerate(datas):
            tid = f"tenant{t}"
            if N_MIGRATIONS:
                side = state["side_of"].get(tid, 0)
                owner_reg = state["sides"][side][0]
            else:
                owner_reg = reg
            ctx = owner_reg.resolve(tid)
            try:
                result = ctx.engine.analyze(data)
            finally:
                # release the resolve lease: a pinned context is
                # eviction-proof, and this scenario MUST churn
                ctx.unpin()
            if N_MIGRATIONS and t < N_MIGRATIONS:
                # live-migrate the tenant to the other registry: a full
                # protocol pass (quiesce, export, stage + bank-hash
                # verify, cutover, frequency restore) inside the
                # measured window; the next pass migrates it back
                side = state["side_of"].get(tid, 0)
                dst = 1 - side
                src_mig = state["sides"][side][1]
                dst_mig = state["sides"][dst][1]
                src_mig.migrate(
                    tid, LocalTarget(dst_mig, url=f"local://side{dst}")
                )
                state["side_of"][tid] = dst
                state["migrations"] += 1
        return result

    result, _, dt = bench_common.measured_phase(bounded, sweep)
    assert result.summary.significant_events > 0
    stats = reg.stats()
    assert stats["evicted"] >= 1 and stats["rebuilds"] >= 1, (
        "residency scenario must churn: " + repr(stats)
    )
    total = per_tenant * N_TENANTS
    rate = total / dt

    bench_common.emit(
        metric,
        round(rate, 1),
        "lines/s",
        round(rate / NORTH_STAR_LINES_PER_SEC, 4),
        platform,
        n_lines=total,
        mode=MODE,
        n_tenants=N_TENANTS,
        bank_mb=round(state["bank_mb"], 3),
        budget_mb=round(stats["budgetMb"], 3),
        resident_tenants=stats["residentTenants"],
        resident_bank_mb=stats["residentBankMb"],
        resolved=stats["resolved"],
        created=stats["created"],
        evicted=stats["evicted"],
        rebuilds=stats["rebuilds"],
        n_events=result.summary.significant_events,
        **(
            {"migrations": state["migrations"],
             "migrations_per_pass": N_MIGRATIONS}
            if N_MIGRATIONS
            else {}
        ),
    )


def main() -> None:
    if N_TENANTS and (RESIDENCY or BUDGET_MB):
        tenant_residency_main()
        return
    if N_TENANTS:
        tenant_main()
        return
    metric = "dp_mesh_lines_per_sec"
    platform = f"{'cpu-virtual' if MODE == 'virtual' else 'real'}-mesh{N_DEVICES}"

    # in ``real`` mode device discovery and every analyze() go through a
    # possibly-wedged backend; the contract is a {"value": null}
    # diagnostics exit, never an unbounded hang. The label getter reads
    # the CURRENT platform: setup() refines it in real mode
    bounded = bench_common.bounded_runner(metric, "lines/s", lambda: platform)

    visible_devices = 0

    def setup():
        nonlocal platform, visible_devices
        import jax

        if MODE == "virtual":
            # the axon sitecustomize force-sets jax_platforms="axon,cpu"
            # at config level; honor the virtual-mesh request (same
            # re-pin as __graft_entry__.dryrun_multichip)
            jax.config.update("jax_platforms", "cpu")
        devices = jax.devices()
        visible_devices = len(devices)
        if MODE == "real":
            # label with what the devices actually ARE (the stale-flag
            # masquerade is already prevented by the flag strip above;
            # this makes the artifact self-describing either way)
            platform = f"{devices[0].platform}-mesh{N_DEVICES}"
        if len(devices) < N_DEVICES:
            bench_common.exit_null(
                metric,
                "lines/s",
                platform,
                f"need {N_DEVICES} devices, found {len(devices)} on "
                f"{devices[0].platform}",
            )

        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.parallel import ShardedEngine, make_mesh
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

        mesh = make_mesh(N_DEVICES)
        return ShardedEngine(
            load_builtin_pattern_sets(), ScoringConfig(), mesh=mesh
        )

    engine = bounded(setup, bench_common.PROBE_TIMEOUT_S, "device init")

    from log_parser_tpu.models.pod import PodFailureData

    data = PodFailureData(
        pod={"metadata": {"name": "bench-mesh"}}, logs=build_corpus(N_LINES)
    )

    # warmup (sharded-program compile) + best-of-n under the shared
    # sequence (bench_common.measured_phase)
    result, _, dt = bench_common.measured_phase(
        bounded, lambda: engine.analyze(data)
    )
    assert result.summary.significant_events > 0
    rate = N_LINES / dt

    extra: dict = {}
    if OVERHEAD:
        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
        from log_parser_tpu.runtime import AnalysisEngine

        def plain_setup():
            return AnalysisEngine(load_builtin_pattern_sets(), ScoringConfig())

        plain = bounded(plain_setup, bench_common.PROBE_TIMEOUT_S, "plain init")
        plain_result, _, plain_dt = bench_common.measured_phase(
            bounded, lambda: plain.analyze(data)
        )
        plain_rate = N_LINES / plain_dt
        extra = {
            "plain_lines_per_sec": round(plain_rate, 1),
            # two views, because they answer different questions:
            # - per_device: overhead the shard program adds per REAL
            #   device (meaningful on hardware meshes; at mesh=1 it is
            #   pure program structure with zero communication)
            # - total: sharded/plain at equal wall — the right bound on
            #   a TIME-SHARED virtual mesh, where N "devices" split one
            #   core and the per-device division means nothing
            "shard_overhead_per_device": round(
                1.0 - (rate / N_DEVICES) / plain_rate, 4
            ),
            "sharded_vs_plain_total": round(rate / plain_rate, 4),
        }
        if (
            plain_result.summary.significant_events
            != result.summary.significant_events
        ):
            # a parity divergence is the SUITE's job to fail on; the
            # bench's contract is one JSON line — record the
            # disagreement beside the already-measured rates instead of
            # crashing after both expensive phases completed
            extra["overhead_parity_mismatch"] = (
                f"sharded {result.summary.significant_events} != "
                f"plain {plain_result.summary.significant_events} events"
            )

    bench_common.emit(
        metric,
        round(rate, 1),
        "lines/s",
        round(rate / NORTH_STAR_LINES_PER_SEC, 4),
        platform,
        n_lines=N_LINES,
        n_devices=N_DEVICES,
        # OBSERVED count, not an echo of --devices: lets consumers (and
        # the smoke test) verify the topology request actually took
        visible_devices=visible_devices,
        mode=MODE,
        n_events=result.summary.significant_events,
        **extra,
    )


if __name__ == "__main__":
    main()
