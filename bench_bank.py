"""High-cardinality library benchmark: automaton compile + match throughput.

Implements BASELINE.md config 4 (10k YAML regexes; target "establish").
Generates a synthetic library of distinct failure-shaped regexes, then
reports DFA-bank compile time (cold and warm disk cache) and end-to-end
scored lines/sec with the pattern axis sharded over the visible devices.

Prints exactly one JSON line:
    {"metric": ..., "value": lines_per_sec, "unit": "lines/s",
     "vs_baseline": warm_compile_seconds}

Defaults are CPU-feasible (--patterns 2000 --lines 4096); on TPU run the
full `--patterns 10000`.
"""

from __future__ import annotations

import sys
import time

import bench_common  # noqa: F401  (sets LOG_PARSER_TPU_NO_FALLBACK=1 on import)

N_PATTERNS = int(sys.argv[sys.argv.index("--patterns") + 1]) if "--patterns" in sys.argv else 2000
N_LINES = int(sys.argv[sys.argv.index("--lines") + 1]) if "--lines" in sys.argv else 4096

_SERVICES = ["auth", "billing", "cart", "search", "ingest", "gateway", "scheduler", "worker"]
_ERRORS = ["Timeout", "Refused", "Unavailable", "Overflow", "Corrupt", "Denied", "Leak", "Panic"]


def synth_library(n: int):
    """n distinct patterns: literal-bearing regexes with varied structure."""
    from log_parser_tpu.models.pattern import (
        Pattern,
        PatternSet,
        PatternSetMetadata,
        PrimaryPattern,
        SecondaryPattern,
    )

    patterns = []
    for i in range(n):
        svc = _SERVICES[i % len(_SERVICES)]
        err = _ERRORS[(i // len(_SERVICES)) % len(_ERRORS)]
        body = f"{svc}-{i:05d}"
        shape = i % 4
        if shape == 0:
            regex = f"{body}: {err}"
        elif shape == 1:
            regex = f"{body}\\s+(fatal|{err.lower()})"
        elif shape == 2:
            regex = f"^\\d+ {body} {err}"
        else:
            regex = f"{body} (code|status)=[45]\\d\\d"
        patterns.append(
            Pattern(
                id=f"p{i:05d}",
                name=f"synthetic {i}",
                severity=["LOW", "MEDIUM", "HIGH", "CRITICAL"][i % 4],
                primary_pattern=PrimaryPattern(regex=regex, confidence=0.5 + (i % 5) / 10),
                secondary_patterns=(
                    [SecondaryPattern(regex=f"{svc} degraded", weight=0.4, proximity_window=10)]
                    if i % 7 == 0
                    else None
                ),
            )
        )
    return [
        PatternSet(
            metadata=PatternSetMetadata(library_id="synthetic-10k", name="synthetic"),
            patterns=patterns,
        )
    ]


def synth_logs(n_lines: int, n_patterns: int) -> str:
    rows = []
    for j in range(n_lines):
        if j % 19 == 4:  # ~5% of lines hit some pattern
            i = (j * 37) % n_patterns
            svc = _SERVICES[i % len(_SERVICES)]
            err = _ERRORS[(i // len(_SERVICES)) % len(_ERRORS)]
            rows.append(f"{svc}-{i:05d}: {err}")
        else:
            rows.append(f"2026-07-29T10:{j % 60:02d}:00Z INFO tick {j} ok")
    return "\n".join(rows)


def main() -> None:
    import os
    import shutil
    import tempfile

    metric = f"match_lines_per_sec_{N_PATTERNS}regex_library"
    platform = bench_common.probe_backend(metric, "lines/s")

    # every device touch must yield the {"value": null} diagnostics exit
    # on a wedged backend, never an unbounded hang
    bounded = bench_common.bounded_runner(metric, "lines/s", platform)

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.parallel.pattern_sharded import PatternShardedEngine

    sets = synth_library(N_PATTERNS)
    cache_dir = tempfile.mkdtemp(prefix="lpt-bankbench-")
    os.environ["LOG_PARSER_TPU_CACHE"] = cache_dir
    try:
        # bank compiles are host-side work, but the engine constructor
        # also touches the device layer — keep them bounded too
        t0 = time.perf_counter()
        engine = bounded(
            lambda: PatternShardedEngine(sets, ScoringConfig()),
            bench_common.PROBE_TIMEOUT_S,
            "cold compile",
        )
        cold_compile = time.perf_counter() - t0
        assert not engine.skipped_patterns, engine.skipped_patterns[:3]
        # deferred per-regex cache writes must not contend with the next
        # timed phase; their drain time is recorded separately (the
        # engine is already serving-ready when the cold timer stops)
        from log_parser_tpu.patterns.regex import cache as _dfa_cache

        t0 = time.perf_counter()
        # bounded like every other phase: a wedged filesystem must
        # degrade the artifact (drained=false), not hang the bench
        cache_flush_ok = _dfa_cache.flush(120.0)
        cache_flush = time.perf_counter() - t0

        t0 = time.perf_counter()
        engine = bounded(
            lambda: PatternShardedEngine(sets, ScoringConfig()),
            bench_common.PROBE_TIMEOUT_S,
            "warm compile",
        )
        warm_compile = time.perf_counter() - t0

        data = PodFailureData(
            pod={"metadata": {"name": "bank"}}, logs=synth_logs(N_LINES, N_PATTERNS)
        )
        # warmup (device-program compile) + best-of-n under the shared
        # sequence (bench_common.measured_phase)
        result, _, elapsed = bench_common.measured_phase(
            bounded, lambda: engine.analyze(data)
        )
        assert result.summary.significant_events > 0

        bench_common.emit(
            metric,
            round(N_LINES / elapsed, 1),
            "lines/s",
            round(warm_compile, 3),
            platform,
            cold_compile_s=round(cold_compile, 3),
            cache_flush_s=round(cache_flush, 3),
            cache_flush_drained=cache_flush_ok,
            n_lines=N_LINES,
        )
    finally:
        # drain pending pack writes BEFORE removing the dir: the atexit
        # flush runs after this finally and would otherwise recreate the
        # temp cache dir (leaking it) on an error exit mid-build
        from log_parser_tpu.patterns.regex import cache as _c

        _c.flush(30.0)
        shutil.rmtree(cache_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
