"""Scoring configuration — the 10 tunables of the reference service.

Mirrors the reference's MicroProfile Config surface
(src/main/resources/application.properties:1-20) with the same keys and the
same code-side defaults (ScoringService.java:38-51,
ContextAnalysisService.java:24-25, FrequencyTrackingService.java:27-34).
Every key is optional except ``pattern_directory``
(PatternService.java:35-36 has no default).

Severity multipliers and the per-line context weights are deliberately NOT
configurable — they are hardcoded constants in the reference
(ScoringService.java:30-36; ContextAnalysisService.java:62-88) and live as
module constants in :mod:`log_parser_tpu.golden.engine` /
:mod:`log_parser_tpu.runtime.finalize` so they are baked statically into the
jitted kernels.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Mapping

# application.properties key -> dataclass field name
_PROPERTY_KEYS = {
    "pattern.directory": "pattern_directory",
    "scoring.proximity.decay-constant": "proximity_decay_constant",
    "scoring.proximity.max-window": "proximity_max_window",
    "scoring.chronological.early-bonus-threshold": "chronological_early_bonus_threshold",
    "scoring.chronological.max-early-bonus": "chronological_max_early_bonus",
    "scoring.chronological.penalty-threshold": "chronological_penalty_threshold",
    "scoring.context.max-context-factor": "context_max_context_factor",
    "scoring.frequency.threshold": "frequency_threshold",
    "scoring.frequency.max-penalty": "frequency_max_penalty",
    "scoring.frequency.time-window-hours": "frequency_time_window_hours",
}


@dataclasses.dataclass(frozen=True)
class ScoringConfig:
    """All tunables, with the reference's defaults.

    Defaults cite the injection sites that carry them:

    - ``proximity_decay_constant``: ScoringService.java:38-39
    - ``proximity_max_window``: ScoringService.java:41-42
    - ``chronological_early_bonus_threshold``: ScoringService.java:44-45
    - ``chronological_max_early_bonus``: ScoringService.java:47-48
    - ``chronological_penalty_threshold``: ScoringService.java:50-51
    - ``context_max_context_factor``: ContextAnalysisService.java:24-25
    - ``frequency_threshold``: FrequencyTrackingService.java:27-28
    - ``frequency_max_penalty``: FrequencyTrackingService.java:30-31
    - ``frequency_time_window_hours``: FrequencyTrackingService.java:33-34
    """

    pattern_directory: str | None = None
    proximity_decay_constant: float = 10.0
    proximity_max_window: int = 100
    chronological_early_bonus_threshold: float = 0.2
    chronological_max_early_bonus: float = 2.5
    chronological_penalty_threshold: float = 0.5
    context_max_context_factor: float = 2.5
    frequency_threshold: float = 10.0
    frequency_max_penalty: float = 0.8
    frequency_time_window_hours: int = 1

    @classmethod
    def from_mapping(cls, props: Mapping[str, Any]) -> "ScoringConfig":
        """Build from a mapping keyed either by the reference's property names
        (``scoring.proximity.decay-constant``) or by field names."""
        kwargs: dict[str, Any] = {}
        fields = {f.name: f for f in dataclasses.fields(cls)}
        for key, value in props.items():
            name = _PROPERTY_KEYS.get(key, key)
            if name not in fields:
                continue
            typ = fields[name].type
            if value is not None:
                if typ == "int":
                    value = int(value)
                elif typ == "float":
                    value = float(value)
            kwargs[name] = value
        return cls(**kwargs)

    @classmethod
    def from_properties_file(cls, path: str) -> "ScoringConfig":
        """Parse a Java ``.properties`` file (the reference's config format)."""
        props: dict[str, str] = {}
        with open(path, "r", encoding="utf-8") as fh:
            for raw in fh:
                line = raw.strip()
                if not line or line.startswith(("#", "!")):
                    continue
                if "=" in line:
                    key, _, value = line.partition("=")
                    props[key.strip()] = value.strip()
        return cls.from_mapping(props)

    @classmethod
    def from_env(cls, env: Mapping[str, str] | None = None) -> "ScoringConfig":
        """Build from environment variables: each property key upper-cased with
        ``.``/``-`` → ``_`` (the MicroProfile Config env-var convention)."""
        env = os.environ if env is None else env
        props = {}
        for key in _PROPERTY_KEYS:
            env_key = key.upper().replace(".", "_").replace("-", "_")
            if env_key in env:
                props[key] = env[env_key]
        return cls.from_mapping(props)
