"""Runtime: the TPU analysis engine orchestrating encode → match → score →
assemble, plus cross-request frequency state."""

from log_parser_tpu.runtime.engine import AnalysisEngine

__all__ = ["AnalysisEngine"]
