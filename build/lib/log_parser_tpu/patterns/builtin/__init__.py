"""Built-in starter pattern libraries (YAML, reference schema)."""

import os

from log_parser_tpu.patterns.loader import load_pattern_directory

BUILTIN_DIR = os.path.dirname(__file__)


def load_builtin_pattern_sets():
    return load_pattern_directory(BUILTIN_DIR)
