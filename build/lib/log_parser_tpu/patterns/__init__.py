"""Pattern-library management: YAML loading and matcher compilation.

Replaces the reference's ``PatternService`` (PatternService.java:28-95) and —
by design, not accident — compiles every regex exactly once at load time
into immutable automaton banks, matching the documented intent
("compiled once at startup", docs/SCORING_ALGORITHM.md:186) rather than the
reference's actual per-request recompilation race
(AnalysisService.java:55-86; SURVEY.md §5.2).
"""

from log_parser_tpu.patterns.loader import load_pattern_directory, load_pattern_file

__all__ = ["load_pattern_directory", "load_pattern_file"]
