"""Regex → automaton compilation for TPU execution.

The reference matches Java regexes line-by-line with ``Matcher.find()``
(AnalysisService.java:93-95) — substring semantics. To run that on TPU we
compile each regex once at load time into a byte-level DFA
(parser → Thompson NFA with assertion edges → subset construction with
byte-class compression), pack pattern banks into int32 arrays XLA can gather
from, and extract *required literal factors* so a single combined
Aho-Corasick automaton can prefilter lines before exact verification —
the Hyperscan architecture, re-built TPU-first.

Correctness contract: the DFA is exact for ASCII lines (the automaton runs
over UTF-8 bytes; Java regexes run over UTF-16 chars, which agree on ASCII).
Lines containing non-ASCII bytes are flagged by the encoder and routed to
host verification, so end-to-end results stay exact.
"""

from log_parser_tpu.patterns.regex.parser import (
    RegexUnsupportedError,
    parse_java_regex,
)
from log_parser_tpu.patterns.regex.dfa import (
    DfaLimitError,
    CompiledDfa,
    compile_regex_to_dfa,
)
from log_parser_tpu.patterns.regex.literals import extract_literals
from log_parser_tpu.patterns.regex.ac import AhoCorasick

__all__ = [
    "AhoCorasick",
    "CompiledDfa",
    "DfaLimitError",
    "RegexUnsupportedError",
    "compile_regex_to_dfa",
    "extract_literals",
    "parse_java_regex",
]
