"""JAX kernels: line encoding, automaton execution, integer factor extraction.

No float64 — and no floating point at all — runs on the device: the match
path is pure int32/bool (DFA gathers over line bytes, prefix sums, record
compaction), and the seven-factor f64 arithmetic the ≤1e-6 parity target
requires happens on the host over the integer match records
(runtime/finalize.py), in the same IEEE doubles the JVM uses.
"""

from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.ops.fused import FusedMatchScore
from log_parser_tpu.ops.match import AcRunner, DfaBank

__all__ = ["AcRunner", "DfaBank", "FusedMatchScore", "encode_lines"]
