"""Mesh construction helpers."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the line-batch ("data") axis.

    The workload has exactly one natural parallel axis (independent lines);
    pattern-axis sharding for very large libraries composes later as a
    second mesh dimension.
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[: n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))
