"""Reference shim client — the executable documentation of the wire
protocol for the JVM implementer (protobuf-java + a Socket is all the
front-end needs)."""

from __future__ import annotations

import json
import socket

from log_parser_tpu.shim import logparser_pb2 as pb
from log_parser_tpu.shim.framing import read_frame, write_frame


class ShimClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 9090):
        self.sock = socket.create_connection((host, port))
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def close(self) -> None:
        self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def call(self, method: str, message) -> pb.Envelope:
        write_frame(
            self.sock,
            pb.Envelope(
                method=method, payload=message.SerializeToString()
            ).SerializeToString(),
        )
        frame = read_frame(self.sock)
        if frame is None:
            raise ConnectionError("shim server closed the connection")
        env = pb.Envelope()
        env.ParseFromString(frame)
        return env

    # ---------------------------------------------------------- convenience

    def parse(self, pod: dict | None, logs: str) -> pb.ParseResponse:
        env = self.call(
            "Parse",
            pb.ParseRequest(
                pod_json=json.dumps(pod) if pod is not None else "", logs=logs
            ),
        )
        if env.error:
            raise ValueError(env.error)
        resp = pb.ParseResponse()
        resp.ParseFromString(env.payload)
        return resp

    def health(self) -> str:
        env = self.call("Health", pb.HealthRequest())
        if env.error:
            raise ValueError(env.error)
        resp = pb.HealthResponse()
        resp.ParseFromString(env.payload)
        return resp.status
