"""Wire framing for the JVM↔TPU shim: gRPC's message frame on a bare socket.

Each direction carries a stream of frames, every frame being
``0x00 (uncompressed flag) + uint32 big-endian length + Envelope bytes`` —
exactly gRPC's length-prefixed message encoding minus the HTTP/2 layer
(grpcio is not a dependency of either side; the JVM front-end needs only
protobuf-java and a socket). One request frame yields exactly one response
frame; requests on one connection are served in order.
"""

from __future__ import annotations

import socket
import struct

_HDR = struct.Struct(">BI")
MAX_FRAME = 1 << 30  # 1 GiB: generous bound for a 1M-line corpus request


class FramingError(ConnectionError):
    pass


def read_exact(sock: socket.socket, n: int) -> bytes | None:
    """None on clean EOF at a frame boundary; raises mid-frame."""
    chunks = []
    got = 0
    while got < n:
        buf = sock.recv(min(n - got, 1 << 20))
        if not buf:
            if got == 0:
                return None
            raise FramingError(f"connection closed mid-frame ({got}/{n} bytes)")
        chunks.append(buf)
        got += len(buf)
    return b"".join(chunks)


def read_frame(sock: socket.socket) -> bytes | None:
    head = read_exact(sock, _HDR.size)
    if head is None:
        return None
    flag, length = _HDR.unpack(head)
    if flag != 0:
        raise FramingError(f"compressed frames unsupported (flag={flag})")
    if length > MAX_FRAME:
        raise FramingError(f"frame of {length} bytes exceeds MAX_FRAME")
    body = read_exact(sock, length)
    if body is None:
        raise FramingError("connection closed before frame body")
    return body


def write_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_HDR.pack(0, len(payload)) + payload)
