from log_parser_tpu.utils.trace import PhaseTrace, profiler_trace

__all__ = ["PhaseTrace", "profiler_trace"]
