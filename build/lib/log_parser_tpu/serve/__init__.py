"""HTTP serving: the reference's ``POST /parse`` contract plus operational
endpoints the reference lacked (health, frequency admin)."""

from log_parser_tpu.serve.http import ParseServer, make_server

__all__ = ["ParseServer", "make_server"]
