"""CLI entry: ``python -m log_parser_tpu.serve --pattern-dir /shared/patterns``.

Mirrors the reference's boot sequence: load the pattern directory at startup
(PatternService @PostConstruct, PatternService.java:45-69), then serve
``POST /parse`` on :8080 (Dockerfile.native:28). Config comes from a Java
``.properties`` file (``--config``), environment variables (MicroProfile
convention), or flags — flags win.
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import sys

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.patterns import load_pattern_directory
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.serve.http import make_server


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="log_parser_tpu.serve")
    parser.add_argument("--pattern-dir", help="pattern YAML directory (pattern.directory)")
    parser.add_argument("--config", help="Java .properties config file")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument(
        "--sharded",
        action="store_true",
        help="shard the line batch over every visible device (jax mesh)",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=args.log_level.upper(),
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s",
    )
    log = logging.getLogger("log_parser_tpu.serve")

    config = (
        ScoringConfig.from_properties_file(args.config)
        if args.config
        else ScoringConfig.from_env()
    )
    if args.pattern_dir:
        config = dataclasses.replace(config, pattern_directory=args.pattern_dir)
    if not config.pattern_directory:
        log.error("pattern.directory is required (--pattern-dir / config / env)")
        return 2

    pattern_sets = load_pattern_directory(config.pattern_directory)
    if args.sharded:
        from log_parser_tpu.parallel import ShardedEngine, make_mesh

        mesh = make_mesh()
        engine = ShardedEngine(pattern_sets, config, mesh=mesh)
        log.info("Sharding line batches over %d devices", mesh.devices.size)
    else:
        engine = AnalysisEngine(pattern_sets, config)
    if engine.skipped_patterns:
        for pid, reason in engine.skipped_patterns:
            log.warning("pattern %r disabled: %s", pid, reason)
    log.info(
        "Loaded %d pattern sets (%d patterns, %d matcher columns; %d on-device DFAs)",
        len(pattern_sets),
        engine.bank.n_patterns,
        engine.bank.n_columns,
        sum(1 for c in engine.bank.columns if c.dfa is not None),
    )

    server = make_server(engine, args.host, args.port)
    log.info("Serving POST /parse on %s:%d", args.host, args.port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        log.info("Shutting down")
    finally:
        server.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
