"""log_parser_tpu — a TPU-native pod-failure log analysis framework.

A ground-up JAX/XLA re-design of the capabilities of podmortem/log-parser
(reference: a Java 21 / Quarkus REST microservice, see /root/reference):
YAML-defined regex failure-pattern libraries, a seven-factor confidence
scoring formula, and a ``POST /parse`` REST contract — with the hot loop
(regex matching + scoring over every log line) executed as batched XLA ops
on TPU instead of a single JVM thread.

Architecture (TPU-first, not a translation):

- ``models/``    — the data-model surface of the reference's external
                   ``common-lib`` artifact, as plain dataclasses.
- ``config``     — the 10 scoring tunables (reference:
                   src/main/resources/application.properties:1-20).
- ``patterns/``  — YAML pattern-set loader + regex→DFA compiler +
                   literal-factor extraction + Aho-Corasick automaton bank.
- ``golden/``    — pure-Python exact reference implementation of the JVM
                   semantics; the parity anchor for every kernel.
- ``ops/``       — JAX kernels: batched automaton execution and the
                   vectorized scoring pipeline.
- ``parallel/``  — ``shard_map`` data parallelism over the line axis with
                   halo exchange and collective frequency reduction.
- ``runtime/``   — the analysis engine orchestrating encode→match→score→
                   assemble, plus cross-request frequency state.
- ``serve/``     — HTTP ``POST /parse`` endpoint with the reference's
                   request/response contract.
"""

__version__ = "0.1.0"

from log_parser_tpu.config import ScoringConfig

__all__ = ["ScoringConfig", "__version__"]
