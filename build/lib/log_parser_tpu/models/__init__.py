"""Data models — the surface of the reference's external ``common-lib`` jar.

The reference imports these POJOs from
``com.redhat.podmortem:common`` (reference pom.xml:55-59); the full surface
used by the parser is reconstructed from its call sites (see SURVEY.md §2.3).
Here they are plain dataclasses with JSON/YAML (de)serialization that accepts
both snake_case (the YAML pattern-file schema,
reference docs/SCORING_ALGORITHM.md:29-33) and camelCase (Jackson bean
convention for the REST payloads).
"""

from log_parser_tpu.models.analysis import (
    AnalysisMetadata,
    AnalysisResult,
    AnalysisSummary,
    EventContext,
    MatchedEvent,
    PatternFrequency,
)
from log_parser_tpu.models.pattern import (
    ContextExtraction,
    Pattern,
    PatternSet,
    PatternSetMetadata,
    PrimaryPattern,
    SecondaryPattern,
    SequenceEvent,
    SequencePattern,
)
from log_parser_tpu.models.pod import PodFailureData

__all__ = [
    "AnalysisMetadata",
    "AnalysisResult",
    "AnalysisSummary",
    "ContextExtraction",
    "EventContext",
    "MatchedEvent",
    "Pattern",
    "PatternFrequency",
    "PatternSet",
    "PatternSetMetadata",
    "PodFailureData",
    "PrimaryPattern",
    "SecondaryPattern",
    "SequenceEvent",
    "SequencePattern",
]
