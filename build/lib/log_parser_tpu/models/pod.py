"""``PodFailureData`` — the ``POST /parse`` request body.

Reference surface: ``getPod()`` (a Kubernetes Pod object whose
``metadata.name`` is logged, Parse.java:51), ``getLogs()`` (a single string
later split on ``\\r?\\n``, AnalysisService.java:53), and ``getEvents()``
(Kubernetes events, carried but unused by the parser — Parse.java:33-34
documents "pod specification, logs, and events").

The pod spec and events are opaque Kubernetes objects to the parser, so they
are carried as plain dicts/lists here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, ClassVar

from log_parser_tpu.models._base import Model


@dataclasses.dataclass
class PodFailureData(Model):
    _camel_output: ClassVar[bool] = True

    pod: dict[str, Any] | None = None
    logs: str = ""
    events: list[Any] | None = None

    @property
    def pod_name(self) -> str | None:
        """``data.getPod().getMetadata().getName()`` — Parse.java:51."""
        if not self.pod:
            return None
        return (self.pod.get("metadata") or {}).get("name")
