"""Shared (de)serialization helpers for the dataclass models.

The reference's data travels in two spellings: the YAML pattern files use
snake_case (``primary_pattern`` — reference docs/SCORING_ALGORITHM.md:29-33)
and the REST JSON uses Jackson's camelCase bean convention
(``lineNumber`` from ``MatchedEvent.setLineNumber``,
reference AnalysisService.java:101). Models here accept either spelling on
input and emit a chosen canonical spelling on output.
"""

from __future__ import annotations

import dataclasses
import re
import types
import typing
from typing import Any

_CAMEL_RE = re.compile(r"(?<!^)(?=[A-Z])")


def camel_to_snake(name: str) -> str:
    return _CAMEL_RE.sub("_", name).lower()


def snake_to_camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(part.title() for part in rest)


def _strip_optional(typ: Any) -> Any:
    origin = typing.get_origin(typ)
    if origin is typing.Union or origin is types.UnionType:
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return typ


class Model:
    """Mixin for dataclass models: dict/JSON round-tripping with key-spelling
    normalization and recursive nested-model construction."""

    # Subclasses set this to emit camelCase keys (REST JSON payloads).
    _camel_output: typing.ClassVar[bool] = False

    @classmethod
    def from_dict(cls, data: dict[str, Any] | None):
        if data is None:
            return None
        hints = typing.get_type_hints(cls)
        fields = {f.name: f for f in dataclasses.fields(cls)}  # type: ignore[arg-type]
        kwargs: dict[str, Any] = {}
        for key, value in data.items():
            name = camel_to_snake(key) if key not in fields else key
            if name not in fields:
                continue
            kwargs[name] = _coerce(_strip_optional(hints[name]), value)
        return cls(**kwargs)

    def to_dict(self, drop_none: bool = False) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for f in dataclasses.fields(self):  # type: ignore[arg-type]
            value = getattr(self, f.name)
            if value is None and drop_none:
                continue
            key = snake_to_camel(f.name) if self._camel_output else f.name
            out[key] = _unparse(value, drop_none)
        return out


def _coerce(typ: Any, value: Any) -> Any:
    if value is None:
        return None
    typ = _strip_optional(typ)
    origin = typing.get_origin(typ)
    if origin in (list, typing.List):
        (item_t,) = typing.get_args(typ)
        return [_coerce(item_t, v) for v in value]
    if origin in (dict, typing.Dict):
        return dict(value)
    if isinstance(typ, type) and issubclass(typ, Model):
        return typ.from_dict(value)
    if typ is float and isinstance(value, (int, float)):
        return float(value)
    if typ is int and isinstance(value, (int, float)):
        return int(value)
    return value


def _unparse(value: Any, drop_none: bool) -> Any:
    if isinstance(value, Model):
        return value.to_dict(drop_none=drop_none)
    if isinstance(value, list):
        return [_unparse(v, drop_none) for v in value]
    if isinstance(value, dict):
        return {k: _unparse(v, drop_none) for k, v in value.items()}
    return value
