"""Java floating-point edge-case semantics.

Java double arithmetic never throws: ``n/0.0`` is ±Infinity, ``0.0/0.0`` is
NaN, and ``Math.min`` propagates NaN. Python raises ``ZeroDivisionError`` and
``min`` silently prefers its first argument on NaN. The scoring pipeline
reaches these corners when tunables are set to 0 (e.g.
``frequency_time_window_hours=0`` makes ``getHourlyRate`` divide by zero,
FrequencyTrackingService.java:74), so parity requires Java's rules.
"""

from __future__ import annotations

import math


def java_div(a: float, b: float) -> float:
    """``a / b`` with Java double semantics (no exception on b == 0)."""
    try:
        return a / b
    except ZeroDivisionError:
        if a == 0.0 or math.isnan(a):
            return math.nan
        sign = math.copysign(1.0, a) * math.copysign(1.0, b)
        return math.inf if sign > 0 else -math.inf


def java_min(a: float, b: float) -> float:
    """``Math.min`` — NaN-propagating, unlike Python's ``min``."""
    if math.isnan(a) or math.isnan(b):
        return math.nan
    return min(a, b)
