"""Golden reference — pure-Python, exact replication of the JVM semantics.

The reference ships zero tests (SURVEY.md §4), so this package IS the
executable specification: a line-for-intent (not line-for-line) Python
implementation of the reference's analysis pipeline, including its quirks
(discovery-order events, read-before-record frequency state, the context
else-if, the unknown-severity ranking). Every TPU kernel is property-tested
against it at ≤1e-6 score delta.
"""

from log_parser_tpu.golden.engine import GoldenAnalyzer
from log_parser_tpu.golden.javacompat import compile_java_regex, java_split_lines

__all__ = ["GoldenAnalyzer", "compile_java_regex", "java_split_lines"]
