"""Shared harness for the bench scripts (bench.py, bench_bank.py,
bench_latency.py).

Round-1 postmortem (VERDICT.md r1): a hung device tunnel plus the engine's
golden host fallback turned a benchmark into a silent multi-minute
pure-Python crawl and an rc=124 timeout.  Round-2 postmortem (VERDICT.md
r2): the fail-fast fix over-corrected — one 100s probe window, no retry
for slow inits, and a ``null`` artifact when it expired.  A clean failure
is not a number.

This version treats backend init as a campaign, not a probe:

- the golden fallback stays disabled (a bench number silently served from
  the pure-Python host path would be nonsense);
- backend init runs in THROWAWAY subprocesses in staged attempts under a
  total wall budget (default 600s — well past one cold TPU runtime start),
  with the full stderr tail of every attempt kept;
- if the device backend never comes up, the bench DOES NOT exit null: it
  pins the JAX host (CPU) platform and records a clearly-labeled
  ``{"platform": "cpu"}`` floor, with the device-probe diagnostics
  embedded in the artifact.  Every artifact therefore carries a non-null
  value and enough detail to debug the device layer.

Importing this module sets ``LOG_PARSER_TPU_NO_FALLBACK=1``; import it
before constructing any engine.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

os.environ["LOG_PARSER_TPU_NO_FALLBACK"] = "1"

# Total wall budget for device-backend init attempts.  One real compile
# warmup takes 20-40s on TPU; a *cold runtime* start through the axon
# tunnel has been observed to exceed 100s, and the tunnel has also been
# observed to hang indefinitely — so: a large total budget, staged into
# attempts, then a labeled CPU floor instead of giving up.
PROBE_TIMEOUT_S = float(os.environ.get("LOG_PARSER_TPU_PROBE_TIMEOUT", "600"))

# Per-attempt ceilings.  Early attempts are short so a fast deterministic
# error gets retried quickly; later attempts grow so a slow-but-live init
# can finish.  The loop itself runs until the total deadline, not until
# the ceilings run out — the last ceiling repeats.
_ATTEMPT_CEILINGS_S = (90.0, 180.0, 300.0)
# Pause between fast deterministic failures so a restarting runtime gets
# time to come back instead of burning every attempt in the first seconds.
_RETRY_PAUSE_S = 20.0

_PROBE_SRC = """
import os, jax
# the axon plugin's sitecustomize pins jax_platforms="axon,cpu" at CONFIG
# level, overriding the JAX_PLATFORMS env var — re-pin when an explicit
# platform was requested (e.g. LOG_PARSER_TPU_PLATFORM=cpu for CPU runs).
# "tpu" is special: device plugins register under their own plugin name
# (the axon tunnel's devices live on platform "axon" yet report
# d[0].platform == "tpu"), so pinning jax_platforms="tpu" fails even
# with a live chip — auto-select instead and VERIFY the device platform.
p = os.environ.get("LOG_PARSER_TPU_PLATFORM")
if p and p != "tpu":
    jax.config.update("jax_platforms", p)
import jax.numpy as jnp
d = jax.devices()
if p == "tpu" and d[0].platform != p:
    # only the unpinned auto-select path verifies: a successfully PINNED
    # plugin platform (e.g. "axon") legitimately reports its devices
    # under a different name ("tpu")
    raise SystemExit(f"auto-select landed on {d[0].platform!r}, wanted {p!r}")
x = jnp.arange(64, dtype=jnp.int32)
(x + 1).block_until_ready()
print("PROBE_OK", d[0].platform, len(d), flush=True)
"""

#: Filled by probe_backend(); benches embed it in their artifact when the
#: device layer failed and they fell back to the CPU floor.
last_probe_diagnostics: list[dict] = []


def timeit(fn, n: int = 3, warmup: int = 1) -> float:
    """Best-of-n wall time after warmup — THE timing rule shared by every
    probe script (tools/probe_*.py), so methodology changes land in one
    place."""
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


def pin_platform(platform: str | None = None) -> None:
    """Pin the CURRENT process's JAX platform (the axon sitecustomize
    overrides the JAX_PLATFORMS env var at config level, so this must be
    a config-level update).

    ``tpu`` is never pinned directly: device plugins register under their
    own plugin name (the axon tunnel registers "axon" whose devices report
    ``platform == "tpu"``), so ``jax_platforms="tpu"`` would fail on a
    live tunneled chip.  The probe already verified auto-select lands on
    a TPU device; leave the default platform list in place.
    """
    p = platform or os.environ.get("LOG_PARSER_TPU_PLATFORM")
    if p:
        os.environ["LOG_PARSER_TPU_PLATFORM"] = p
        import jax

        if p != "tpu":
            jax.config.update("jax_platforms", p)
        else:
            # re-establish the probe's device check IN THIS PROCESS: with
            # auto-select still in effect a tunnel that died between the
            # probe and here would silently hand the bench a CPU backend
            # under a "tpu" artifact label (the r1 mislabel failure)
            actual = _device_platform()
            if actual != "tpu":
                raise RuntimeError(
                    f"bench process auto-selected {actual!r} after the "
                    "probe verified a TPU device; refusing to record a "
                    "mislabeled artifact"
                )


def _device_platform() -> str:
    """The ONE way in-process device identity is read for labeling —
    every mislabel guard (pin_platform's tpu branch, the floor check)
    goes through here so a methodology change can't drift between
    sites.  (_PROBE_SRC carries its own copy by necessity: it is a
    standalone subprocess source string.)"""
    import jax

    return jax.devices()[0].platform


class _PinWedged(RuntimeError):
    """In-process verification never returned: the backend is wedged and
    any later JAX use in this process (including a CPU floor) would hang
    behind the stuck xla_bridge init."""


def _pin_and_verify(platform: str, timeout_s: float) -> None:
    """Pin the CURRENT process to the probed platform and re-check its
    device layer, bounded by ``timeout_s``.

    The probe subprocess proves the backend *can* come up; this proves it
    is still up *here*, so a tunnel that died in between can never yield
    a CPU-speed number in a device-labeled artifact (the r1 mislabel
    failure).  The check runs in a daemon worker thread: a cleanly-dying
    backend raises RuntimeError; a *wedged* one trips the timeout and
    raises :class:`_PinWedged` so the caller can emit a diagnostics
    artifact and exit — a CPU-floor attempt would hang behind the stuck
    init, which is worse than an honest null.
    """
    outcome: list[BaseException | None] = []

    def check() -> None:
        try:
            pin_platform(platform)
            outcome.append(None)
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            outcome.append(exc)

    t = threading.Thread(target=check, name="pin-verify", daemon=True)
    t.start()
    t.join(timeout_s)
    if not outcome:
        raise _PinWedged(
            f"device layer wedged: in-process verification of {platform!r} "
            f"exceeded {timeout_s:.0f}s after a successful probe"
        )
    if outcome[0] is not None:
        raise RuntimeError(str(outcome[0]))


def _one_attempt(timeout_s: float) -> tuple[str | None, dict]:
    """Run the probe subprocess once.  Returns (platform or None, diag)."""
    t0 = time.monotonic()
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as e:
        return None, {
            "outcome": "timeout",
            "timeout_s": round(timeout_s, 1),
            "stderr_tail": ((e.stderr or b"").decode("utf-8", "replace") if isinstance(e.stderr, bytes) else (e.stderr or ""))[-2000:],
        }
    elapsed = time.monotonic() - t0
    if r.returncode == 0 and "PROBE_OK" in r.stdout:
        platform = r.stdout.split("PROBE_OK", 1)[1].split()[0]
        return platform, {"outcome": "ok", "platform": platform, "elapsed_s": round(elapsed, 1)}
    return None, {
        "outcome": "error",
        "rc": r.returncode,
        "elapsed_s": round(elapsed, 1),
        "stderr_tail": (r.stderr or r.stdout or "no output")[-2000:],
    }


def probe_backend(metric: str, unit: str) -> str:
    """Bring up a JAX backend for this bench, preferring the device.

    Staged subprocess attempts under PROBE_TIMEOUT_S total; on success the
    current process is pinned to that platform and its name is returned.
    If every device attempt fails, falls back to the JAX host (CPU)
    platform — pinned in-process so a hung device plugin is never touched
    — and returns ``"cpu"``.  Device-attempt diagnostics are left in
    ``last_probe_diagnostics`` for the bench to embed in its artifact.

    The bench never exits without a number: a CPU-floor run is a labeled
    regression-checkable datapoint, not a substitute for the device run
    (VERDICT.md r2 "Next round" item 1).
    """
    global last_probe_diagnostics
    last_probe_diagnostics = []

    explicit = os.environ.get("LOG_PARSER_TPU_PLATFORM")
    deadline = time.monotonic() + PROBE_TIMEOUT_S
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 1:
            break
        ceiling = _ATTEMPT_CEILINGS_S[min(attempt, len(_ATTEMPT_CEILINGS_S) - 1)]
        attempt += 1
        platform, diag = _one_attempt(min(ceiling, remaining))
        diag["attempt"] = attempt
        last_probe_diagnostics.append(diag)
        if platform is not None:
            try:
                # a successful probe earns a fair in-process dial window
                # even when staged probing consumed most of the budget:
                # a relay dial under bad tunnel weather has been observed
                # past 100s and is slow-but-live, not wedged
                _pin_and_verify(platform, max(120.0, deadline - time.monotonic()))
            except _PinWedged as exc:
                # no number can come out of this process any more (any
                # JAX use would hang behind the stuck init) — emit the
                # diagnostics artifact and stop, instead of the rc=124
                # silence a hung floor attempt would produce
                last_probe_diagnostics.append(
                    {"outcome": "pin-wedged", "attempt": attempt, "error": str(exc)}
                )
                print(f"# backend pin wedged: {exc}", file=sys.stderr)
                _exit_null(metric, unit, explicit or platform, str(exc))
            except RuntimeError as exc:
                # the device layer died (or wedged) between the probe
                # subprocess and this process. Retrying is FUTILE: this
                # process's jax has already initialized and cached its
                # backend, so every later probe-then-pin cycle would
                # re-read the same cached devices and fail — stop the
                # campaign now (floor or hard exit below) instead of
                # burning the remaining budget on doomed attempts.
                last_probe_diagnostics.append(
                    {"outcome": "pin-failed", "attempt": attempt, "error": str(exc)}
                )
                print(f"# backend pin failed: {exc}", file=sys.stderr)
                break
            print(f"# backend ok: {platform} (attempt {attempt})", file=sys.stderr)
            last_probe_diagnostics = []
            return platform
        print(f"# backend attempt {attempt} failed: {diag['outcome']}", file=sys.stderr)
        # a hang consumed its whole window; a fast deterministic error
        # waits out a pause first so a restarting runtime can recover —
        # either way the loop runs until the total budget is gone
        if diag["outcome"] != "timeout":
            time.sleep(min(_RETRY_PAUSE_S, max(0.0, deadline - time.monotonic())))

    if explicit:
        # an explicitly-requested platform that won't come up is a hard
        # failure — there is no meaningful floor to substitute
        _exit_null(metric, unit, explicit, f"requested platform {explicit!r} unavailable")

    print(
        "# device backend unavailable; falling back to labeled CPU floor",
        file=sys.stderr,
    )
    pin_platform("cpu")
    # on the pin-failed break path JAX is already initialized, so the
    # config update above is a no-op — trust the DEVICES, not the config,
    # before stamping "cpu" on the artifact (the inverse-mislabel guard)
    actual = _device_platform()
    if actual != "cpu":
        _exit_null(
            metric,
            unit,
            actual,
            f"floor fallback landed on already-initialized {actual!r} "
            "backend; refusing to record it under a 'cpu' label",
        )
    return "cpu"


def _exit_null(metric: str, unit: str, platform: str, error: str) -> None:
    """Emit the null-value diagnostics artifact and hard-exit: used when
    no honest number can be produced (explicit platform unavailable,
    wedged in-process backend, mislabel refusal)."""
    print(
        json.dumps(
            {
                "metric": metric,
                "value": None,
                "unit": unit,
                "vs_baseline": None,
                "platform": platform,
                "error": error,
                "device_probe": last_probe_diagnostics,
            }
        )
    )
    sys.exit(3)


def emit(metric: str, value: float, unit: str, vs_baseline: float | None,
         platform: str, **extra) -> None:
    """Print the single artifact JSON line, embedding the platform label
    and (when the device probe failed) the probe diagnostics."""
    doc = {
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
        "platform": platform,
    }
    doc.update(extra)
    if last_probe_diagnostics:
        doc["device_probe"] = last_probe_diagnostics
    print(json.dumps(doc))
