"""Shared harness for the bench scripts (bench.py, bench_bank.py,
bench_latency.py).

Round-1 postmortem (VERDICT.md r1): a hung device tunnel plus the engine's
golden host fallback turned a benchmark into a silent multi-minute
pure-Python crawl and an rc=124 timeout.  Round-2 postmortem (VERDICT.md
r2): the fail-fast fix over-corrected — one 100s probe window, no retry
for slow inits, and a ``null`` artifact when it expired.  A clean failure
is not a number.

This version treats backend init as a campaign, not a probe:

- the golden fallback stays disabled (a bench number silently served from
  the pure-Python host path would be nonsense);
- backend init runs in THROWAWAY subprocesses in staged attempts under a
  total wall budget (default 600s — well past one cold TPU runtime start),
  with the full stderr tail of every attempt kept;
- if the device backend never comes up, the bench DOES NOT exit null: it
  pins the JAX host (CPU) platform and records a clearly-labeled
  ``{"platform": "cpu"}`` floor, with the device-probe diagnostics
  embedded in the artifact.  Every artifact therefore carries a non-null
  value and enough detail to debug the device layer.

Importing this module sets ``LOG_PARSER_TPU_NO_FALLBACK=1``; import it
before constructing any engine.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

os.environ["LOG_PARSER_TPU_NO_FALLBACK"] = "1"

# Total wall budget for device-backend init attempts.  One real compile
# warmup takes 20-40s on TPU; a *cold runtime* start through the axon
# tunnel has been observed to exceed 100s, and the tunnel has also been
# observed to hang indefinitely — so: a large total budget, staged into
# attempts, then a labeled CPU floor instead of giving up.
PROBE_TIMEOUT_S = float(os.environ.get("LOG_PARSER_TPU_PROBE_TIMEOUT", "600"))

# Per-attempt ceilings.  Early attempts are short so a fast deterministic
# error gets retried quickly; later attempts grow so a slow-but-live init
# can finish.  The loop itself runs until the total deadline, not until
# the ceilings run out — the last ceiling repeats.
_ATTEMPT_CEILINGS_S = (90.0, 180.0, 300.0)
# Pause between fast deterministic failures so a restarting runtime gets
# time to come back instead of burning every attempt in the first seconds.
_RETRY_PAUSE_S = 20.0

_PROBE_SRC = """
import os, jax
# the axon plugin's sitecustomize pins jax_platforms="axon,cpu" at CONFIG
# level, overriding the JAX_PLATFORMS env var — re-pin when an explicit
# platform was requested (e.g. LOG_PARSER_TPU_PLATFORM=cpu for CPU runs)
p = os.environ.get("LOG_PARSER_TPU_PLATFORM")
if p:
    jax.config.update("jax_platforms", p)
import jax.numpy as jnp
d = jax.devices()
x = jnp.arange(64, dtype=jnp.int32)
(x + 1).block_until_ready()
print("PROBE_OK", d[0].platform, len(d), flush=True)
"""

#: Filled by probe_backend(); benches embed it in their artifact when the
#: device layer failed and they fell back to the CPU floor.
last_probe_diagnostics: list[dict] = []


def timeit(fn, n: int = 3, warmup: int = 1) -> float:
    """Best-of-n wall time after warmup — THE timing rule shared by every
    probe script (tools/probe_*.py), so methodology changes land in one
    place."""
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


def pin_platform(platform: str | None = None) -> None:
    """Pin the CURRENT process's JAX platform (the axon sitecustomize
    overrides the JAX_PLATFORMS env var at config level, so this must be
    a config-level update)."""
    p = platform or os.environ.get("LOG_PARSER_TPU_PLATFORM")
    if p:
        os.environ["LOG_PARSER_TPU_PLATFORM"] = p
        import jax

        jax.config.update("jax_platforms", p)


def _one_attempt(timeout_s: float) -> tuple[str | None, dict]:
    """Run the probe subprocess once.  Returns (platform or None, diag)."""
    t0 = time.monotonic()
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as e:
        return None, {
            "outcome": "timeout",
            "timeout_s": round(timeout_s, 1),
            "stderr_tail": ((e.stderr or b"").decode("utf-8", "replace") if isinstance(e.stderr, bytes) else (e.stderr or ""))[-2000:],
        }
    elapsed = time.monotonic() - t0
    if r.returncode == 0 and "PROBE_OK" in r.stdout:
        platform = r.stdout.split("PROBE_OK", 1)[1].split()[0]
        return platform, {"outcome": "ok", "platform": platform, "elapsed_s": round(elapsed, 1)}
    return None, {
        "outcome": "error",
        "rc": r.returncode,
        "elapsed_s": round(elapsed, 1),
        "stderr_tail": (r.stderr or r.stdout or "no output")[-2000:],
    }


def probe_backend(metric: str, unit: str) -> str:
    """Bring up a JAX backend for this bench, preferring the device.

    Staged subprocess attempts under PROBE_TIMEOUT_S total; on success the
    current process is pinned to that platform and its name is returned.
    If every device attempt fails, falls back to the JAX host (CPU)
    platform — pinned in-process so a hung device plugin is never touched
    — and returns ``"cpu"``.  Device-attempt diagnostics are left in
    ``last_probe_diagnostics`` for the bench to embed in its artifact.

    The bench never exits without a number: a CPU-floor run is a labeled
    regression-checkable datapoint, not a substitute for the device run
    (VERDICT.md r2 "Next round" item 1).
    """
    global last_probe_diagnostics
    last_probe_diagnostics = []

    explicit = os.environ.get("LOG_PARSER_TPU_PLATFORM")
    deadline = time.monotonic() + PROBE_TIMEOUT_S
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 1:
            break
        ceiling = _ATTEMPT_CEILINGS_S[min(attempt, len(_ATTEMPT_CEILINGS_S) - 1)]
        attempt += 1
        platform, diag = _one_attempt(min(ceiling, remaining))
        diag["attempt"] = attempt
        last_probe_diagnostics.append(diag)
        if platform is not None:
            print(f"# backend ok: {platform} (attempt {attempt})", file=sys.stderr)
            pin_platform()
            last_probe_diagnostics = []
            return platform
        print(f"# backend attempt {attempt} failed: {diag['outcome']}", file=sys.stderr)
        # a hang consumed its whole window; a fast deterministic error
        # waits out a pause first so a restarting runtime can recover —
        # either way the loop runs until the total budget is gone
        if diag["outcome"] != "timeout":
            time.sleep(min(_RETRY_PAUSE_S, max(0.0, deadline - time.monotonic())))

    if explicit:
        # an explicitly-requested platform that won't come up is a hard
        # failure — there is no meaningful floor to substitute
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": None,
                    "unit": unit,
                    "vs_baseline": None,
                    "platform": explicit,
                    "error": f"requested platform {explicit!r} unavailable",
                    "device_probe": last_probe_diagnostics,
                }
            )
        )
        sys.exit(3)

    print(
        f"# device backend unavailable after {PROBE_TIMEOUT_S:.0f}s; "
        "falling back to labeled CPU floor",
        file=sys.stderr,
    )
    pin_platform("cpu")
    return "cpu"


def emit(metric: str, value: float, unit: str, vs_baseline: float | None,
         platform: str, **extra) -> None:
    """Print the single artifact JSON line, embedding the platform label
    and (when the device probe failed) the probe diagnostics."""
    doc = {
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
        "platform": platform,
    }
    doc.update(extra)
    if last_probe_diagnostics:
        doc["device_probe"] = last_probe_diagnostics
    print(json.dumps(doc))
