"""Shared fail-fast harness for the bench scripts (bench.py, bench_bank.py,
bench_latency.py).

Round-1 postmortem (VERDICT.md): a hung device tunnel plus the engine's
golden host fallback turned a benchmark into a silent multi-minute
pure-Python crawl and an rc=124 timeout. Every bench therefore:

- disables the golden fallback (a bench number from the host path would be
  nonsense), and
- probes backend init in a THROWAWAY subprocess under one total wall
  budget before doing any real work, exiting non-zero with a diagnostic
  JSON line if the device layer is down.

Importing this module sets ``LOG_PARSER_TPU_NO_FALLBACK=1``; import it
before constructing any engine.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

os.environ["LOG_PARSER_TPU_NO_FALLBACK"] = "1"

# one real-compile warmup can take 20-40s on TPU; device *init* alone
# should take far less, but the axon tunnel has been observed to hang
# indefinitely — hence a hard TOTAL wall across all probe attempts
PROBE_TIMEOUT_S = float(os.environ.get("LOG_PARSER_TPU_PROBE_TIMEOUT", "100"))

_PROBE_SRC = """
import os, jax
# the axon plugin's sitecustomize pins jax_platforms="axon,cpu" at CONFIG
# level, overriding the JAX_PLATFORMS env var — re-pin when an explicit
# platform was requested (e.g. LOG_PARSER_TPU_PLATFORM=cpu for CPU runs)
p = os.environ.get("LOG_PARSER_TPU_PLATFORM")
if p:
    jax.config.update("jax_platforms", p)
import jax.numpy as jnp
d = jax.devices()
x = jnp.arange(64, dtype=jnp.int32)
(x + 1).block_until_ready()
print("PROBE_OK", d[0].platform, len(d), flush=True)
"""


def pin_platform() -> None:
    """Apply LOG_PARSER_TPU_PLATFORM to the CURRENT process (the axon
    sitecustomize overrides the JAX_PLATFORMS env var at config level)."""
    if os.environ.get("LOG_PARSER_TPU_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["LOG_PARSER_TPU_PLATFORM"])


def probe_backend_or_exit(metric: str, unit: str) -> str:
    """Initialize the configured JAX backend in a throwaway subprocess under
    one total wall budget (PROBE_TIMEOUT_S); returns the platform name, or
    prints a diagnostic JSON line in the bench's schema and exits 3. Fast
    deterministic init errors get one retry (the axon backend has been seen
    to error once then recover); a hang consumes the whole budget exactly
    once — no retry can help it."""
    deadline = time.monotonic() + PROBE_TIMEOUT_S
    last = ""
    for attempt in (1, 2):
        remaining = deadline - time.monotonic()
        if remaining <= 1:
            break
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True,
                text=True,
                timeout=remaining,
            )
        except subprocess.TimeoutExpired:
            last = (
                f"backend init exceeded probe budget "
                f"({PROBE_TIMEOUT_S:.0f}s total, attempt {attempt})"
            )
            break
        if r.returncode == 0 and "PROBE_OK" in r.stdout:
            platform = r.stdout.split("PROBE_OK", 1)[1].split()[0]
            print(f"# backend ok: {platform}", file=sys.stderr)
            pin_platform()
            return platform
        tail = (r.stderr or r.stdout).strip().splitlines()[-1:] or ["no output"]
        last = f"probe rc={r.returncode}: {tail[0][:300]} (attempt {attempt})"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": None,
                "unit": unit,
                "vs_baseline": None,
                "error": f"device backend unavailable: {last}",
            }
        )
    )
    sys.exit(3)
