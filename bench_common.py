"""Shared harness for the bench scripts (bench.py, bench_bank.py,
bench_latency.py).

Round-1 postmortem (VERDICT.md r1): a hung device tunnel plus the engine's
golden host fallback turned a benchmark into a silent multi-minute
pure-Python crawl and an rc=124 timeout.  Round-2 postmortem (VERDICT.md
r2): the fail-fast fix over-corrected — one 100s probe window, no retry
for slow inits, and a ``null`` artifact when it expired.  A clean failure
is not a number.

This version treats backend init as a campaign, not a probe:

- the golden fallback stays disabled (a bench number silently served from
  the pure-Python host path would be nonsense);
- backend init runs in THROWAWAY subprocesses in staged attempts under a
  total wall budget (default 600s — well past one cold TPU runtime start),
  with the full stderr tail of every attempt kept;
- if the device backend never comes up, the bench pins the JAX host
  (CPU) platform and records a clearly-labeled ``{"platform": "cpu"}``
  floor, with the device-probe diagnostics embedded in the artifact;
- when no HONEST number exists at all — an explicitly-requested platform
  is unavailable, the backend wedges inside this process after a
  successful probe, or the only label available would be a lie — the
  bench emits a ``{"value": null}`` diagnostics line and exits 3
  (:func:`exit_null`) rather than hanging or mislabeling.  Consumers
  must check the exit code (tools/refresh_artifacts.sh keeps the
  previous artifact on rc != 0).

Round-4 postmortem (VERDICT.md r4, PERF.md §10): probe subprocesses used
``subprocess.run(timeout=...)``, which KILLS the child on expiry — and a
probe killed mid-remote-compile leaves queued compiles that wedge the
single-session axon relay for the rest of the session (it is spawned by
external infrastructure and cannot be restarted from inside).  Two such
kills turned the round-4 headline into a CPU fallback.  The rule is now
code, not prose:

- probe subprocesses are spawned DETACHED (own session, own output
  files) and are NEVER signaled.  An attempt "timeout" abandons the
  still-running probe and the next attempt resumes polling the SAME
  process (one probe at a time, however slow), so a mid-compile probe
  can neither be killed nor doubled up on the relay;
- every device-labeled artifact carries ``relay_health`` — the measured
  tiny-dispatch RTT through the tunnel — so a reader can tell engine
  regressions from tunnel weather (session-quality spreads of 161k-596k
  lines/s on identical code are documented in PERF.md §8b).

Importing this module sets ``LOG_PARSER_TPU_NO_FALLBACK=1``; import it
before constructing any engine.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

os.environ["LOG_PARSER_TPU_NO_FALLBACK"] = "1"

# Total wall budget for device-backend init attempts.  One real compile
# warmup takes 20-40s on TPU; a *cold runtime* start through the axon
# tunnel has been observed to exceed 100s, and the tunnel has also been
# observed to hang indefinitely — so: a large total budget, staged into
# attempts, then a labeled CPU floor instead of giving up.
PROBE_TIMEOUT_S = float(os.environ.get("LOG_PARSER_TPU_PROBE_TIMEOUT", "600"))

# Per-attempt ceilings.  Early attempts are short so a fast deterministic
# error gets retried quickly; later attempts grow so a slow-but-live init
# can finish.  The loop itself runs until the total deadline, not until
# the ceilings run out — the last ceiling repeats.
_ATTEMPT_CEILINGS_S = (90.0, 180.0, 300.0)
# Pause between fast deterministic failures so a restarting runtime gets
# time to come back instead of burning every attempt in the first seconds.
_RETRY_PAUSE_S = 20.0

_PROBE_SRC = """
import os, jax
# the axon plugin's sitecustomize pins jax_platforms="axon,cpu" at CONFIG
# level, overriding the JAX_PLATFORMS env var — re-pin when an explicit
# platform was requested (e.g. LOG_PARSER_TPU_PLATFORM=cpu for CPU runs).
# "tpu" is special: device plugins register under their own plugin name
# (the axon tunnel's devices live on platform "axon" yet report
# d[0].platform == "tpu"), so pinning jax_platforms="tpu" fails even
# with a live chip — auto-select instead and VERIFY the device platform.
p = os.environ.get("LOG_PARSER_TPU_PLATFORM")
if p and p != "tpu":
    jax.config.update("jax_platforms", p)
import jax.numpy as jnp
d = jax.devices()
if p == "tpu" and d[0].platform != p:
    # only the unpinned auto-select path verifies: a successfully PINNED
    # plugin platform (e.g. "axon") legitimately reports its devices
    # under a different name ("tpu")
    raise SystemExit(f"auto-select landed on {d[0].platform!r}, wanted {p!r}")
x = jnp.arange(64, dtype=jnp.int32)
(x + 1).block_until_ready()
print("PROBE_OK", d[0].platform, len(d), flush=True)
"""

#: Filled by probe_backend(); benches embed it in their artifact when the
#: device layer failed and they fell back to the CPU floor.
last_probe_diagnostics: list[dict] = []

#: Tiny-dispatch RTT through the device tunnel, measured right after a
#: successful device pin; stamped into every device artifact as
#: ``relay_health`` so a reader can tell engine regressions from tunnel
#: weather (VERDICT r4 weak #3).  None on CPU runs.
last_relay_health: dict | None = None

#: True iff the last probe_backend() call fell back to the labeled CPU
#: floor after a FAILED device campaign (probe attempts errored/timed
#: out until the budget ran out, or the in-process pin failed). False
#: whenever the probe succeeded — including on a deviceless host whose
#: auto-select probe lands on cpu instantly: no probe budget was burned
#: there, which is exactly what policy consumers (bench.py's short
#: fallback dwell) need to know. Do not infer fallback from
#: last_probe_diagnostics truthiness (it is empty on the zero-attempt
#: edge where the probe budget expires before the first attempt).
last_fell_back: bool = False

#: The ACTUAL device platform of this process after probe_backend()
#: returned, read straight from the live device layer (never inferred
#: from the requested label) — emit() stamps it as ``backend`` beside
#: the bench's ``platform`` label so an artifact reader can always tell
#: the two apart (BENCH_r04-r07 carried only the label, and the silent
#: CPU landings had to be reconstructed from probe diagnostics).
last_backend: str | None = None

#: True iff the last probe_backend() call was served from the probe
#: cache (the staged subprocess campaign was skipped); stamped beside
#: ``backend`` for provenance.
last_probe_cached: bool = False

#: On-disk cache of the last SUCCESSFUL probe outcome, so a series of
#: bench invocations (an A/B recording, refresh_artifacts.sh) dials the
#: staged subprocess campaign once instead of per-bench. Bounded two
#: ways: a TTL (below), and the rule that a cache hit still runs the
#: full in-process _pin_and_verify — the cache can only skip the
#: subprocess attempts, never the mislabel guard, so a tunnel that died
#: since the cached probe invalidates the entry instead of mislabeling.
#: Failures are never cached. Per-user for the same reason as the probe
#: handoff record.
_PROBE_CACHE_PATH = os.path.join(
    tempfile.gettempdir(),
    f"log_parser_tpu_probe_cache_{os.getuid()}.json",
)
PROBE_CACHE_TTL_S = float(
    os.environ.get("LOG_PARSER_TPU_PROBE_CACHE_TTL", "600")
)


def timeit(fn, n: int = 3, warmup: int = 1) -> float:
    """Best-of-n wall time after warmup — THE timing rule shared by every
    probe script (tools/probe_*.py), so methodology changes land in one
    place."""
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


# ------------------------------------------------------- repeat-heavy mode
# Shared by bench.py and bench_latency.py (``--repeat-ratio``): a
# synthetic repeat-heavy stream for exercising the exact-match line cache
# (runtime/linecache.py). Template lines are drawn zipf (weight 1/rank)
# from a small pool — the shape of real fleet logs, where a handful of
# templates dominate — and the remaining lines carry a unique tag so they
# can never hit the cache.

# Benign templates dominate the head ranks and the matching templates sit
# at the tail — real fleet logs are overwhelmingly routine (the zipf head
# is heartbeats and reconcile ticks), and a pool where every template
# produced an event would let result-assembly cost (identical cache-on
# and cache-off) drown the cube savings the mode exists to measure.
REPEAT_TEMPLATES = (
    "2026-07-29T07:00:00Z INFO reconcile tick status=ok",
    "INFO steady-state heartbeat marker",
    'GET /healthz 200 17b "kube-probe/1.29"',
    "INFO syncing deployment default/web replicas=3",
    "INFO volume mount ok pvc-data-0",
    "INFO leader-election renewed lease",
    "INFO configmap checksum unchanged",
    "INFO endpoint slice updated 10.0.3.17:8080",
    "INFO image already present on machine",
    "INFO scheduled pod web-7f9c onto node-4",
    "INFO readiness gate passed",
    "INFO garbage collector scanned 312 objects",
    "INFO certificate rotation not due",
    "ERROR request failed with IllegalStateException",
    "dial tcp 10.0.0.7:5432: Connection refused",
    "java.lang.OutOfMemoryError: Java heap space",
)

_ZIPF_CUM: list[float] = []
for _rank in range(len(REPEAT_TEMPLATES)):
    _ZIPF_CUM.append((_ZIPF_CUM[-1] if _ZIPF_CUM else 0.0) + 1.0 / (_rank + 1))


def zipf_template(u: float) -> str:
    """Map uniform ``u`` in [0, 1) to a template with P(rank) ∝ 1/(rank+1)."""
    x = u * _ZIPF_CUM[-1]
    for rank, cum in enumerate(_ZIPF_CUM):
        if x < cum:
            return REPEAT_TEMPLATES[rank]
    return REPEAT_TEMPLATES[-1]


def hash01(x: int) -> float:
    """Deterministic uniform [0, 1) from an integer — lets a corpus
    builder stay a pure function of its indices (the latency sweep's
    prewarm regenerates content by index and must see identical lines)."""
    x = (x * 2654435761) & 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 2246822519) & 0xFFFFFFFF
    x ^= x >> 13
    return x / 4294967296.0


# ``--novel-ratio`` (bench.py): unseen generated-template lines for the
# template miner (log_parser_tpu/mining/). Each is a fixed token skeleton
# with numeric wildcard slots — exactly the shape the clusterer groups —
# and none appears in REPEAT_TEMPLATES or matches a builtin pattern, so
# every draw is a guaranteed line-cache miss feeding the miner tap.
NOVEL_TEMPLATES = (
    "replication backlog drained on shard {a} after {b} entries",
    "checkpoint upload finished for epoch {a} in {b} ms",
    "frobnicator subsystem rebalanced queue {a} depth {b}",
    "thermal governor stepped clock domain {a} to {b} mhz",
)


def novel_line(u: float, i: int) -> str:
    """Map uniform ``u`` and a line index to a generated-template line:
    the skeleton repeats, the slot values never do."""
    tmpl = NOVEL_TEMPLATES[int(u * len(NOVEL_TEMPLATES)) % len(NOVEL_TEMPLATES)]
    return tmpl.format(a=i % 8191, b=(i * 37) % 9973)


def repeat_corpus(
    n: int, ratio: float, tag: str, rng, novel_ratio: float = 0.0
) -> str:
    """``n`` lines, ~``ratio`` of them zipf template draws, the rest
    unique filler stamped with ``tag``. Every ~997th filler still carries
    a matching ERROR so the stream produces events at any ratio.

    ``novel_ratio`` carves that fraction of lines into unseen
    generated-template draws (:data:`NOVEL_TEMPLATES`) for miner benches;
    the default 0.0 takes no extra RNG draws, so miner-off corpora are
    bit-identical to pre-knob ones."""
    rows = []
    for i in range(n):
        if novel_ratio and rng.random() < novel_ratio:
            rows.append(novel_line(rng.random(), i))
        elif rng.random() < ratio:
            rows.append(zipf_template(rng.random()))
        elif i % 997 == 701:
            rows.append(
                f"ERROR request failed with IllegalStateException uniq={tag}.{i}"
            )
        else:
            rows.append(f"INFO unique filler {tag}.{i} status=ok")
    return "\n".join(rows)


def pin_platform(platform: str | None = None) -> None:
    """Pin the CURRENT process's JAX platform (the axon sitecustomize
    overrides the JAX_PLATFORMS env var at config level, so this must be
    a config-level update).

    ``tpu`` is never pinned directly: device plugins register under their
    own plugin name (the axon tunnel registers "axon" whose devices report
    ``platform == "tpu"``), so ``jax_platforms="tpu"`` would fail on a
    live tunneled chip.  The probe already verified auto-select lands on
    a TPU device; leave the default platform list in place.
    """
    p = platform or os.environ.get("LOG_PARSER_TPU_PLATFORM")
    if p:
        os.environ["LOG_PARSER_TPU_PLATFORM"] = p
        import jax

        if p != "tpu":
            jax.config.update("jax_platforms", p)
            # force backend init NOW: the caller's wedge timeout
            # (_pin_and_verify) must guard the real device dial, not a
            # lazy config update that defers the hang to engine warmup.
            # No name assertion — plugin platforms ("axon") legitimately
            # report their devices under a different name ("tpu").
            _device_platform()
        else:
            # re-establish the probe's device check IN THIS PROCESS: with
            # auto-select still in effect a tunnel that died between the
            # probe and here would silently hand the bench a CPU backend
            # under a "tpu" artifact label (the r1 mislabel failure)
            actual = _device_platform()
            if actual != "tpu":
                raise RuntimeError(
                    f"bench process auto-selected {actual!r} after the "
                    "probe verified a TPU device; refusing to record a "
                    "mislabeled artifact"
                )


# Bounded-drain floor for a campaign level (seconds): in-flight requests
# normally finish within ~p99 after the dwell, but a WEDGED backend never
# returns — an unbounded join would hang the bench with no artifact at
# all. 240 s, not 60: a weak-but-LIVE relay session has been observed to
# finish a C=8 request 96 s after its dwell ended, and the pin path
# already grants slow-but-live dials >= 120 s — the floor must sit well
# above both so "wedged" in an artifact means wedged, not slow.
# Module-level so tests can shrink it.
DRAIN_FLOOR_S = 240.0

# Level order: a strong candidate (C=2 — the weak-session saturation
# point; healthy sessions peak at C=4) runs FIRST so a good number is
# banked before any heavier multi-stream stress touches the
# single-session tunnel (a C=8 dwell has been observed to run 128 s of
# wall with a 96 s p99 — the relay, not the chip, is the C>4 wall). The
# payoff is the degrade path: a level that fails degrades the artifact
# to the already-banked levels, and with C=2 first the banked set is
# worth keeping.
CAMPAIGN_LEVELS = (2, 1, 4, 8)


def wedge_failure(prefix: str, errors: list) -> str:
    """One shared format for a wedged fan-out's failure text: a sibling
    worker's error is the likely root cause, so it rides along (repr
    truncated to 300 chars — backend errors carry multi-KB tracebacks
    and artifacts are one JSON line)."""
    if errors:
        prefix += f"; first worker error: {repr(errors[0])[:300]}"
    return prefix


def join_bounded(threads, budget_s: float) -> bool:
    """Join daemon ``threads`` under one shared wall budget; True iff any
    is still alive afterwards (a wedged backend — callers degrade or
    exit_null instead of hanging).  THE wedge-detection rule shared by
    every bench fan-out, so drain-policy changes land in one place.
    Threads must be daemons: a wedged one is abandoned, not waited out.
    """
    deadline = time.monotonic() + budget_s
    for th in threads:
        th.join(max(0.0, deadline - time.monotonic()))
    return any(th.is_alive() for th in threads)


def run_bounded(workers: list, budget_s: float, metric: str, unit: str,
                platform: str, what: str) -> list:
    """Run ``workers`` (zero-arg callables) in daemon threads under one
    bounded join; returns their results in order.  A wedge (any worker
    still alive after the budget) emits the null diagnostics artifact —
    with the first sibling error as the likely root cause — and exits 3;
    a worker error (all workers finished) re-raises.  The ONE wrapper
    every bench fan-out goes through, so the wedge policy (message
    format, exit_null-on-wedge, error propagation) cannot drift between
    benches."""
    results: list = [None] * len(workers)
    errors: list[BaseException] = []

    def wrap(i: int, fn):
        def inner() -> None:
            try:
                results[i] = fn()
            except BaseException as exc:  # noqa: BLE001 - re-raised below
                errors.append(exc)

        return inner

    threads = [
        threading.Thread(target=wrap(i, fn), daemon=True)
        for i, fn in enumerate(workers)
    ]
    for th in threads:
        th.start()
    if join_bounded(threads, budget_s):
        exit_null(
            metric, unit, platform,
            wedge_failure(
                f"wedged: no progress after {budget_s:.0f}s ({what})", errors
            ),
        )
    if errors:
        raise errors[0]
    return results


def run_bounded_one(fn, budget_s: float, metric: str, unit: str,
                    platform: str, what: str):
    """Single-worker :func:`run_bounded` — the common shape for serial
    bench phases (device init, warmup, the timed measure)."""
    return run_bounded([fn], budget_s, metric, unit, platform, what)[0]


def bounded_runner(metric: str, unit: str, platform):
    """Bind a bench's artifact identity once and get its per-phase wedge
    wrapper ``bounded(fn, budget_s, what)`` — so every serial bench
    carries the identical wrapper instead of a local re-binding copy.

    ``platform`` may be the label string or a zero-arg getter: a bench
    that refines its label mid-run (bench_mesh's real mode reports the
    actual device platform discovered during init) passes
    ``lambda: platform`` so every phase reads the CURRENT label — a
    frozen stale label on a wedge artifact would be a mislabel."""

    def bounded(fn, budget_s: float, what: str):
        p = platform() if callable(platform) else platform
        return run_bounded_one(fn, budget_s, metric, unit, p, what)

    return bounded


#: Run count of every timed measure phase (bench_common.timeit n=...);
#: one constant so measure_budget and the timeit call sites cannot drift.
MEASURE_RUNS = 3


def measure_budget(warmup_dt: float, n: int = MEASURE_RUNS) -> float:
    """Wedge budget for an n-run timed measure phase, derived from the
    OBSERVED warmup duration: warmup includes compilation, so 5x it
    over-covers a steady-state run — a slower host or a bigger workload
    scales the budget instead of tripping a false wedge.  One formula so
    benches cannot drift."""
    return n * max(60.0, 5.0 * warmup_dt)


def measured_phase(bounded, fn, n: int = MEASURE_RUNS):
    """THE serial measurement sequence shared by every bench: one warmup
    call of ``fn`` under the cold-start budget (compiles + caches), then
    best-of-``n`` timing under the warmup-derived wedge budget.  Returns
    ``(warmup_result, warmup_dt, best_seconds)``.  ``bounded`` is the
    bench's :func:`bounded_runner` wrapper."""
    w0 = time.perf_counter()
    result = bounded(fn, PROBE_TIMEOUT_S, "warmup")
    warmup_dt = time.perf_counter() - w0
    best = bounded(
        lambda: timeit(fn, n=n, warmup=0),
        measure_budget(warmup_dt, n),
        "measure",
    )
    return result, warmup_dt, best


def run_campaign(
    analyze_once,
    n_lines: int,
    campaign_s: float,
    levels: tuple[int, ...] = CAMPAIGN_LEVELS,
    request_floor_s: float = 0.0,
) -> tuple[list[dict], str | None]:
    """Hold each concurrency level at steady state for ``campaign_s`` of
    wall clock, calling ``analyze_once`` from ``concurrency`` client
    threads (VERDICT r3 weak #5: a burst under a best-of selector is too
    thin a basis for a headline). Engine-agnostic via the callback — THE
    steady-state measurement methodology, shared like :func:`timeit`.

    Returns ``(curve, campaign_error)``: the curve sorted by concurrency,
    one dict per level — measured levels carry requests/wall_s/
    lines_per_sec/percentiles, a failed level carries ``"error"`` and
    ends the campaign (a dead backend fails every later level anyway,
    slowly). ``campaign_error`` is None iff every level completed. A
    level whose in-flight requests never return (wedged backend) is
    detected by a bounded drain and recorded like an error — the old
    raise-on-first-error destroyed the whole artifact instead.
    """
    curve_points: dict[int, dict] = {}
    campaign_error = None
    for concurrency in levels:
        stop = threading.Event()
        errors: list[BaseException] = []
        lat: list[float] = []
        lock = threading.Lock()

        def client() -> None:
            try:
                while not stop.is_set():
                    r0 = time.perf_counter()
                    analyze_once()
                    rd = time.perf_counter() - r0
                    with lock:
                        lat.append(rd)
            except BaseException as exc:
                errors.append(exc)
                stop.set()

        # daemon threads: a request wedged inside a dying backend must
        # not block process exit after the bounded drain below gives up
        threads = [
            threading.Thread(target=client, daemon=True)
            for _ in range(concurrency)
        ]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        stop.wait(campaign_s)  # a failing client ends the dwell early
        stop.set()
        # the drain must scale with REQUEST size, not just the dwell: a
        # 1M-line request is ~5x a 200k one and a C=8 queue multiplies
        # further. ``request_floor_s`` is the caller's measured serial
        # request time (x10 covers a full C=8 queue depth); the max
        # latency observed IN this level adapts to live conditions the
        # caller couldn't have measured (e.g. a degraded relay)
        with lock:
            observed = max(lat, default=0.0)
        drain_s = max(
            DRAIN_FLOOR_S,
            4.0 * campaign_s,
            10.0 * request_floor_s,
            5.0 * observed,
        )
        wedged = join_bounded(threads, drain_s)
        dt = time.perf_counter() - t0
        failure = None
        if wedged:
            failure = wedge_failure(
                f"wedged: requests still in flight after {drain_s:.0f}s drain",
                errors,
            )
        elif errors:
            # 300-char truncation: backend errors carry multi-KB
            # tracebacks and the artifact is one JSON line
            failure = repr(errors[0])[:300]
        if failure is not None:
            campaign_error = f"concurrency {concurrency}: {failure}"
            curve_points[concurrency] = {"concurrency": concurrency, "error": failure}
            break
        lat.sort()
        n = len(lat)
        curve_points[concurrency] = {
            "concurrency": concurrency,
            "requests": n,
            "wall_s": round(dt, 2),
            "lines_per_sec": round(n * n_lines / dt, 1),
            # nearest-rank percentiles: rank ceil(q*n), 1-based
            "p50_ms": round(1e3 * lat[max(0, -(-50 * n // 100) - 1)], 1)
            if n
            else None,
            "p99_ms": round(1e3 * lat[max(0, -(-99 * n // 100) - 1)], 1)
            if n
            else None,
        }
    return [curve_points[c] for c in sorted(curve_points)], campaign_error


def _device_platform() -> str:
    """The ONE way in-process device identity is read for labeling —
    every mislabel guard (pin_platform's tpu branch, the floor check)
    goes through here so a methodology change can't drift between
    sites.  (_PROBE_SRC carries its own copy by necessity: it is a
    standalone subprocess source string.)"""
    import jax

    return jax.devices()[0].platform


class _PinWedged(RuntimeError):
    """In-process verification never returned: the backend is wedged and
    any later JAX use in this process (including a CPU floor) would hang
    behind the stuck xla_bridge init."""


def _pin_and_verify(platform: str, timeout_s: float) -> None:
    """Pin the CURRENT process to the probed platform and re-check its
    device layer, bounded by ``timeout_s``.

    The probe subprocess proves the backend *can* come up; this proves it
    is still up *here*, so a tunnel that died in between can never yield
    a CPU-speed number in a device-labeled artifact (the r1 mislabel
    failure).  The check runs in a daemon worker thread: a cleanly-dying
    backend raises RuntimeError; a *wedged* one trips the timeout and
    raises :class:`_PinWedged` so the caller can emit a diagnostics
    artifact and exit — a CPU-floor attempt would hang behind the stuck
    init, which is worse than an honest null.
    """
    outcome: list[BaseException | None] = []

    def check() -> None:
        try:
            pin_platform(platform)
            outcome.append(None)
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            outcome.append(exc)

    t = threading.Thread(target=check, name="pin-verify", daemon=True)
    t.start()
    t.join(timeout_s)
    if not outcome:
        raise _PinWedged(
            f"device layer wedged: in-process verification of {platform!r} "
            f"exceeded {timeout_s:.0f}s after a successful probe"
        )
    if outcome[0] is not None:
        raise RuntimeError(str(outcome[0]))


#: The one live detached probe, or None.  Module-level so a timed-out
#: attempt's probe is RESUMED by the next attempt instead of killed or
#: doubled up (the relay serves one client; a killed mid-compile probe
#: wedges it — PERF.md §10).
_live_probe: dict | None = None

#: Poll cadence while waiting on a detached probe.
_PROBE_POLL_S = 0.5

#: Cross-process handoff record for an abandoned probe: a bench that
#: exits with its probe still dialing leaves {pid, out, err} here, and
#: the NEXT bench invocation ADOPTS that probe instead of spawning a
#: second one against the single-session relay (the round-4 wedge
#: condition is two concurrent clients, not just kills).
_PROBE_STATE_PATH = os.path.join(
    tempfile.gettempdir(),
    # per-user: on a shared host, users must neither fight over one
    # record (EACCES on overwrite) nor adopt each other's pids
    f"log_parser_tpu_probe_state_{os.getuid()}.json",
)


def _read_tail(path: str) -> str:
    try:
        with open(path, "r", errors="replace") as f:
            return f.read()[-2000:]
    except OSError:
        return ""


def _probe_pid_state(pid: int) -> str:
    """Classify ``pid`` for orphan adoption / completion detection:
    ``"probe"`` (alive probe interpreter — ``python -c`` whose source
    carries the PROBE_OK marker), ``"pending"`` (alive but cmdline not
    yet readable: the post-fork pre-exec window, during which a freshly
    spawned probe must NOT be mistaken for dead — r5 code review caught
    exactly that race deleting a live probe's handoff record), or
    ``"dead"`` (no such process, zombie, or pid reused by a different
    program)."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmd = f.read().split(b"\0")
    except OSError:
        return "dead"
    if (
        len(cmd) >= 3
        and b"python" in os.path.basename(cmd[0])
        and cmd[1] == b"-c"
        and b"PROBE_OK" in cmd[2]
    ):
        return "probe"
    try:
        with open(f"/proc/{pid}/stat") as f:
            # field 3, after the parenthesised comm (which may itself
            # contain spaces/parens — split on the LAST "). ")
            state = f.read().rsplit(") ", 1)[1].split()[0]
    except (OSError, IndexError):
        return "dead"
    if state == "Z":
        return "dead"  # exited, unreaped (a test-spawned child)
    # alive with a non-probe cmdline: either the fork→exec window (on
    # Linux the child briefly shows the PARENT'S argv, not an empty
    # one) or a reused pid — the CALLER disambiguates by re-checking
    # over a grace period (the window resolves in milliseconds)
    return "pending"


def _clear_probe_state(lp: dict | None = None) -> None:
    paths = [_PROBE_STATE_PATH]
    if lp is not None:
        # only unlink paths that look like OUR probe output files — the
        # handoff record sits in a world-writable tempdir, and a forged
        # record must not turn the cleaner into arbitrary file deletion
        paths += [
            p
            for p in (lp["out"], lp["err"])
            if isinstance(p, str)
            and os.path.dirname(p) == tempfile.gettempdir()
            and os.path.basename(p).startswith("lpt_probe_")
        ]
    for p in paths:
        try:
            os.unlink(p)
        except OSError:
            pass


def _adopt_orphan() -> dict | None:
    """Adopt a still-running probe abandoned by a PREVIOUS bench process
    (handle with ``proc=None`` — liveness via /proc, outcome via the
    output file).  A DEAD orphan's result is stale (its bench already
    fell back or exited); discard its record and files instead.  A
    ``pending`` pid (alive, non-probe cmdline) gets a short re-check
    grace first: in the fork→exec window a LIVE probe briefly shows its
    parent's argv, and mistaking it for dead would delete its record and
    double up on the relay; a pid still pending after the grace is a
    reused foreign process and the record is stale."""
    try:
        with open(_PROBE_STATE_PATH) as f:
            st = json.load(f)
        pid, out, err = int(st["pid"]), st["out"], st["err"]
    except (OSError, ValueError, KeyError, TypeError):
        return None
    # reconstruct the true spawn time so diagnostics report the probe's
    # REAL age (the dial time is the relay-weather signal), not the
    # adoption time
    age = max(0.0, time.time() - float(st.get("spawned_unix", time.time())))
    lp = {"proc": None, "pid": pid, "out": out, "err": err,
          "started": time.monotonic() - age}
    deadline = time.monotonic() + 2.0
    while True:
        state = _probe_pid_state(pid)
        if state == "probe":
            return lp  # verified: _probe_finished may key on the marker
        if state == "dead" or time.monotonic() >= deadline:
            _clear_probe_state(lp)
            return None
        time.sleep(0.1)  # exec window resolves in milliseconds


def _spawn_probe() -> dict:
    """Adopt an orphaned probe if one is still dialing; otherwise spawn a
    new one DETACHED: its own session (no signal from a dying parent's
    group), stdout/stderr to its own files (polled, not piped — a pipe
    would force the parent to wait on it).  Nothing in this module ever
    sends it a signal.  The handoff record is written at spawn and
    cleared at completion, so an abandoning process leaves it for the
    next one."""
    orphan = _adopt_orphan()
    if orphan is not None:
        return orphan
    fd_out, out_path = tempfile.mkstemp(prefix="lpt_probe_", suffix=".out")
    fd_err, err_path = tempfile.mkstemp(prefix="lpt_probe_", suffix=".err")
    with os.fdopen(fd_out, "w") as fout, os.fdopen(fd_err, "w") as ferr:
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC],
            stdout=fout,
            stderr=ferr,
            stdin=subprocess.DEVNULL,
            start_new_session=True,
        )
    try:
        with open(_PROBE_STATE_PATH, "w") as f:
            json.dump(
                {
                    "pid": proc.pid,
                    "out": out_path,
                    "err": err_path,
                    "spawned_unix": time.time(),
                },
                f,
            )
    except OSError:
        pass  # no handoff possible; within-process resume still works
    return {
        "proc": proc,
        "pid": proc.pid,
        "out": out_path,
        "err": err_path,
        "started": time.monotonic(),
    }


def _probe_finished(lp: dict) -> bool:
    if lp["proc"] is not None:
        return lp["proc"].poll() is not None
    # adopted handles were VERIFIED as probe interpreters at adoption; a
    # later non-probe reading means exited (possibly with the pid since
    # reused by a foreign process) — either way, our probe is done
    return _probe_pid_state(lp["pid"]) != "probe"


def _probe_succeeded(lp: dict, out: str) -> bool:
    # an adopted orphan has no waitable exit code; the PROBE_OK marker
    # (printed only after the device dispatch succeeds) stands in for it
    if lp["proc"] is not None:
        return lp["proc"].returncode == 0 and "PROBE_OK" in out
    return "PROBE_OK" in out


def _one_attempt(timeout_s: float) -> tuple[str | None, dict]:
    """Poll the detached probe for up to ``timeout_s``.  Returns
    (platform or None, diag).

    Spawns a probe only if none is live — resuming first this process's
    own abandoned probe, then any orphan a previous bench process left
    behind (``_adopt_orphan``).  A probe still running when the window
    closes is ABANDONED IN PLACE (outcome "timeout") — never signaled —
    and the next attempt (or the next bench invocation) resumes polling
    it.  This is the code-enforced form of the PERF.md §10 relay rule:
    one probe process at a time, however many benches run, and no probe
    is ever killed mid-compile.
    """
    global _live_probe
    if _live_probe is None:
        _live_probe = _spawn_probe()
    lp = _live_probe
    deadline = time.monotonic() + timeout_s
    while True:
        if _probe_finished(lp):
            _live_probe = None
            elapsed = time.monotonic() - lp["started"]
            out = _read_tail(lp["out"])
            err = _read_tail(lp["err"])
            adopted = lp["proc"] is None
            success = _probe_succeeded(lp, out)
            _clear_probe_state(lp)
            if success:
                platform = out.split("PROBE_OK", 1)[1].split()[0]
                return platform, {
                    "outcome": "ok",
                    "platform": platform,
                    "elapsed_s": round(elapsed, 1),
                    **({"adopted_orphan": True} if adopted else {}),
                }
            return None, {
                "outcome": "error",
                "rc": lp["proc"].returncode if lp["proc"] is not None else None,
                "elapsed_s": round(elapsed, 1),
                "stderr_tail": (err or out or "no output")[-2000:],
                **({"adopted_orphan": True} if adopted else {}),
            }
        if time.monotonic() >= deadline:
            # abandon, never signal: the probe may be mid-remote-compile,
            # and killing it is exactly what wedged the relay in round 4
            return None, {
                "outcome": "timeout",
                "timeout_s": round(timeout_s, 1),
                "probe_pid": lp["pid"],
                "abandoned_running": True,
                "probe_age_s": round(time.monotonic() - lp["started"], 1),
                "stderr_tail": _read_tail(lp["err"]),
            }
        time.sleep(min(_PROBE_POLL_S, max(0.0, deadline - time.monotonic())))


def _measure_relay_health() -> dict:
    """Fixed tiny-dispatch RTT: one jitted ``v + 1`` over 128 int32s,
    compiled once, then 5 timed dispatches.  Device compute is ~0; the
    number IS the host↔device round-trip through the tunnel, the factor
    PERF.md §8b measured swinging end-to-end numbers 161k-596k lines/s
    on identical code."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda v: v + 1)
    x = jnp.arange(128, dtype=jnp.int32)
    f(x).block_until_ready()
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        ts.append(1e3 * (time.perf_counter() - t0))
    ts.sort()
    return {
        "tiny_dispatch_ms_p50": round(ts[len(ts) // 2], 2),
        "tiny_dispatch_ms_min": round(ts[0], 2),
        "tiny_dispatch_ms_max": round(ts[-1], 2),
    }


def _stamp_relay_health(budget_s: float = 120.0) -> None:
    """Measure relay health in a bounded daemon worker.  A timeout records
    an error field instead of failing the bench — a truly wedged backend
    is caught (and exit_null'd) by the bench's own bounded phases; this
    stamp must never be the thing that kills an otherwise-live run."""
    global last_relay_health
    box: list = []

    def work() -> None:
        try:
            box.append(_measure_relay_health())
        except BaseException as exc:  # noqa: BLE001 - recorded, not raised
            box.append({"error": repr(exc)[:300]})

    t = threading.Thread(target=work, name="relay-health", daemon=True)
    t.start()
    t.join(budget_s)
    last_relay_health = box[0] if box else {
        "error": f"tiny-dispatch probe exceeded {budget_s:.0f}s"
    }


def _probe_cache_load(key: str) -> str | None:
    """The cached platform for ``key`` (the explicit request or "auto"),
    or None when absent, mismatched, unparseable, or past the TTL."""
    if PROBE_CACHE_TTL_S <= 0:
        return None
    try:
        with open(_PROBE_CACHE_PATH) as f:
            doc = json.load(f)
        if (
            doc.get("key") == key
            and isinstance(doc.get("platform"), str)
            and 0 <= time.time() - float(doc.get("ts", 0)) < PROBE_CACHE_TTL_S
        ):
            return doc["platform"]
    except (OSError, ValueError, TypeError):
        pass
    return None


def _probe_cache_store(key: str, platform: str) -> None:
    try:
        with open(_PROBE_CACHE_PATH, "w") as f:
            json.dump({"key": key, "platform": platform, "ts": time.time()}, f)
    except OSError:
        pass


def _probe_cache_clear() -> None:
    try:
        os.unlink(_PROBE_CACHE_PATH)
    except OSError:
        pass


def probe_backend(metric: str, unit: str) -> str:
    """Bring up a JAX backend for this bench, preferring the device.

    Staged subprocess attempts under PROBE_TIMEOUT_S total; on success the
    current process is pinned to that platform (with an in-process device
    re-verify, :func:`_pin_and_verify`) and its name is returned.  If
    every device attempt fails, falls back to the JAX host (CPU)
    platform and returns ``"cpu"`` — a CPU-floor run is a labeled
    regression-checkable datapoint, not a substitute for the device run
    (VERDICT.md r2 "Next round" item 1).  Device-attempt diagnostics are
    left in ``last_probe_diagnostics`` for the bench to embed.

    Does not return on the no-honest-number paths (explicit platform
    unavailable, in-process wedge, mislabel refusal): those emit the
    null diagnostics artifact and exit 3 (:func:`exit_null` — see the
    module docstring's contract).
    """
    global last_probe_diagnostics, last_fell_back, last_relay_health
    global last_backend, last_probe_cached
    last_probe_diagnostics = []
    last_fell_back = False
    last_relay_health = None
    last_backend = None
    last_probe_cached = False

    explicit = os.environ.get("LOG_PARSER_TPU_PLATFORM")
    cache_key = explicit or "auto"
    cached = _probe_cache_load(cache_key)
    if cached is not None:
        # a recent invocation's campaign already proved this backend can
        # come up — skip the staged subprocess dials, but the in-process
        # verification below is NOT skippable: it is the mislabel guard,
        # and a dead tunnel behind a fresh cache entry must invalidate
        # the entry, not produce a mislabeled artifact
        try:
            _pin_and_verify(explicit or cached, 120.0)
        except _PinWedged as exc:
            last_probe_diagnostics.append(
                {"outcome": "pin-wedged", "cached": True, "error": str(exc)}
            )
            print(f"# backend pin wedged (cached probe): {exc}", file=sys.stderr)
            exit_null(metric, unit, explicit or cached, str(exc))
        except RuntimeError as exc:
            print(
                f"# cached probe outcome stale ({exc}); re-dialing",
                file=sys.stderr,
            )
            _probe_cache_clear()
        else:
            print(f"# backend ok: {cached} (cached probe)", file=sys.stderr)
            last_probe_cached = True
            last_backend = _device_platform()
            if cached != "cpu":
                _stamp_relay_health()
                print(f"# relay health: {last_relay_health}", file=sys.stderr)
            return cached

    deadline = time.monotonic() + PROBE_TIMEOUT_S
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 1:
            break
        ceiling = _ATTEMPT_CEILINGS_S[min(attempt, len(_ATTEMPT_CEILINGS_S) - 1)]
        attempt += 1
        platform, diag = _one_attempt(min(ceiling, remaining))
        diag["attempt"] = attempt
        last_probe_diagnostics.append(diag)
        if platform is not None:
            try:
                # a successful probe earns a fair in-process dial window
                # even when staged probing consumed most of the budget:
                # a relay dial under bad tunnel weather has been observed
                # past 100s and is slow-but-live, not wedged.
                # Pin what the OPERATOR asked for, not the device-reported
                # name: an explicit plugin platform (e.g. "axon") must get
                # the same config-level pin the probe subprocess used —
                # its devices REPORT "tpu", and pinning that instead would
                # skip the pin and break hosts with no sitecustomize
                # default list (the probe would pass, the bench fail)
                _pin_and_verify(
                    explicit or platform, max(120.0, deadline - time.monotonic())
                )
            except _PinWedged as exc:
                # no number can come out of this process any more (any
                # JAX use would hang behind the stuck init) — emit the
                # diagnostics artifact and stop, instead of the rc=124
                # silence a hung floor attempt would produce
                last_probe_diagnostics.append(
                    {"outcome": "pin-wedged", "attempt": attempt, "error": str(exc)}
                )
                print(f"# backend pin wedged: {exc}", file=sys.stderr)
                exit_null(metric, unit, explicit or platform, str(exc))
            except RuntimeError as exc:
                # the device layer died (or wedged) between the probe
                # subprocess and this process. Retrying is FUTILE: this
                # process's jax has already initialized and cached its
                # backend, so every later probe-then-pin cycle would
                # re-read the same cached devices and fail — stop the
                # campaign now (floor or hard exit below) instead of
                # burning the remaining budget on doomed attempts.
                last_probe_diagnostics.append(
                    {"outcome": "pin-failed", "attempt": attempt, "error": str(exc)}
                )
                print(f"# backend pin failed: {exc}", file=sys.stderr)
                break
            print(f"# backend ok: {platform} (attempt {attempt})", file=sys.stderr)
            last_probe_diagnostics = []
            last_backend = _device_platform()
            _probe_cache_store(cache_key, platform)
            if platform != "cpu":
                _stamp_relay_health()
                print(f"# relay health: {last_relay_health}", file=sys.stderr)
            return platform
        print(f"# backend attempt {attempt} failed: {diag['outcome']}", file=sys.stderr)
        # a hang consumed its whole window; a fast deterministic error
        # waits out a pause first so a restarting runtime can recover —
        # either way the loop runs until the total budget is gone
        if diag["outcome"] != "timeout":
            time.sleep(min(_RETRY_PAUSE_S, max(0.0, deadline - time.monotonic())))

    if explicit:
        # an explicitly-requested platform that won't come up is a hard
        # failure — there is no meaningful floor to substitute
        exit_null(metric, unit, explicit, f"requested platform {explicit!r} unavailable")

    print(
        "# device backend unavailable; falling back to labeled CPU floor",
        file=sys.stderr,
    )
    last_fell_back = True
    pin_platform("cpu")
    # on the pin-failed break path JAX is already initialized, so the
    # config update above is a no-op — trust the DEVICES, not the config,
    # before stamping "cpu" on the artifact (the inverse-mislabel guard)
    actual = _device_platform()
    if actual != "cpu":
        exit_null(
            metric,
            unit,
            actual,
            f"floor fallback landed on already-initialized {actual!r} "
            "backend; refusing to record it under a 'cpu' label",
        )
    last_backend = actual
    return "cpu"


def host_load() -> dict | None:
    """The host's concurrent-load fingerprint at measurement time: a
    number means nothing without knowing what else the box was doing.
    Stamped into every artifact; tools/bench_diff.py marks comparisons
    whose sides ran under very different load advisory-only."""
    try:
        one, five, fifteen = os.getloadavg()
    except OSError:  # pragma: no cover - platform without getloadavg
        return None
    return {
        "loadavg": [round(one, 3), round(five, 3), round(fifteen, 3)],
        "cpus": os.cpu_count(),
    }


def exit_null(metric: str, unit: str, platform: str, error: str) -> None:
    """Emit the null-value diagnostics artifact and hard-exit: used when
    no honest number can be produced (explicit platform unavailable,
    wedged in-process backend, mislabel refusal)."""
    print(
        json.dumps(
            {
                "metric": metric,
                "value": None,
                "unit": unit,
                "vs_baseline": None,
                "platform": platform,
                "error": error,
                "device_probe": last_probe_diagnostics,
                "host_load": host_load(),
            }
        )
    )
    sys.exit(3)


def emit(metric: str, value: float, unit: str, vs_baseline: float | None,
         platform: str, **extra) -> None:
    """Print the single artifact JSON line, embedding the platform label
    and (when the device probe failed) the probe diagnostics."""
    doc = {
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
        "platform": platform,
    }
    doc.update(extra)
    load = host_load()
    if load is not None:
        doc["host_load"] = load
    if last_backend is not None:
        # the label says what the bench CLAIMS; ``backend`` says what
        # the device layer actually was when the probe pinned it — plus
        # whether the probe outcome came from the cache
        doc["backend"] = last_backend
        doc["probe_cached"] = last_probe_cached
    if last_relay_health is not None:
        doc["relay_health"] = last_relay_health
    if last_probe_diagnostics:
        doc["device_probe"] = last_probe_diagnostics
    print(json.dumps(doc))
